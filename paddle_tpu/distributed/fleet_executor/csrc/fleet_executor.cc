// FleetExecutor — actor-model pipeline runtime (carrier + interceptors +
// cross-host MessageBus).
//
// Reference analogue: paddle/fluid/distributed/fleet_executor/
//   carrier.h:49      — Carrier owns interceptors, routes InterceptorMessage
//   interceptor.h:43  — an actor: message queue + handler thread
//   task_node.h       — DAG node: upstream/downstream edges, max_run_times
//   message_bus.h:40  — inter-carrier transport (brpc there); here a framed
//                       TCP bus (ps_net.h helpers) carrying control messages
//                       AND tensor payload blobs between carriers, so
//                       interceptors span processes/hosts.
//
// TPU-native role: the host-side orchestrator for multi-program pipeline
// schedules (across-host DCN pipelines and async data/ckpt work), where the
// in-XLA ppermute pipeline (parallel/pipeline.py) doesn't apply. Compute
// callbacks are C function pointers (ctypes thunks into Python, which
// acquire the GIL per call; heavy work should release it via jax dispatch).
//
// Build: via paddle_tpu.utils.cpp_extension (g++ -shared -fPIC).

#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <utility>
#include <vector>

#include "../../ps/csrc/ps_net.h"

namespace {

enum MsgType : int32_t { DATA = 0, STOP = 1 };

struct InterceptorMessage {
  int64_t src_id;
  int64_t dst_id;
  int32_t type;
  int64_t scope_idx;  // microbatch index
};

// compute callback: fn(task_id, scope_idx) -> 0 ok / nonzero error
typedef int32_t (*ComputeFn)(int64_t, int64_t);

class Carrier;

class Interceptor {
 public:
  Interceptor(Carrier* carrier, int64_t id, ComputeFn fn, int64_t max_runs,
              std::vector<int64_t> ups, std::vector<int64_t> downs)
      : carrier_(carrier),
        id_(id),
        fn_(fn),
        max_runs_(max_runs),
        ups_(std::move(ups)),
        downs_(std::move(downs)) {}

  void Start() { thread_ = std::thread([this] { Loop(); }); }

  void Join() {
    if (thread_.joinable()) thread_.join();
  }

  void Enqueue(const InterceptorMessage& msg) {
    {
      std::lock_guard<std::mutex> lk(mu_);
      queue_.push_back(msg);
    }
    cv_.notify_one();
  }

 private:
  void Loop();

  Carrier* carrier_;
  int64_t id_;
  ComputeFn fn_;
  int64_t max_runs_;
  std::vector<int64_t> ups_;
  std::vector<int64_t> downs_;
  std::mutex mu_;
  std::condition_variable cv_;
  std::deque<InterceptorMessage> queue_;
  std::thread thread_;
  // per-microbatch count of upstream DATA arrivals
  std::unordered_map<int64_t, int64_t> ready_;

  friend class Carrier;
};

class MessageBus;

class Carrier {
 public:
  ~Carrier() { Wait(); }

  void AddTask(int64_t id, ComputeFn fn, int64_t max_runs,
               const int64_t* ups, int64_t n_ups,
               const int64_t* downs, int64_t n_downs) {
    interceptors_[id] = std::unique_ptr<Interceptor>(new Interceptor(
        this, id, fn, max_runs, std::vector<int64_t>(ups, ups + n_ups),
        std::vector<int64_t>(downs, downs + n_downs)));
  }

  // route a message: local interceptor queue, or — when a bus is attached
  // and the task lives on another rank — the cross-host MessageBus
  // (reference: Carrier::Send falling through to MessageBus::Send)
  void Send(const InterceptorMessage& msg);

  // bus → local delivery only (never re-routed, so no forwarding loops)
  void DeliverLocal(const InterceptorMessage& msg) {
    auto it = interceptors_.find(msg.dst_id);
    if (it != interceptors_.end()) it->second->Enqueue(msg);
  }

  bool IsLocal(int64_t task) const {
    return interceptors_.count(task) != 0;
  }

  void SetBus(MessageBus* bus) { bus_ = bus; }

  void Start() {
    error_.store(0);
    for (auto& kv : interceptors_) kv.second->Start();
    // kick sources: one DATA per microbatch from the virtual source (-1)
    for (auto& kv : interceptors_) {
      if (kv.second->ups_.empty()) {
        for (int64_t s = 0; s < kv.second->max_runs_; ++s) {
          Send({-1, kv.first, DATA, s});
        }
      }
    }
  }

  void Wait() {
    for (auto& kv : interceptors_) kv.second->Join();
  }

  // record the error AND wake every interceptor with STOP — a failed stage
  // must not leave downstream actors blocked on queues that will never fill
  void SetError(int32_t e) { SetErrorImpl(e, /*broadcast=*/true); }

  // a STOP that arrived over the bus must not be re-broadcast (loop)
  void SetErrorFromBus(int32_t e) { SetErrorImpl(e, /*broadcast=*/false); }

  int32_t GetError() const { return error_.load(); }

 private:
  void SetErrorImpl(int32_t e, bool broadcast);

  std::unordered_map<int64_t, std::unique_ptr<Interceptor>> interceptors_;
  std::atomic<int32_t> error_{0};
  MessageBus* bus_ = nullptr;
};

// ---------------------------------------------------------------------------
// MessageBus — inter-carrier transport (reference: message_bus.h:40, brpc
// there; framed TCP here). Carries two kinds of traffic between ranks:
//   - interceptor control messages (DATA/STOP), delivered straight into the
//     peer carrier's local queues;
//   - tensor payload blobs keyed by (dst_task, scope), parked in a store
//     until the consuming interceptor fetches them (activations/cotangents
//     of cross-host pipeline stages).
// ---------------------------------------------------------------------------
enum BusMsgType : int32_t { BUS_CTRL = 0, BUS_STOP = 1, BUS_PAYLOAD = 2 };

struct BusWireMsg {
  uint32_t magic;
  int32_t type;      // BusMsgType
  int64_t src_task;
  int64_t dst_task;
  int32_t ctrl_type;  // MsgType for BUS_CTRL
  int64_t scope;
  int64_t nbytes;    // payload bytes following
};

class MessageBus {
 public:
  MessageBus(int rank, std::vector<std::pair<std::string, int>> peers)
      : rank_(rank), peers_(std::move(peers)), out_mu_(peers_.size()) {
    out_fds_.assign(peers_.size(), -1);
  }

  ~MessageBus() { Stop(); }

  bool Start() {
    listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd_ < 0) return false;
    int one = 1;
    ::setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons(static_cast<uint16_t>(peers_[rank_].second));
    if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr),
               sizeof(addr)) != 0 ||
        ::listen(listen_fd_, 16) != 0) {
      ::close(listen_fd_);
      listen_fd_ = -1;
      return false;
    }
    socklen_t alen = sizeof(addr);
    ::getsockname(listen_fd_, reinterpret_cast<sockaddr*>(&addr), &alen);
    port_ = ntohs(addr.sin_port);
    accept_thread_ = std::thread([this] { AcceptLoop(); });
    return true;
  }

  int port() const { return port_; }
  int rank() const { return rank_; }

  // deliveries hold carrier_mu_, so after AttachCarrier(nullptr) returns no
  // read thread can still be inside the old carrier — destroy is then safe.
  // Control messages that arrived while no carrier was attached (a faster
  // peer already started its next step) are parked and flushed on attach.
  void AttachCarrier(Carrier* c) {
    std::lock_guard<std::mutex> lk(carrier_mu_);
    carrier_ = c;
    if (c != nullptr) {
      if (pending_stop_) {
        pending_stop_ = false;
        pending_ctrl_.clear();
        c->SetErrorFromBus(-3);  // a remote failure arrived while detached
        return;
      }
      for (const auto& m : pending_ctrl_) c->DeliverLocal(m);
      pending_ctrl_.clear();
    }
  }

  void SetTaskRank(int64_t task, int r) {
    std::lock_guard<std::mutex> lk(map_mu_);
    task_rank_[task] = r;
  }

  int RankOf(int64_t task) {
    std::lock_guard<std::mutex> lk(map_mu_);
    auto it = task_rank_.find(task);
    return it == task_rank_.end() ? -1 : it->second;
  }

  // control message to the rank owning msg.dst_id
  bool SendCtrl(const InterceptorMessage& msg) {
    int r = RankOf(msg.dst_id);
    if (r < 0 || r == rank_) return false;
    BusWireMsg w{ps::kMagic, BUS_CTRL, msg.src_id, msg.dst_id,
                 msg.type, msg.scope_idx, 0};
    return SendRaw(r, w, nullptr);
  }

  void BroadcastStop() {
    BusWireMsg w{ps::kMagic, BUS_STOP, -1, -1, STOP, 0, 0};
    for (size_t r = 0; r < peers_.size(); ++r) {
      if (static_cast<int>(r) != rank_) SendRaw(static_cast<int>(r), w, nullptr);
    }
  }

  // payload blob for (dst_task, scope): local store or remote rank
  bool Put(int64_t dst_task, int64_t scope, const void* buf, int64_t nbytes) {
    int r = RankOf(dst_task);
    if (r < 0) return false;
    if (r == rank_) {
      StorePayload(dst_task, scope,
                   std::vector<char>(static_cast<const char*>(buf),
                                     static_cast<const char*>(buf) + nbytes));
      return true;
    }
    BusWireMsg w{ps::kMagic, BUS_PAYLOAD, -1, dst_task, DATA, scope, nbytes};
    return SendRaw(r, w, buf);
  }

  // blocking fetch of a payload's size; -1 on timeout/stop
  int64_t GetSize(int64_t task, int64_t scope, int64_t timeout_ms) {
    std::unique_lock<std::mutex> lk(store_mu_);
    auto key = std::make_pair(task, scope);
    bool ok = store_cv_.wait_for(
        lk, std::chrono::milliseconds(timeout_ms),
        [&] { return store_.count(key) != 0 || !running_.load(); });
    if (!ok || !store_.count(key)) return -1;
    return static_cast<int64_t>(store_[key].size());
  }

  // copy out + erase; returns bytes copied, -1 when absent, -2 when the
  // stored blob exceeds `cap` (a larger payload was re-put under the same
  // key between the caller's GetSize and Take — never overflow the buffer)
  int64_t Take(int64_t task, int64_t scope, void* out, int64_t cap) {
    std::lock_guard<std::mutex> lk(store_mu_);
    auto key = std::make_pair(task, scope);
    auto it = store_.find(key);
    if (it == store_.end()) return -1;
    int64_t n = static_cast<int64_t>(it->second.size());
    if (n > cap) return -2;
    std::memcpy(out, it->second.data(), static_cast<size_t>(n));
    store_.erase(it);
    return n;
  }

  void Stop() {
    bool was = running_.exchange(false);
    if (!was) return;
    store_cv_.notify_all();
    if (listen_fd_ >= 0) {
      // poke accept() loose, then close
      int fd = ps::connect_to("127.0.0.1", port_);
      if (fd >= 0) ::close(fd);
    }
    if (accept_thread_.joinable()) accept_thread_.join();
    {
      std::lock_guard<std::mutex> lk(conns_mu_);
      for (int fd : conn_fds_) ::shutdown(fd, SHUT_RDWR);
    }
    for (auto& t : conn_threads_)
      if (t.joinable()) t.join();
    for (size_t r = 0; r < out_fds_.size(); ++r) {
      std::lock_guard<std::mutex> lk(out_mu_[r]);
      if (out_fds_[r] >= 0) ::close(out_fds_[r]);
      out_fds_[r] = -1;
    }
  }

 private:
  void AcceptLoop() {
    while (running_.load()) {
      int fd = ::accept(listen_fd_, nullptr, nullptr);
      if (fd < 0) {
        if (!running_.load()) break;
        continue;
      }
      if (!running_.load()) {
        ::close(fd);
        break;
      }
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      std::lock_guard<std::mutex> lk(conns_mu_);
      conn_fds_.push_back(fd);
      conn_threads_.emplace_back([this, fd] { ReadLoop(fd); });
    }
    ::close(listen_fd_);
    listen_fd_ = -1;
  }

  void ReadLoop(int fd) {
    std::vector<char> buf;
    while (running_.load()) {
      BusWireMsg w{};
      if (!ps::read_full(fd, &w, sizeof(w)) || w.magic != ps::kMagic) break;
      buf.resize(static_cast<size_t>(w.nbytes));
      if (w.nbytes > 0 && !ps::read_full(fd, buf.data(), buf.size())) break;
      if (w.type == BUS_PAYLOAD) {
        StorePayload(w.dst_task, w.scope, std::move(buf));
        buf = std::vector<char>();
      } else {
        std::lock_guard<std::mutex> lk(carrier_mu_);
        Carrier* car = carrier_;
        if (w.type == BUS_CTRL) {
          InterceptorMessage m{w.src_task, w.dst_task, w.ctrl_type, w.scope};
          if (car != nullptr)
            car->DeliverLocal(m);
          else
            pending_ctrl_.push_back(m);  // peer outran our next attach
        } else if (w.type == BUS_STOP) {
          if (car != nullptr)
            car->SetErrorFromBus(-3);
          else
            pending_stop_ = true;  // surface the remote failure on attach
        }
      }
    }
    // deregister BEFORE closing: Stop() walks conn_fds_ and shutdown()s
    // each entry — a stale number could be recycled by the kernel for an
    // unrelated socket (e.g. a new outbound connection) in this process
    {
      std::lock_guard<std::mutex> lk(conns_mu_);
      conn_fds_.erase(std::remove(conn_fds_.begin(), conn_fds_.end(), fd),
                      conn_fds_.end());
    }
    ::close(fd);
  }

  void StorePayload(int64_t task, int64_t scope, std::vector<char> data) {
    {
      std::lock_guard<std::mutex> lk(store_mu_);
      store_[std::make_pair(task, scope)] = std::move(data);
    }
    store_cv_.notify_all();
  }

  bool SendRaw(int r, const BusWireMsg& w, const void* payload) {
    std::lock_guard<std::mutex> lk(out_mu_[r]);
    for (int attempt = 0; attempt < 2; ++attempt) {
      if (out_fds_[r] < 0) {
        out_fds_[r] = ps::connect_to(peers_[r].first, peers_[r].second);
        if (out_fds_[r] < 0) {
          // peer may still be binding — or still importing its python
          // runtime (~5s with jax on a loaded host); retry up to 15s
          for (int i = 0; i < 150 && out_fds_[r] < 0 && running_.load(); ++i) {
            std::this_thread::sleep_for(std::chrono::milliseconds(100));
            out_fds_[r] = ps::connect_to(peers_[r].first, peers_[r].second);
          }
          if (out_fds_[r] < 0) return false;
        }
      }
      bool ok = ps::write_full(out_fds_[r], &w, sizeof(w)) &&
                (w.nbytes == 0 ||
                 ps::write_full(out_fds_[r], payload,
                                static_cast<size_t>(w.nbytes)));
      if (ok) return true;
      ::close(out_fds_[r]);
      out_fds_[r] = -1;  // stale connection — reconnect once
    }
    return false;
  }

  int rank_;
  std::vector<std::pair<std::string, int>> peers_;
  int listen_fd_ = -1;
  int port_ = 0;
  std::atomic<bool> running_{true};
  std::mutex carrier_mu_;
  Carrier* carrier_ = nullptr;
  std::vector<InterceptorMessage> pending_ctrl_;
  bool pending_stop_ = false;

  std::mutex map_mu_;
  std::unordered_map<int64_t, int> task_rank_;

  std::thread accept_thread_;
  std::mutex conns_mu_;
  std::vector<int> conn_fds_;
  std::vector<std::thread> conn_threads_;

  std::vector<std::mutex> out_mu_;
  std::vector<int> out_fds_;

  std::mutex store_mu_;
  std::condition_variable store_cv_;
  std::map<std::pair<int64_t, int64_t>, std::vector<char>> store_;
};

void Carrier::Send(const InterceptorMessage& msg) {
  auto it = interceptors_.find(msg.dst_id);
  if (it != interceptors_.end()) {
    it->second->Enqueue(msg);
  } else if (bus_ != nullptr) {
    bus_->SendCtrl(msg);
  }
}

void Carrier::SetErrorImpl(int32_t e, bool broadcast) {
  int32_t expected = 0;
  bool first = error_.compare_exchange_strong(expected, e);
  for (auto& kv : interceptors_) DeliverLocal({-1, kv.first, STOP, 0});
  if (first && broadcast && bus_ != nullptr) bus_->BroadcastStop();
}

void Interceptor::Loop() {
  int64_t done = 0;
  int64_t n_need = ups_.empty() ? 1 : static_cast<int64_t>(ups_.size());
  while (done < max_runs_) {
    InterceptorMessage msg;
    {
      std::unique_lock<std::mutex> lk(mu_);
      cv_.wait(lk, [this] { return !queue_.empty(); });
      msg = queue_.front();
      queue_.pop_front();
    }
    if (msg.type == STOP) break;
    if (carrier_->GetError() != 0) break;
    int64_t scope = msg.scope_idx;
    if (++ready_[scope] < n_need) continue;  // wait for all upstreams
    ready_.erase(scope);
    if (fn_ != nullptr) {
      int32_t rc = fn_(id_, scope);  // ctypes thunk: grabs the GIL
      if (rc != 0) {
        carrier_->SetError(rc);
        break;
      }
    }
    for (int64_t d : downs_) carrier_->Send({id_, d, DATA, scope});
    ++done;
  }
}

}  // namespace

extern "C" {

void* carrier_create() { return new Carrier(); }

void carrier_add_task(void* h, int64_t id, ComputeFn fn, int64_t max_runs,
                      const int64_t* ups, int64_t n_ups,
                      const int64_t* downs, int64_t n_downs) {
  static_cast<Carrier*>(h)->AddTask(id, fn, max_runs, ups, n_ups, downs,
                                    n_downs);
}

void carrier_start(void* h) { static_cast<Carrier*>(h)->Start(); }

// abort: wake every interceptor with STOP so Wait() returns promptly
void carrier_stop(void* h) { static_cast<Carrier*>(h)->SetError(-2); }

int32_t carrier_wait(void* h) {
  Carrier* c = static_cast<Carrier*>(h);
  c->Wait();
  return c->GetError();
}

void carrier_destroy(void* h) { delete static_cast<Carrier*>(h); }

// ---- MessageBus C ABI (reference: message_bus.h Init/Send surface) --------

// endpoints_csv: "host:port,host:port,..." indexed by rank; port 0 = auto
void* bus_create(int rank, const char* endpoints_csv) {
  auto peers = ps::parse_endpoints(endpoints_csv);
  if (rank < 0 || rank >= static_cast<int>(peers.size())) return nullptr;
  auto* b = new MessageBus(rank, std::move(peers));
  if (!b->Start()) {
    delete b;
    return nullptr;
  }
  return b;
}

int bus_port(void* h) { return static_cast<MessageBus*>(h)->port(); }

void bus_attach(void* bus, void* carrier) {
  auto* b = static_cast<MessageBus*>(bus);
  auto* c = static_cast<Carrier*>(carrier);
  b->AttachCarrier(c);
  c->SetBus(b);
}

// detach before carrier_destroy: the bus read threads must never deliver
// into a dead carrier
void bus_detach(void* bus) {
  static_cast<MessageBus*>(bus)->AttachCarrier(nullptr);
}

void bus_set_task_rank(void* h, int64_t task, int rank) {
  static_cast<MessageBus*>(h)->SetTaskRank(task, rank);
}

int bus_put(void* h, int64_t dst_task, int64_t scope, const void* buf,
            int64_t nbytes) {
  return static_cast<MessageBus*>(h)->Put(dst_task, scope, buf, nbytes) ? 0
                                                                        : -1;
}

int64_t bus_get_size(void* h, int64_t task, int64_t scope,
                     int64_t timeout_ms) {
  return static_cast<MessageBus*>(h)->GetSize(task, scope, timeout_ms);
}

int64_t bus_take(void* h, int64_t task, int64_t scope, void* out,
                 int64_t cap) {
  return static_cast<MessageBus*>(h)->Take(task, scope, out, cap);
}

void bus_stop(void* h) { static_cast<MessageBus*>(h)->Stop(); }

void bus_destroy(void* h) { delete static_cast<MessageBus*>(h); }

}  // extern "C"
