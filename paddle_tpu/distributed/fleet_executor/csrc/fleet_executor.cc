// FleetExecutor — actor-model pipeline runtime (carrier + interceptors).
//
// Reference analogue: paddle/fluid/distributed/fleet_executor/
//   carrier.h:49      — Carrier owns interceptors, routes InterceptorMessage
//   interceptor.h:43  — an actor: message queue + handler thread
//   task_node.h       — DAG node: upstream/downstream edges, max_run_times
//   message_bus.h:40  — inter-carrier transport (brpc); here single-process,
//                       so the bus is the in-memory queue fabric.
//
// TPU-native role: the host-side orchestrator for multi-program pipeline
// schedules (across-host DCN pipelines and async data/ckpt work), where the
// in-XLA ppermute pipeline (parallel/pipeline.py) doesn't apply. Compute
// callbacks are C function pointers (ctypes thunks into Python, which
// acquire the GIL per call; heavy work should release it via jax dispatch).
//
// Build: via paddle_tpu.utils.cpp_extension (g++ -shared -fPIC).

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

enum MsgType : int32_t { DATA = 0, STOP = 1 };

struct InterceptorMessage {
  int64_t src_id;
  int64_t dst_id;
  int32_t type;
  int64_t scope_idx;  // microbatch index
};

// compute callback: fn(task_id, scope_idx) -> 0 ok / nonzero error
typedef int32_t (*ComputeFn)(int64_t, int64_t);

class Carrier;

class Interceptor {
 public:
  Interceptor(Carrier* carrier, int64_t id, ComputeFn fn, int64_t max_runs,
              std::vector<int64_t> ups, std::vector<int64_t> downs)
      : carrier_(carrier),
        id_(id),
        fn_(fn),
        max_runs_(max_runs),
        ups_(std::move(ups)),
        downs_(std::move(downs)) {}

  void Start() { thread_ = std::thread([this] { Loop(); }); }

  void Join() {
    if (thread_.joinable()) thread_.join();
  }

  void Enqueue(const InterceptorMessage& msg) {
    {
      std::lock_guard<std::mutex> lk(mu_);
      queue_.push_back(msg);
    }
    cv_.notify_one();
  }

 private:
  void Loop();

  Carrier* carrier_;
  int64_t id_;
  ComputeFn fn_;
  int64_t max_runs_;
  std::vector<int64_t> ups_;
  std::vector<int64_t> downs_;
  std::mutex mu_;
  std::condition_variable cv_;
  std::deque<InterceptorMessage> queue_;
  std::thread thread_;
  // per-microbatch count of upstream DATA arrivals
  std::unordered_map<int64_t, int64_t> ready_;

  friend class Carrier;
};

class Carrier {
 public:
  ~Carrier() { Wait(); }

  void AddTask(int64_t id, ComputeFn fn, int64_t max_runs,
               const int64_t* ups, int64_t n_ups,
               const int64_t* downs, int64_t n_downs) {
    interceptors_[id] = std::unique_ptr<Interceptor>(new Interceptor(
        this, id, fn, max_runs, std::vector<int64_t>(ups, ups + n_ups),
        std::vector<int64_t>(downs, downs + n_downs)));
  }

  // route a message to its destination queue (the in-process MessageBus)
  void Send(const InterceptorMessage& msg) {
    auto it = interceptors_.find(msg.dst_id);
    if (it != interceptors_.end()) it->second->Enqueue(msg);
  }

  void Start() {
    error_.store(0);
    for (auto& kv : interceptors_) kv.second->Start();
    // kick sources: one DATA per microbatch from the virtual source (-1)
    for (auto& kv : interceptors_) {
      if (kv.second->ups_.empty()) {
        for (int64_t s = 0; s < kv.second->max_runs_; ++s) {
          Send({-1, kv.first, DATA, s});
        }
      }
    }
  }

  void Wait() {
    for (auto& kv : interceptors_) kv.second->Join();
  }

  // record the error AND wake every interceptor with STOP — a failed stage
  // must not leave downstream actors blocked on queues that will never fill
  void SetError(int32_t e) {
    error_.store(e);
    for (auto& kv : interceptors_) Send({-1, kv.first, STOP, 0});
  }
  int32_t GetError() const { return error_.load(); }

 private:
  std::unordered_map<int64_t, std::unique_ptr<Interceptor>> interceptors_;
  std::atomic<int32_t> error_{0};
};

void Interceptor::Loop() {
  int64_t done = 0;
  int64_t n_need = ups_.empty() ? 1 : static_cast<int64_t>(ups_.size());
  while (done < max_runs_) {
    InterceptorMessage msg;
    {
      std::unique_lock<std::mutex> lk(mu_);
      cv_.wait(lk, [this] { return !queue_.empty(); });
      msg = queue_.front();
      queue_.pop_front();
    }
    if (msg.type == STOP) break;
    if (carrier_->GetError() != 0) break;
    int64_t scope = msg.scope_idx;
    if (++ready_[scope] < n_need) continue;  // wait for all upstreams
    ready_.erase(scope);
    if (fn_ != nullptr) {
      int32_t rc = fn_(id_, scope);  // ctypes thunk: grabs the GIL
      if (rc != 0) {
        carrier_->SetError(rc);
        break;
      }
    }
    for (int64_t d : downs_) carrier_->Send({id_, d, DATA, scope});
    ++done;
  }
}

}  // namespace

extern "C" {

void* carrier_create() { return new Carrier(); }

void carrier_add_task(void* h, int64_t id, ComputeFn fn, int64_t max_runs,
                      const int64_t* ups, int64_t n_ups,
                      const int64_t* downs, int64_t n_downs) {
  static_cast<Carrier*>(h)->AddTask(id, fn, max_runs, ups, n_ups, downs,
                                    n_downs);
}

void carrier_start(void* h) { static_cast<Carrier*>(h)->Start(); }

// abort: wake every interceptor with STOP so Wait() returns promptly
void carrier_stop(void* h) { static_cast<Carrier*>(h)->SetError(-2); }

int32_t carrier_wait(void* h) {
  Carrier* c = static_cast<Carrier*>(h);
  c->Wait();
  return c->GetError();
}

void carrier_destroy(void* h) { delete static_cast<Carrier*>(h); }

}  // extern "C"
