"""Host-driven multi-program pipeline training over the actor runtime.

Reference analogue: the PipelineTrainer/SectionWorker stack
(framework/trainer.h:303, device_worker.h:615) and FleetExecutor's
dist-model pipelines — each pipeline section is its own program run by a
worker, activations/gradients hop between sections over the wire.

TPU-native role: the COMPILED pipeline (parallel/pipeline.py, ppermute
inside one XLA program) is the right mode within an ICI slice. This module
is the OTHER mode: each stage is an independent jitted program placed on
its own device (standing in for another host across DCN), and the C++
carrier/interceptor actors (fleet_executor.cc) drive the microbatch
schedule — forward activations flow stage k → k+1, backward cotangents
flow k+1 → k through the saved vjp closures, and each stage applies its
own SGD update from microbatch-accumulated grads. Device-to-device
`jax.device_put` is the transfer; across real hosts the same schedule
rides the coordination-service transports.
"""
from __future__ import annotations

from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp

from . import FleetExecutor, TaskNode

__all__ = ["HostPipelineTrainer"]


class HostPipelineTrainer:
    """Train stage_fns(params, x)->y chained stages with actor scheduling.

    stage_fns: per-stage pure functions; params: per-stage pytrees (placed
    on devices[k]); loss_fn(y, label)->scalar runs on the last stage.
    """

    def __init__(
        self,
        stage_fns: Sequence[Callable],
        params: Sequence,
        loss_fn: Callable,
        learning_rate: float = 0.01,
        devices: Optional[Sequence] = None,
    ):
        n = len(stage_fns)
        if len(params) != n:
            raise ValueError("one params pytree per stage")
        devs = list(devices) if devices is not None else jax.devices()[:n]
        if len(devs) < n:
            raise ValueError(f"need {n} devices, have {len(devs)}")
        self.n_stages = n
        self.devices = devs[:n]
        self.loss_fn = loss_fn
        self.lr = learning_rate
        self.params = [
            jax.device_put(p, d) for p, d in zip(params, self.devices)
        ]

        # per-stage compiled programs, pinned to the stage device:
        #   fwd: (params, x) -> (y, vjp_closure)   [vjp closures are pytrees]
        #   bwd: (vjp_closure, ct_y) -> (d_params, d_x)
        self._fwd = []
        self._bwd = []
        for k, fn in enumerate(stage_fns):
            if k == n - 1:
                def wrapped(p, x, lbl, _fn=fn):
                    y = _fn(p, x)
                    return self.loss_fn(y, lbl)

                self._fwd.append(
                    jax.jit(lambda p, x, lbl, _w=wrapped: jax.vjp(_w, p, x, lbl))
                )
            else:
                self._fwd.append(
                    jax.jit(lambda p, x, _fn=fn: jax.vjp(_fn, p, x))
                )
            self._bwd.append(jax.jit(lambda vjp, ct: vjp(ct)))
        # placement follows the committed operands: params/activations are
        # device_put onto each stage's device, so every program runs there
        self._sgd = jax.jit(
            lambda p, g, lr: jax.tree_util.tree_map(
                lambda pv, gv: pv - lr * gv, p, g
            )
        )

    def train_batch(self, micro_xs: Sequence, micro_labels: Sequence) -> float:
        """One step over num_micro microbatches; returns the mean loss.

        Schedule: forward task chain (stage k gated on k-1 per microbatch,
        pipelined by the actors) then backward chain in reverse — GPipe
        order, the reference's origin_scheduler."""
        num_micro = len(micro_xs)
        if num_micro == 0:
            raise ValueError("train_batch needs at least one microbatch")
        if len(micro_labels) != num_micro:
            raise ValueError(
                f"{num_micro} microbatches but {len(micro_labels)} label sets"
            )
        n = self.n_stages
        acts = [[None] * num_micro for _ in range(n + 1)]   # stage inputs
        vjps = [[None] * num_micro for _ in range(n)]
        cts = [[None] * num_micro for _ in range(n + 1)]    # cotangents
        losses = [None] * num_micro
        grads = [[None] * num_micro for _ in range(n)]
        for t, x in enumerate(micro_xs):
            acts[0][t] = jax.device_put(x, self.devices[0])

        def fwd_task(k):
            def run(t):
                x = jax.device_put(acts[k][t], self.devices[k])
                if k == n - 1:
                    lbl = jax.device_put(micro_labels[t], self.devices[k])
                    loss, vjp = self._fwd[k](self.params[k], x, lbl)
                    losses[t] = loss
                    cts[k + 1][t] = jnp.ones_like(loss)
                else:
                    y, vjp = self._fwd[k](self.params[k], x)
                    acts[k + 1][t] = y
                vjps[k][t] = vjp

            return run

        def bwd_task(k):
            def run(t):
                ct = jax.device_put(cts[k + 1][t], self.devices[k])
                out = self._bwd[k](vjps[k][t], ct)
                grads[k][t] = out[0]
                cts[k][t] = out[1]
                vjps[k][t] = None  # free residuals early

            return run

        # one linear chain: fwd stages 0..n-1 then bwd stages n-1..0 —
        # exactly FleetExecutor.pipeline's wiring
        chain = [fwd_task(k) for k in range(n)] + [bwd_task(k) for k in reversed(range(n))]
        FleetExecutor.pipeline(chain, num_micro).run()

        # microbatch-accumulated grads -> per-stage SGD
        for k in range(n):
            total = grads[k][0]
            for t in range(1, num_micro):
                total = jax.tree_util.tree_map(jnp.add, total, grads[k][t])
            total = jax.tree_util.tree_map(lambda g: g / num_micro, total)
            self.params[k] = self._sgd(self.params[k], total, self.lr)
        return float(sum(jax.device_get(l) for l in losses) / num_micro)
