"""Host-driven multi-program pipeline training over the actor runtime.

Reference analogue: the PipelineTrainer/SectionWorker stack
(framework/trainer.h:303, device_worker.h:615) and FleetExecutor's
dist-model pipelines — each pipeline section is its own program run by a
worker, activations/gradients hop between sections over the wire.

TPU-native role: the COMPILED pipeline (parallel/pipeline.py, ppermute
inside one XLA program) is the right mode within an ICI slice. This module
is the OTHER mode: each stage is an independent jitted program placed on
its own device (standing in for another host across DCN), and the C++
carrier/interceptor actors (fleet_executor.cc) drive the microbatch
schedule — forward activations flow stage k → k+1, backward cotangents
flow k+1 → k through the saved vjp closures, and each stage applies its
own SGD update from microbatch-accumulated grads. Device-to-device
`jax.device_put` is the transfer; across real hosts the same schedule
rides the coordination-service transports.
"""
from __future__ import annotations

from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp

from . import FleetExecutor, TaskNode

__all__ = ["HostPipelineTrainer"]


class HostPipelineTrainer:
    """Train stage_fns(params, x)->y chained stages with actor scheduling.

    stage_fns: per-stage pure functions; params: per-stage pytrees (placed
    on devices[k]); loss_fn(y, label)->scalar runs on the last stage.
    """

    def __init__(
        self,
        stage_fns: Sequence[Callable],
        params: Sequence,
        loss_fn: Callable,
        learning_rate: float = 0.01,
        devices: Optional[Sequence] = None,
    ):
        n = len(stage_fns)
        if len(params) != n:
            raise ValueError("one params pytree per stage")
        devs = list(devices) if devices is not None else jax.devices()[:n]
        if len(devs) < n:
            raise ValueError(f"need {n} devices, have {len(devs)}")
        self.n_stages = n
        self.devices = devs[:n]
        self.loss_fn = loss_fn
        self.lr = learning_rate
        self.params = [
            jax.device_put(p, d) for p, d in zip(params, self.devices)
        ]

        # per-stage compiled programs, pinned to the stage device:
        #   fwd: (params, x) -> (y, vjp_closure)   [vjp closures are pytrees]
        #   bwd: (vjp_closure, ct_y) -> (d_params, d_x)
        self._fwd = []
        self._bwd = []
        for k, fn in enumerate(stage_fns):
            if k == n - 1:
                def wrapped(p, x, lbl, _fn=fn):
                    y = _fn(p, x)
                    return self.loss_fn(y, lbl)

                self._fwd.append(
                    jax.jit(lambda p, x, lbl, _w=wrapped: jax.vjp(_w, p, x, lbl))
                )
            else:
                self._fwd.append(
                    jax.jit(lambda p, x, _fn=fn: jax.vjp(_fn, p, x))
                )
            self._bwd.append(jax.jit(lambda vjp, ct: vjp(ct)))
        # placement follows the committed operands: params/activations are
        # device_put onto each stage's device, so every program runs there
        self._sgd = jax.jit(
            lambda p, g, lr: jax.tree_util.tree_map(
                lambda pv, gv: pv - lr * gv, p, g
            )
        )

    def train_batch(self, micro_xs: Sequence, micro_labels: Sequence,
                    schedule: str = "1f1b") -> float:
        """One step over num_micro microbatches; returns the mean loss.

        The actors gate stage k's microbatch t on stage k-1's t, so
        execution is dataflow-pipelined either way; `schedule` controls the
        RESIDENCY policy (reference: pipeline_parallel.py:80
        forward_backward_pipeline vs the origin/GPipe scheduler):
          - "1f1b": stage 0 admits at most n_stages microbatches beyond the
            completed backwards — steady-state one-forward-one-backward, so
            at most n_stages residual sets are ever live per stage.
          - "gpipe": all forwards admitted immediately; every microbatch's
            residuals stay live until its backward (more memory, same math).
        """
        if schedule not in ("1f1b", "gpipe"):
            raise ValueError(f"schedule must be 1f1b|gpipe, got {schedule!r}")
        num_micro = len(micro_xs)
        if num_micro == 0:
            raise ValueError("train_batch needs at least one microbatch")
        if len(micro_labels) != num_micro:
            raise ValueError(
                f"{num_micro} microbatches but {len(micro_labels)} label sets"
            )
        n = self.n_stages
        import threading as _threading

        # 1F1B window: n_stages tokens; fwd stage 0 takes one per admitted
        # microbatch, bwd stage 0 returns it when that microbatch's grads
        # are done (the classic warmup / steady 1F1B / cooldown shape)
        window = _threading.Semaphore(n) if schedule == "1f1b" else None
        self._inflight = 0
        self._peak_inflight = 0
        self._failed = False
        lock = _threading.Lock()

        def _admit():
            # bounded wait so a failure elsewhere in the pipeline surfaces
            # as an exception instead of parking this actor thread forever
            # on a token the dead backward will never return
            while window is not None and not window.acquire(timeout=0.2):
                if self._failed:
                    raise RuntimeError(
                        "pipeline failed on another stage; aborting admission"
                    )

        def _fail():
            self._failed = True
        acts = [[None] * num_micro for _ in range(n + 1)]   # stage inputs
        vjps = [[None] * num_micro for _ in range(n)]
        cts = [[None] * num_micro for _ in range(n + 1)]    # cotangents
        losses = [None] * num_micro
        grads = [[None] * num_micro for _ in range(n)]
        for t, x in enumerate(micro_xs):
            acts[0][t] = jax.device_put(x, self.devices[0])

        def fwd_task(k):
            def run(t):
                try:
                    if k == 0:
                        _admit()
                        with lock:
                            self._inflight += 1
                            self._peak_inflight = max(
                                self._peak_inflight, self._inflight
                            )
                    x = jax.device_put(acts[k][t], self.devices[k])
                    if k == n - 1:
                        lbl = jax.device_put(micro_labels[t], self.devices[k])
                        loss, vjp = self._fwd[k](self.params[k], x, lbl)
                        losses[t] = loss
                        cts[k + 1][t] = jnp.ones_like(loss)
                    else:
                        y, vjp = self._fwd[k](self.params[k], x)
                        acts[k + 1][t] = y
                    vjps[k][t] = vjp
                except BaseException:
                    _fail()
                    raise

            return run

        def bwd_task(k):
            def run(t):
                try:
                    ct = jax.device_put(cts[k + 1][t], self.devices[k])
                    out = self._bwd[k](vjps[k][t], ct)
                    grads[k][t] = out[0]
                    cts[k][t] = out[1]
                    vjps[k][t] = None  # free residuals early
                except BaseException:
                    _fail()
                    raise
                finally:
                    if k == 0:
                        with lock:
                            self._inflight -= 1
                        if window is not None:
                            window.release()

            return run

        # one linear chain: fwd stages 0..n-1 then bwd stages n-1..0 —
        # exactly FleetExecutor.pipeline's wiring
        chain = [fwd_task(k) for k in range(n)] + [bwd_task(k) for k in reversed(range(n))]
        FleetExecutor.pipeline(chain, num_micro).run()

        # microbatch-accumulated grads -> per-stage SGD
        for k in range(n):
            total = grads[k][0]
            for t in range(1, num_micro):
                total = jax.tree_util.tree_map(jnp.add, total, grads[k][t])
            total = jax.tree_util.tree_map(lambda g: g / num_micro, total)
            self.params[k] = self._sgd(self.params[k], total, self.lr)
        return float(sum(jax.device_get(l) for l in losses) / num_micro)
