"""Host-driven multi-program pipeline training over the actor runtime.

Reference analogue: the PipelineTrainer/SectionWorker stack
(framework/trainer.h:303, device_worker.h:615) and FleetExecutor's
dist-model pipelines — each pipeline section is its own program run by a
worker, activations/gradients hop between sections over the wire.

TPU-native role: the COMPILED pipeline (parallel/pipeline.py, ppermute
inside one XLA program) is the right mode within an ICI slice. This module
is the OTHER mode: each stage is an independent jitted program placed on
its own device (standing in for another host across DCN), and the C++
carrier/interceptor actors (fleet_executor.cc) drive the microbatch
schedule — forward activations flow stage k → k+1, backward cotangents
flow k+1 → k through the saved vjp closures, and each stage applies its
own SGD update from microbatch-accumulated grads. Device-to-device
`jax.device_put` is the transfer; across real hosts the same schedule
rides the coordination-service transports.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp

from . import FleetExecutor, MessageBus, TaskNode

__all__ = ["HostPipelineTrainer", "DistHostPipelineTrainer"]


class HostPipelineTrainer:
    """Train stage_fns(params, x)->y chained stages with actor scheduling.

    stage_fns: per-stage pure functions; params: per-stage pytrees (placed
    on devices[k]); loss_fn(y, label)->scalar runs on the last stage.
    """

    def __init__(
        self,
        stage_fns: Sequence[Callable],
        params: Sequence,
        loss_fn: Callable,
        learning_rate: float = 0.01,
        devices: Optional[Sequence] = None,
    ):
        n = len(stage_fns)
        if len(params) != n:
            raise ValueError("one params pytree per stage")
        devs = list(devices) if devices is not None else jax.devices()[:n]
        if len(devs) < n:
            raise ValueError(f"need {n} devices, have {len(devs)}")
        self.n_stages = n
        self.devices = devs[:n]
        self.loss_fn = loss_fn
        self.lr = learning_rate
        self.params = [
            jax.device_put(p, d) for p, d in zip(params, self.devices)
        ]

        # per-stage compiled programs, pinned to the stage device:
        #   fwd: (params, x) -> (y, vjp_closure)   [vjp closures are pytrees]
        #   bwd: (vjp_closure, ct_y) -> (d_params, d_x)
        self._fwd = []
        self._bwd = []
        for k, fn in enumerate(stage_fns):
            if k == n - 1:
                def wrapped(p, x, lbl, _fn=fn):
                    y = _fn(p, x)
                    return self.loss_fn(y, lbl)

                self._fwd.append(
                    jax.jit(lambda p, x, lbl, _w=wrapped: jax.vjp(_w, p, x, lbl))
                )
            else:
                self._fwd.append(
                    jax.jit(lambda p, x, _fn=fn: jax.vjp(_fn, p, x))
                )
            self._bwd.append(jax.jit(lambda vjp, ct: vjp(ct)))
        # placement follows the committed operands: params/activations are
        # device_put onto each stage's device, so every program runs there
        self._sgd = jax.jit(
            lambda p, g, lr: jax.tree_util.tree_map(
                lambda pv, gv: pv - lr * gv, p, g
            )
        )

    def train_batch(self, micro_xs: Sequence, micro_labels: Sequence,
                    schedule: str = "1f1b") -> float:
        """One step over num_micro microbatches; returns the mean loss.

        The actors gate stage k's microbatch t on stage k-1's t, so
        execution is dataflow-pipelined either way; `schedule` controls the
        RESIDENCY policy (reference: pipeline_parallel.py:80
        forward_backward_pipeline vs the origin/GPipe scheduler):
          - "1f1b": stage 0 admits at most n_stages microbatches beyond the
            completed backwards — steady-state one-forward-one-backward, so
            at most n_stages residual sets are ever live per stage.
          - "gpipe": all forwards admitted immediately; every microbatch's
            residuals stay live until its backward (more memory, same math).
        """
        if schedule not in ("1f1b", "gpipe"):
            raise ValueError(f"schedule must be 1f1b|gpipe, got {schedule!r}")
        num_micro = len(micro_xs)
        if num_micro == 0:
            raise ValueError("train_batch needs at least one microbatch")
        if len(micro_labels) != num_micro:
            raise ValueError(
                f"{num_micro} microbatches but {len(micro_labels)} label sets"
            )
        n = self.n_stages
        import threading as _threading

        # 1F1B window: n_stages tokens; fwd stage 0 takes one per admitted
        # microbatch, bwd stage 0 returns it when that microbatch's grads
        # are done (the classic warmup / steady 1F1B / cooldown shape)
        window = _threading.Semaphore(n) if schedule == "1f1b" else None
        self._inflight = 0
        self._peak_inflight = 0
        self._failed = False
        lock = _threading.Lock()

        def _admit():
            # bounded wait so a failure elsewhere in the pipeline surfaces
            # as an exception instead of parking this actor thread forever
            # on a token the dead backward will never return
            while window is not None and not window.acquire(timeout=0.2):
                if self._failed:
                    raise RuntimeError(
                        "pipeline failed on another stage; aborting admission"
                    )

        def _fail():
            self._failed = True
        acts = [[None] * num_micro for _ in range(n + 1)]   # stage inputs
        vjps = [[None] * num_micro for _ in range(n)]
        cts = [[None] * num_micro for _ in range(n + 1)]    # cotangents
        losses = [None] * num_micro
        grads = [[None] * num_micro for _ in range(n)]
        for t, x in enumerate(micro_xs):
            acts[0][t] = jax.device_put(x, self.devices[0])

        def fwd_task(k):
            def run(t):
                try:
                    if k == 0:
                        _admit()
                        with lock:
                            self._inflight += 1
                            self._peak_inflight = max(
                                self._peak_inflight, self._inflight
                            )
                    x = jax.device_put(acts[k][t], self.devices[k])
                    if k == n - 1:
                        lbl = jax.device_put(micro_labels[t], self.devices[k])
                        loss, vjp = self._fwd[k](self.params[k], x, lbl)
                        losses[t] = loss
                        cts[k + 1][t] = jnp.ones_like(loss)
                    else:
                        y, vjp = self._fwd[k](self.params[k], x)
                        acts[k + 1][t] = y
                    vjps[k][t] = vjp
                except BaseException:
                    _fail()
                    raise

            return run

        def bwd_task(k):
            def run(t):
                try:
                    ct = jax.device_put(cts[k + 1][t], self.devices[k])
                    out = self._bwd[k](vjps[k][t], ct)
                    grads[k][t] = out[0]
                    cts[k][t] = out[1]
                    vjps[k][t] = None  # free residuals early
                except BaseException:
                    _fail()
                    raise
                finally:
                    if k == 0:
                        with lock:
                            self._inflight -= 1
                        if window is not None:
                            window.release()

            return run

        # one linear chain: fwd stages 0..n-1 then bwd stages n-1..0 —
        # exactly FleetExecutor.pipeline's wiring
        chain = [fwd_task(k) for k in range(n)] + [bwd_task(k) for k in reversed(range(n))]
        FleetExecutor.pipeline(chain, num_micro).run()

        # microbatch-accumulated grads -> per-stage SGD
        for k in range(n):
            total = grads[k][0]
            for t in range(1, num_micro):
                total = jax.tree_util.tree_map(jnp.add, total, grads[k][t])
            total = jax.tree_util.tree_map(lambda g: g / num_micro, total)
            self.params[k] = self._sgd(self.params[k], total, self.lr)
        return float(sum(jax.device_get(l) for l in losses) / num_micro)


class DistHostPipelineTrainer:
    """Cross-process 1F1B pipeline: stage k lives in process/rank k, and
    interceptors exchange control + activations over the MessageBus
    (reference: FleetExecutor dist-model pipelines — SectionWorkers on
    different ranks wired by message_bus.h over brpc; here the bus is the
    framed-TCP transport in fleet_executor.cc).

    Each rank constructs this with ITS stage function and params only.
    Activations flow rank k → k+1 and cotangents k+1 → k as bus payloads;
    per-microbatch scheduling is the same dataflow gating as the local
    HostPipelineTrainer, with the 1F1B admission window enforced on rank 0
    (fwd 0 admits, bwd 0 releases — both local to rank 0).
    """

    LOSS_CHAN = -100  # bus payload channel: last rank ships losses to rank 0

    def __init__(self, stage_fn: Callable, params, loss_fn: Callable,
                 learning_rate: float, rank: int, n_stages: int,
                 bus: MessageBus, schedule: str = "1f1b",
                 admission_timeout: float = 30.0):
        if schedule not in ("1f1b", "gpipe"):
            raise ValueError(f"schedule must be 1f1b|gpipe, got {schedule!r}")
        # the first train_batch includes XLA compilation of every stage's
        # fwd/bwd across ranks, which can dwarf steady-state step time — a
        # much larger window applies until the first step completes
        self.admission_timeout = float(admission_timeout)
        self._first_step_done = False
        self.rank = int(rank)
        self.n = int(n_stages)
        self.bus = bus
        self.lr = learning_rate
        self.schedule = schedule
        self.params = params
        self.loss_fn = loss_fn
        last = self.rank == self.n - 1
        if last:
            def wrapped(p, x, lbl, _fn=stage_fn):
                return loss_fn(_fn(p, x), lbl)

            self._fwd = jax.jit(lambda p, x, lbl: jax.vjp(wrapped, p, x, lbl))
        else:
            self._fwd = jax.jit(lambda p, x: jax.vjp(stage_fn, p, x))
        self._bwd = jax.jit(lambda vjp, ct: vjp(ct))
        self._sgd = jax.jit(
            lambda p, g, lr: jax.tree_util.tree_map(
                lambda pv, gv: pv - lr * gv, p, g
            )
        )
        # global task ids: fwd stage k = k, bwd stage k = 2n-1-k
        self.task_ranks: Dict[int, int] = {}
        for k in range(self.n):
            self.task_ranks[k] = k
            self.task_ranks[2 * self.n - 1 - k] = k
        bus.set_task_rank(self.LOSS_CHAN, 0)
        self._step = 0

    def _nodes(self, num_micro: int) -> List[TaskNode]:
        """The FULL 2n-node chain, declared identically on every rank."""
        total = 2 * self.n
        nodes = []
        for tid in range(total):
            fn = None
            if self.task_ranks[tid] == self.rank:
                k = tid if tid < self.n else 2 * self.n - 1 - tid
                fn = self._fwd_task(k) if tid < self.n else self._bwd_task(k)
            node = TaskNode(tid, fn, max_run_times=num_micro)
            if tid > 0:
                node.add_upstream_task(tid - 1)
            if tid < total - 1:
                node.add_downstream_task(tid + 1)
            nodes.append(node)
        return nodes

    def _fwd_task(self, k):
        def run(t):
            if k == 0:
                self._admit()
                x = self._micro_xs[t]
            else:
                x = self.bus.get(k, self._scope(t))
            x = jnp.asarray(x)
            if k == self.n - 1:
                lbl = jnp.asarray(self._micro_labels[t])
                loss, vjp = self._fwd(self.params, x, lbl)
                self._losses[t] = loss
            else:
                y, vjp = self._fwd(self.params, x)
                self.bus.put(k + 1, self._scope(t), jax.device_get(y))
            self._vjps[t] = vjp

        return run

    def _bwd_task(self, k):
        def run(t):
            try:
                if k == self.n - 1:
                    ct = jnp.ones_like(self._losses[t])
                    out = self._bwd(self._vjps[t], ct)
                    gp, gx = out[0], out[1]
                else:
                    ct = jnp.asarray(self.bus.get(2 * self.n - 1 - k,
                                                  self._scope(t)))
                    gp, gx = self._bwd(self._vjps[t], ct)
                self._vjps[t] = None  # free residuals early
                if k > 0:
                    # cotangent to the upstream stage's bwd task
                    self.bus.put(2 * self.n - k, self._scope(t),
                                 jax.device_get(gx))
                self._grads[t] = gp
            finally:
                if k == 0 and self._window is not None:
                    self._window.release()

        return run

    def _scope(self, t: int) -> int:
        # bus payload keys must be unique across steps (a fast rank may
        # ship step s+1 payloads before a slow rank drained step s)
        return self._step * 1_000_000 + t

    def _admit(self):
        timeout = (self.admission_timeout if self._first_step_done
                   else max(self.admission_timeout, 600.0))
        if self._window is not None and not self._window.acquire(timeout=timeout):
            raise RuntimeError(
                "1f1b admission window starved (a downstream stage likely "
                "failed; its STOP aborts this step)"
            )

    def train_batch(self, micro_xs: Optional[Sequence] = None,
                    micro_labels: Optional[Sequence] = None,
                    num_micro: Optional[int] = None):
        """One global step. rank 0 supplies micro_xs, the last rank
        micro_labels; everyone else just passes num_micro. Returns the mean
        loss on rank 0 and the last rank, None on middle ranks."""
        import threading

        if num_micro is None:
            num_micro = len(micro_xs) if micro_xs is not None else len(micro_labels)
        self._micro_xs = list(micro_xs or [])
        self._micro_labels = list(micro_labels or [])
        if self.rank == 0 and len(self._micro_xs) != num_micro:
            raise ValueError("rank 0 needs one x per microbatch")
        if self.rank == self.n - 1 and len(self._micro_labels) != num_micro:
            raise ValueError("last rank needs one label per microbatch")
        self._vjps = [None] * num_micro
        self._grads = [None] * num_micro
        self._losses = [None] * num_micro
        self._window = (
            threading.Semaphore(self.n)
            if (self.schedule == "1f1b" and self.rank == 0)
            else None
        )

        FleetExecutor(
            self._nodes(num_micro), bus=self.bus, task_ranks=self.task_ranks
        ).run()

        total = self._grads[0]
        for t in range(1, num_micro):
            total = jax.tree_util.tree_map(jnp.add, total, self._grads[t])
        total = jax.tree_util.tree_map(lambda g: g / num_micro, total)
        self.params = self._sgd(self.params, total, self.lr)

        loss = None
        if self.rank == self.n - 1:
            loss = float(
                sum(jax.device_get(l) for l in self._losses) / num_micro
            )
            if self.rank != 0:
                self.bus.put(self.LOSS_CHAN, self._step,
                             jnp.asarray(loss, jnp.float32))
        if self.rank == 0 and loss is None:
            loss = float(self.bus.get(self.LOSS_CHAN, self._step))
        self._step += 1
        self._first_step_done = True
        return loss
