"""Cross-mesh checkpoint conversion.

Reference analogue: python/paddle/distributed/auto_parallel/converter.py:22
— Converter merges per-rank tensor shards saved under one distributed
strategy (process_shape + dims_mapping per tensor) into complete tensors,
then re-slices them for a different strategy, so a checkpoint from a 2×4
run restores onto a 4×2 (or any other) mesh.

Two paths here:
  - the numpy shard path (`Converter`): same contract as the reference —
    dicts of per-rank shard lists + dist_attrs in, re-sliced shards out.
    This is what multi-host restore uses when each host loads only its
    ranks' shards.
  - the live-array path (`reshard_state_dict`): single-controller jax can
    reshard in one device_put — assemble the global array (jax gathers
    addressable shards) and place it under the new NamedSharding.
"""
from __future__ import annotations

from typing import Dict, List, Sequence

import numpy as np

__all__ = ["Converter", "reshard_state_dict"]


class Converter:
    """Merge-and-slice tensors between distributed strategies.

    `pre_strategy` / `cur_strategy`: dict tensor_name -> dist_attr with
      process_shape : mesh topology the shards were produced on
      process_group : flat rank ids (len == prod(process_shape))
      dims_mapping  : tensor dim -> mesh dim (-1 = replicated), as in the
                      reference's dist_attr (converter.py:56 checks the
                      same three keys).
    `tensors_dict`: tensor_name -> list of per-rank numpy shards ordered by
    process_group position.
    """

    def __init__(self, tensors_dict: Dict[str, List[np.ndarray]],
                 pre_strategy: Dict[str, dict],
                 cur_strategy: Dict[str, dict]):
        self._tensors = self._check_tensors(tensors_dict)
        self._pre = self._check_strategy(pre_strategy, "pre_strategy")
        self._cur = self._check_strategy(cur_strategy, "cur_strategy")

    @staticmethod
    def _check_tensors(d):
        if not isinstance(d, dict) or not d:
            raise ValueError("tensors_dict must be a non-empty dict")
        out = {}
        for k, v in d.items():
            if not isinstance(v, (list, tuple)):
                v = [v]
            out[k] = [np.asarray(t) for t in v]
        return out

    @staticmethod
    def _check_strategy(s, name):
        if not isinstance(s, dict) or not s:
            raise ValueError(f"{name} must be a non-empty dict")
        for k, attr in s.items():
            for key in ("process_shape", "process_group", "dims_mapping"):
                if key not in attr:
                    raise ValueError(f"{name}[{k!r}] missing {key!r}")
            ndim = len(attr["process_shape"])
            bad = [d for d in attr["dims_mapping"] if d != -1 and not
                   (0 <= d < ndim)]
            if bad:
                raise ValueError(
                    f"{name}[{k!r}] dims_mapping {attr['dims_mapping']} "
                    f"references mesh dims {bad} outside the "
                    f"{ndim}-d process_shape"
                )
            n = 1
            for d in attr["process_shape"]:
                n *= int(d)
            if len(attr["process_group"]) != n:
                raise ValueError(
                    f"{name}[{k!r}] process_group has "
                    f"{len(attr['process_group'])} ranks but process_shape "
                    f"{attr['process_shape']} implies {n}"
                )
        return s

    # -- public --------------------------------------------------------------
    def convert(self, strict: bool = True) -> Dict[str, List[np.ndarray]]:
        """Return tensor_name -> per-rank shards under cur_strategy."""
        out = {}
        missing_pre = [k for k in self._cur if k not in self._tensors]
        if missing_pre and strict:
            raise ValueError(
                f"tensors missing from the checkpoint: {missing_pre}"
            )
        for name, shards in self._tensors.items():
            if name not in self._pre:
                if strict:
                    raise ValueError(f"{name!r} has no pre dist_attr")
                continue
            full = self.merge_with_dist_attr(shards, self._pre[name])
            cur = self._cur.get(name)
            if cur is None:
                out[name] = [full]
                continue
            out[name] = self.slice_with_dist_attr(full, cur)
        return out

    # -- merge ---------------------------------------------------------------
    @staticmethod
    def merge_with_dist_attr(shards: Sequence[np.ndarray], attr) -> np.ndarray:
        """Assemble the complete tensor from per-rank shards (reference:
        converter.py merge_with_dist_attr/merge)."""
        pshape = list(attr["process_shape"])
        group = list(attr["process_group"])
        dmap = list(attr["dims_mapping"])
        if len(shards) != len(group):
            raise ValueError(
                f"{len(shards)} shards for a {len(group)}-rank group"
            )
        s0 = shards[0]
        full_shape = list(s0.shape)
        for dim, mdim in enumerate(dmap):
            if mdim != -1:
                full_shape[dim] *= pshape[mdim]
        full = np.empty(full_shape, dtype=s0.dtype)
        for pos, _rank in enumerate(group):
            coord = _unravel(pos, pshape)
            index = []
            for dim, mdim in enumerate(dmap):
                if mdim == -1:
                    index.append(slice(None))
                else:
                    size = s0.shape[dim]
                    start = coord[mdim] * size
                    index.append(slice(start, start + size))
            full[tuple(index)] = shards[pos]
        return full

    # -- slice ---------------------------------------------------------------
    @staticmethod
    def slice_with_dist_attr(full: np.ndarray, attr) -> List[np.ndarray]:
        """Cut the complete tensor into per-rank shards for attr (reference:
        converter.py slice_with_dist_attr/split)."""
        pshape = list(attr["process_shape"])
        group = list(attr["process_group"])
        dmap = list(attr["dims_mapping"])
        out = []
        for pos, _rank in enumerate(group):
            coord = _unravel(pos, pshape)
            index = []
            for dim, mdim in enumerate(dmap):
                if mdim == -1:
                    index.append(slice(None))
                else:
                    n = pshape[mdim]
                    if full.shape[dim] % n:
                        raise ValueError(
                            f"dim {dim} ({full.shape[dim]}) not divisible "
                            f"by mesh dim {mdim} ({n})"
                        )
                    size = full.shape[dim] // n
                    start = coord[mdim] * size
                    index.append(slice(start, start + size))
            out.append(np.ascontiguousarray(full[tuple(index)]))
        return out


def _unravel(pos: int, shape: Sequence[int]) -> List[int]:
    coord = []
    for n in reversed(shape):
        coord.append(pos % n)
        pos //= n
    return list(reversed(coord))


def reshard_state_dict(state: dict, mesh, specs: dict, default_spec=None):
    """Live-array path: place every array of `state` onto `mesh` under
    `specs[name]` (a PartitionSpec), regardless of how (or on which mesh)
    it was previously sharded — single-controller jax assembles the global
    value and re-lays it out in one device_put."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ...core.tensor import Tensor

    out = {}
    for name, v in state.items():
        arr = v._value if isinstance(v, Tensor) else v
        spec = specs.get(name, default_spec) or P()
        placed = jax.device_put(jax.device_get(arr),
                                NamedSharding(mesh, spec))
        out[name] = Tensor(placed, stop_gradient=True) \
            if isinstance(v, Tensor) else placed
    return out
