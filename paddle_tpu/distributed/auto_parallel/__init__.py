"""Semi-automatic SPMD parallelism (auto-parallel).

Reference analogue: python/paddle/distributed/auto_parallel/ (~17k LoC) —
`ProcessMesh` (process_mesh.py), `shard_tensor`/`shard_op` annotations
(interface.py:34,74), the `Completer` that propagates dist attrs over the
program (completion.py:126), the `Partitioner` that rewrites it per rank
(partitioner.py:37), the `Resharder` inserting comm ops (reshard.py:603),
and the `Engine` fit/predict API (engine.py:50).

TPU-native design: the reference implements attribute propagation, program
partitioning and resharding by hand; XLA's GSPMD pass IS that pipeline
(SURVEY.md §7.7 — the mapping is almost 1:1):
  - ProcessMesh         → jax.sharding.Mesh over real devices
  - shard_tensor        → a PartitionSpec pinned to the tensor (params: a
                          `dist_spec` read by the compiled step; activations:
                          an in-trace sharding constraint)
  - shard_op            → sharding constraints on the op's outputs
  - Completer/Partitioner/Resharder → GSPMD propagation + partitioning +
                          collective insertion at compile time
  - Engine              → mesh install + param sharding + the compiled
                          hybrid train step (parallel/sharding.py)
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ...core.tensor import Tensor

__all__ = ["ProcessMesh", "shard_tensor", "shard_op", "Engine", "get_mesh",
           "Planner", "CostModel", "ModelDesc", "ClusterSpec", "DeviceSpec",
           "Candidate", "Plan", "Converter", "reshard_state_dict"]

_default_process_mesh: Optional["ProcessMesh"] = None


class ProcessMesh:
    """reference: process_mesh.py — N-d array of logical process ids.

    On TPU the logical process ids index jax.devices(); the mesh directly
    becomes a jax.sharding.Mesh with one axis name per dim ("d0", "d1", ...
    or user-provided dim_names)."""

    def __init__(self, mesh: Sequence, dim_names: Optional[List[str]] = None,
                 parent=None):
        arr = np.asarray(mesh)
        if arr.ndim == 0:
            arr = arr.reshape(1)
        self.mesh = arr.tolist()
        self.topology = list(arr.shape)
        self.processes = [int(i) for i in arr.flatten()]
        if len(set(self.processes)) != len(self.processes):
            raise ValueError("ProcessMesh must not contain duplicate processes")
        self.dim_names = list(dim_names) if dim_names else [
            f"d{i}" for i in range(arr.ndim)
        ]
        if len(self.dim_names) != arr.ndim:
            raise ValueError("dim_names length must match mesh ndim")
        self._jax_mesh = None
        # the most recently constructed mesh is the default for annotations
        # that omit process_mesh (reference: default_dist_context) — latest
        # wins, so a stale early mesh cannot shadow the one in use
        global _default_process_mesh
        _default_process_mesh = self

    @property
    def ndim(self):
        return len(self.topology)

    @property
    def shape(self):
        return list(self.topology)

    def jax_mesh(self) -> Mesh:
        if self._jax_mesh is None:
            devs = jax.devices()
            if max(self.processes) >= len(devs):
                raise ValueError(
                    f"ProcessMesh references process {max(self.processes)} "
                    f"but only {len(devs)} devices are visible"
                )
            dev_arr = np.asarray([devs[i] for i in self.processes]).reshape(
                self.topology
            )
            self._jax_mesh = Mesh(dev_arr, tuple(self.dim_names))
        return self._jax_mesh

    def __eq__(self, other):
        return (
            isinstance(other, ProcessMesh)
            and self.mesh == other.mesh
            and self.dim_names == other.dim_names
        )

    def __repr__(self):
        return f"ProcessMesh(shape={self.topology}, dims={self.dim_names})"


def get_mesh() -> Optional[ProcessMesh]:
    return _default_process_mesh


def _spec_from_dims_mapping(pm: ProcessMesh, dims_mapping: Sequence[int]) -> P:
    entries = []
    for d in dims_mapping:
        entries.append(None if d == -1 else pm.dim_names[d])
    return P(*entries)


def _resolve(dist_attr, x=None):
    """dist_attr dict → (ProcessMesh, dims_mapping). Accepts the reference's
    raw nested-list process_mesh form."""
    dist_attr = dist_attr or {}
    pm = dist_attr.get("process_mesh") or _default_process_mesh
    if pm is not None and not isinstance(pm, ProcessMesh):
        pm = ProcessMesh(pm)
    dm = dist_attr.get("dims_mapping")
    if dm is None and x is not None:
        dm = [-1] * x.ndim
    return pm, dm


def shard_tensor(x, dist_attr=None, process_mesh=None, shard_spec=None):
    """reference: interface.py:34. Two accepted forms:
      shard_tensor(x, {"process_mesh": pm, "dims_mapping": [0, -1]})
      shard_tensor(x, process_mesh=pm, shard_spec=["dp", None])  (2.4 style)
    Parameters get a pinned `dist_spec` (consumed by the compiled step's
    GSPMD partitioning = the reference's Completer+Partitioner); activations
    additionally get an in-trace sharding constraint."""
    if process_mesh is not None:
        pm = process_mesh if isinstance(process_mesh, ProcessMesh) else ProcessMesh(process_mesh)
        spec = P(*[s for s in (shard_spec or [None] * x.ndim)])
    else:
        pm, dm = _resolve(dist_attr, x)
        if pm is None:
            raise ValueError("no ProcessMesh given or previously created")
        spec = _spec_from_dims_mapping(pm, dm)
    x.dist_spec = tuple(spec)
    x.process_mesh = pm
    if not getattr(x, "is_parameter", False):
        from ...parallel.sharding import with_sharding_constraint

        return with_sharding_constraint(x, *tuple(spec))
    return x


class _ShardedOp:
    """reference: DistributedModule (dist_op.py) returned by shard_op."""

    def __init__(self, op_fn, dist_attr=None):
        self.op_fn = op_fn
        self.dist_attr = dist_attr or {}

    def __call__(self, *args, **kwargs):
        from ...parallel.topology import use_mesh

        pm, _ = _resolve(self.dist_attr)
        out_attr = self.dist_attr.get("out") or self.dist_attr.get("outputs")
        if out_attr is None or pm is None:
            return self.op_fn(*args, **kwargs)
        # run under the annotation's own mesh so the constraint binds even
        # when no global mesh (or a different one) is installed
        with use_mesh(pm.jax_mesh()):
            from ...parallel.sharding import with_sharding_constraint

            out = self.op_fn(*args, **kwargs)
            spec = _spec_from_dims_mapping(pm, out_attr["dims_mapping"])
            outs = out if isinstance(out, (list, tuple)) else [out]
            outs = [with_sharding_constraint(o, *tuple(spec)) for o in outs]
            return type(out)(outs) if isinstance(out, (list, tuple)) else outs[0]


def shard_op(op_fn, dist_attr=None):
    """reference: interface.py:74."""
    return _ShardedOp(op_fn, dist_attr)


class Engine:
    """reference: engine.py:50 — prepare/fit/evaluate/predict over the
    annotated model. TPU-native: installs the ProcessMesh as the global
    mesh, physically shards annotated parameters, and compiles ONE hybrid
    SPMD train step (the _build/_plan/_parallel/_initialize pipeline
    collapses into GSPMD compilation)."""

    def __init__(self, model=None, inputs_spec=None, labels_spec=None,
                 cluster=None, strategy=None, process_mesh=None,
                 data_axis=None, auto=False, tune=False):
        self.model = model
        self.inputs_spec = inputs_spec
        self.labels_spec = labels_spec
        self.cluster = cluster
        self.strategy = strategy
        # auto=True (or strategy.auto): the Planner chooses the mesh
        # factorization from the cost model instead of the user's
        # process_mesh (reference: engine.py _plan → Planner.search);
        # tune=True additionally MEASURES the planner's top candidates on
        # the devices and keeps the fastest (reference: OptimizationTuner)
        self.auto = bool(auto or (strategy is not None
                                  and getattr(strategy, "auto", False)))
        self.tune = bool(tune)
        self.plan = None
        self.process_mesh = process_mesh or (
            None if self.auto else _default_process_mesh
        )
        # mesh axis the batch is sharded over; defaults to mesh dim 0 (the
        # conventional data axis) — pass data_axis when your mesh orders
        # model-parallel first
        self.data_axis = data_axis
        self._optimizer = None
        self._loss = None
        self._metrics = None
        self._train_step = None
        self._prepared = False
        self.mode = "train"

    def prepare(self, optimizer=None, loss=None, metrics=None, mode="train",
                all_ranks=False):
        from ...parallel.topology import set_mesh
        from ...parallel.sharding import shard_params

        self._optimizer = optimizer
        self._loss = loss
        self._metrics = metrics
        self.mode = mode
        if self.auto and self.process_mesh is None:
            self.process_mesh = self._plan_mesh()
        if self.process_mesh is None:
            self.process_mesh = _default_process_mesh
        if self.process_mesh is not None:
            # install as the global mesh; the hcg is cleared in the same
            # call so topology queries cannot disagree with this mesh
            set_mesh(self.process_mesh.jax_mesh())
        if self.model is not None:
            shard_params(self.model)
        self._prepared = True
        return self

    def _plan_mesh(self) -> "ProcessMesh":
        """auto=True: choose the mesh factorization with the cost-model
        Planner (reference: engine.py _plan → planner_v2/Planner). The
        chosen spec is logged and kept on `self.plan`. A zero_stage>0 plan
        names its data axis 'sharding' — that is the axis param_spec/
        _state_spec shard ZeRO state over (parallel/sharding.py)."""
        import jax as _jax

        from .planner import ClusterSpec, plan_for_model

        batch, seq = self._data_shape_hint()
        cluster = self.cluster if isinstance(self.cluster, ClusterSpec) \
            else ClusterSpec(n_devices=len(_jax.devices()))
        # Engine's compiled step expresses dp/mp/zero; pp needs the
        # pipeline-block protocol, which the fleet path handles
        plans = plan_for_model(self.model, seq_len=seq, global_batch=batch,
                               cluster=cluster, allow_pp=False,
                               topk=3 if self.tune else 1)
        if self.tune:
            self.plan = self._tune_plan(plans, batch)
        else:
            self.plan = plans
        c = self.plan.candidate
        ids = np.arange(cluster.n_devices).reshape(c.dp, c.mp)
        data_dim = "sharding" if c.zero_stage > 0 else "dp"
        return ProcessMesh(ids.tolist(), dim_names=[data_dim, "mp"])

    def _tune_plan(self, plans, batch):
        """Measure the planner's top candidates on the devices and keep the
        fastest (reference: tuner/optimization_tuner.py). Needs concrete
        single-tensor inputs_spec+labels_spec to synthesize a trial batch;
        parameter/buffer/optimizer state is snapshotted and restored so
        trial steps don't perturb the init. Any failure falls back to the
        analytic best plan with a warning."""
        import warnings

        import jax.numpy as jnp

        from ...parallel.sharding import shard_params, sharded_train_step
        from ...parallel.topology import init_mesh
        from .tuner import ProfileTuner

        analytic = plans[0]
        if not (self.inputs_spec and self.labels_spec and self._loss
                and self._optimizer):
            warnings.warn(
                "Engine(tune=True) needs inputs_spec, labels_spec, loss and "
                "optimizer to synthesize trial batches; keeping the "
                "analytic plan"
            )
            return analytic
        if isinstance(self.labels_spec, (list, tuple)) \
                and len(self.labels_spec) > 1:
            warnings.warn(
                "Engine(tune=True) needs a single-tensor labels spec (the "
                "compiled step's loss contract takes one label tensor); "
                "keeping the analytic plan"
            )
            return analytic
        if len(plans) < 2:
            return analytic

        def synth_one(spec):
            shape = [batch if (d in (None, -1) or i == 0) else int(d)
                     for i, d in enumerate(spec.shape)]
            dtype = str(getattr(spec, "dtype", "float32"))
            if "int" in dtype:
                return Tensor(jnp.zeros(shape, jnp.int32),
                              stop_gradient=True)
            return Tensor(jnp.zeros(shape, jnp.float32),
                          stop_gradient=True)

        def synth(spec):
            # multi-input models (r4 weak #6): synthesize every tensor
            if isinstance(spec, (list, tuple)):
                return tuple(synth_one(s) for s in spec)
            return (synth_one(spec),)

        xs, (y,) = synth(self.inputs_spec), synth(self.labels_spec)
        # shared donation-safety harness (tuner.TrialStateGuard): trial
        # steps donate the device buffers — params/buffers/opt state
        # snapshot to host and restore per candidate + once in finally
        from .tuner import TrialStateGuard

        guard = TrialStateGuard(self.model, self._optimizer)

        def model_fn(cand):
            from .planner import mesh_degrees_for

            guard.restore()
            init_mesh(**mesh_degrees_for(cand))
            shard_params(self.model, zero_stage=cand.zero_stage)
            step = sharded_train_step(
                self.model, self._loss, self._optimizer,
                zero_stage=cand.zero_stage,
                batch_axes=("dp", "sharding"),
            )
            return step, tuple(xs) + (y,)

        best = None
        try:
            tuner = ProfileTuner(model_fn,
                                 [p.candidate for p in plans], iters=2)
            best = tuner.tune(verbose=True)
        except RuntimeError as e:
            warnings.warn(
                f"profile tuning failed ({e}); keeping the analytic plan"
            )
        finally:
            guard.restore()
        for p in plans:
            if p.candidate is best:
                return p
        return analytic

    def _data_shape_hint(self):
        """(global_batch, seq_len) from inputs_spec, else a dp-wide default."""
        import jax as _jax

        shape = None
        spec = self.inputs_spec
        if spec:
            first = spec[0] if isinstance(spec, (list, tuple)) else spec
            shape = list(getattr(first, "shape", None) or [])
        if not shape:
            return len(_jax.devices()), 1
        batch = shape[0] if shape[0] and shape[0] > 0 else len(_jax.devices())
        seq = shape[1] if len(shape) > 1 and shape[1] else 1
        return int(batch), int(seq)

    def _ensure_step(self):
        if not self._prepared:
            raise RuntimeError(
                "Engine.prepare(optimizer=..., loss=...) must be called "
                "before fit/evaluate/predict"
            )
        if self._train_step is None:
            from ...parallel.sharding import ShardedTrainStep

            mesh = self.process_mesh.jax_mesh() if self.process_mesh else None
            axis = self.data_axis or (
                self.process_mesh.dim_names[0] if self.process_mesh else "dp"
            )
            zero = self.plan.candidate.zero_stage if self.plan else 0
            self._train_step = ShardedTrainStep(
                self.model, self._loss, self._optimizer, mesh=mesh,
                batch_axes=(axis,), zero_stage=zero,
            )
        return self._train_step

    def _iter_batches(self, data, batch_size):
        from ...io import DataLoader, Dataset

        if isinstance(data, Dataset):
            return DataLoader(data, batch_size=batch_size or 1)
        return data  # already an iterable of ready batches

    def fit(self, train_data, batch_size=1, epochs=1, steps_per_epoch=None,
            verbose=0):
        """train_data: a paddle.io.Dataset (batched via `batch_size`) or an
        iterable of ready (inputs, labels) batches (batch_size ignored)."""
        step = self._ensure_step()
        loader = self._iter_batches(train_data, batch_size)
        history = []
        for epoch in range(epochs):
            for i, batch in enumerate(loader):
                if steps_per_epoch is not None and i >= steps_per_epoch:
                    break
                xs, ys = batch
                loss = step(xs, ys)
                history.append(float(loss))
                if verbose:
                    print(f"epoch {epoch} step {i}: loss {history[-1]:.4f}")
        return history

    def _eval_forward(self, xs):
        from ...jit import functional_call

        params = dict(self.model.named_parameters())
        params.update(dict(self.model.named_buffers()))
        return functional_call(self.model, params, xs)

    def evaluate(self, valid_data, batch_size=1, steps=None):
        if not self._prepared:
            raise RuntimeError("call Engine.prepare(...) before evaluate")
        total, n = 0.0, 0
        for i, batch in enumerate(self._iter_batches(valid_data, batch_size)):
            if steps is not None and i >= steps:
                break
            xs, ys = batch
            out = self._eval_forward(xs)
            loss = self._loss(out, ys) if self._loss else out
            lv = loss.mean() if loss.ndim > 0 else loss
            total += float(lv)
            n += 1
        return total / max(n, 1)

    def predict(self, test_data, batch_size=1, steps=None):
        if not self._prepared:
            raise RuntimeError("call Engine.prepare(...) before predict")
        outs = []
        for i, batch in enumerate(self._iter_batches(test_data, batch_size)):
            if steps is not None and i >= steps:
                break
            xs = batch[0] if isinstance(batch, (tuple, list)) else batch
            outs.append(self._eval_forward(xs))
        return outs

    def save(self, path, training=True, mode=None):
        import paddle_tpu as paddle

        paddle.save(self.model.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            paddle.save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, strict=True, load_optimizer=True, mode=None):
        import paddle_tpu as paddle

        self.model.set_state_dict(paddle.load(path + ".pdparams"))
        if load_optimizer and self._optimizer is not None:
            import os

            if os.path.exists(path + ".pdopt"):
                self._optimizer.set_state_dict(paddle.load(path + ".pdopt"))


from .planner import (  # noqa: E402
    Candidate,
    ClusterSpec,
    CostModel,
    DeviceSpec,
    ModelDesc,
    Plan,
    Planner,
)
from .converter import Converter, reshard_state_dict  # noqa: E402
from .tuner import ProfileTuner, cluster_from_json, map_processes  # noqa: E402,F401
