"""Cost-model-driven sharding planner.

Reference analogue: python/paddle/distributed/auto_parallel/planner.py:826
(Planner driving an MCMC search over per-op dims_mappings, planner.py:379),
cost_model.py (comm+compute cost estimation over the op graph), cluster.py
(Device/Link/Machine capability model) and mapper.py (process→device
placement by link bandwidth).

TPU-native design: GSPMD already solves the reference's inner problem — given
a mesh and input/param shardings it propagates per-op partitionings and
inserts collectives — so the search space collapses from per-op dims_mapping
enumeration (the reference's PlanSpace, planner.py:105) to MESH
FACTORIZATIONS × ZeRO stage. An analytic roofline model scores each
candidate: MXU compute time (with small-tile efficiency decay), ICI/DCN
collective time (DP grad reduction, TP activation all-reduces, PP bubble,
ring-attention rotation), and HBM feasibility (params + optimizer state +
activations under remat). The mapper's job — keep the chattiest axis on the
fastest links — becomes axis ORDERING: mp innermost (intra-host ICI), dp
outermost (can ride DCN).
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

__all__ = ["DeviceSpec", "ClusterSpec", "ModelDesc", "Candidate", "Plan",
           "CostModel", "Planner"]


@dataclass
class DeviceSpec:
    """One accelerator (reference: cluster.py Device — dp_gflops/memory).
    Defaults are TPU v5e-class, matching the measured numbers committed in
    PROFILE_RESNET.md (practical bf16 throughput ≈135 TF/s of the 197 peak)."""

    flops_bf16: float = 197e12          # peak MXU throughput, bytes/s
    mxu_efficiency: float = 0.68        # practical fraction at healthy tiles
    hbm_bytes: float = 16e9
    hbm_bw: float = 8.1e11              # bytes/s


@dataclass
class ClusterSpec:
    """reference: cluster.py Machine/Link graph. TPU pods are regular, so
    bandwidth per axis-neighbor is enough: ICI within a slice, DCN across
    hosts of a multi-slice job."""

    n_devices: int = 8
    devices_per_host: int = 8
    ici_bw: float = 9e10                # bytes/s per direction per link
    dcn_bw: float = 6.25e9              # bytes/s per host NIC
    coll_latency: float = 3e-6          # fixed cost per collective launch
    device: DeviceSpec = field(default_factory=DeviceSpec)

    def axis_bandwidth(self, inner: bool) -> float:
        """Collectives on inner (intra-host) axes ride ICI; outer axes may
        cross hosts (reference mapper.py places by link type)."""
        return self.ici_bw if inner else (
            self.ici_bw if self.n_devices <= self.devices_per_host
            else self.dcn_bw
        )


@dataclass
class ModelDesc:
    """What the cost model needs to know about the network — the TPU
    replacement for the reference's per-op graph walk (cost_model.py): for
    dense transformer-family models these five numbers determine FLOPs,
    comm volumes, and activation footprints to ~10%."""

    params: int                          # trainable parameter count
    layers: int                          # repeated blocks (pp split unit)
    hidden: int
    seq_len: int
    global_batch: int                    # sequences per optimizer step
    vocab: int = 0
    param_bytes: int = 4                 # master/weight dtype bytes
    act_bytes: int = 2                   # activation dtype (bf16 compute)
    opt_state_bytes_per_param: int = 8   # adam m+v fp32
    use_remat: bool = True

    @classmethod
    def from_gpt_config(cls, cfg, global_batch: int) -> "ModelDesc":
        h, L, v = cfg.hidden_size, cfg.num_layers, cfg.vocab_size
        ffn = cfg.ffn_hidden_size or 4 * h
        params = L * (4 * h * h + 2 * h * ffn) + v * h + cfg.max_seq_len * h
        return cls(params=int(params), layers=L, hidden=h,
                   seq_len=cfg.max_seq_len, global_batch=global_batch, vocab=v)

    @classmethod
    def from_model(cls, model, seq_len: int, global_batch: int) -> "ModelDesc":
        """Introspect a generic Layer: parameter count from the tree, layer
        count from the longest repeated-sublayer container."""
        params = sum(
            int(math.prod(p.shape)) for p in model.parameters()
            if not p.stop_gradient
        )
        blocks = 1
        hidden = 0
        for _, sub in model.named_sublayers():
            kids = getattr(sub, "_sub_layers", {})
            same = {}
            for child in kids.values():
                same.setdefault(type(child).__name__, 0)
                same[type(child).__name__] += 1
            if same:
                blocks = max(blocks, max(same.values()))
        for p in model.parameters():
            if len(p.shape) == 2:
                hidden = max(hidden, min(int(p.shape[0]), int(p.shape[1])))
        return cls(params=params, layers=blocks, hidden=max(hidden, 1),
                   seq_len=seq_len, global_batch=global_batch)


@dataclass
class Candidate:
    dp: int = 1
    mp: int = 1
    pp: int = 1
    sep: int = 1
    zero_stage: int = 0
    micro_batches: int = 1

    @property
    def degrees(self) -> Dict[str, int]:
        return {"dp": self.dp, "mp": self.mp, "pp": self.pp, "sep": self.sep}

    def __str__(self):
        return (f"dp={self.dp} mp={self.mp} pp={self.pp} sep={self.sep} "
                f"zero={self.zero_stage} micro={self.micro_batches}")


@dataclass
class Plan:
    candidate: Candidate
    cost_ms: float
    breakdown: Dict[str, float]
    mem_bytes: float
    rejected: List[Tuple[Candidate, str]] = field(default_factory=list)

    def log(self) -> str:
        bd = " ".join(f"{k}={v:.2f}ms" for k, v in self.breakdown.items())
        return (f"[auto-parallel plan] {self.candidate} | est "
                f"{self.cost_ms:.2f} ms/step ({bd}) | "
                f"{self.mem_bytes / 1e9:.2f} GB/chip")


class CostModel:
    """Analytic roofline estimate of one training step under a candidate.

    Reference analogue: cost_model.py estimate_cost (graph-walk with static
    per-op tables + cross_node_penalty). Here the volumes come from the
    transformer structure and the times from the ClusterSpec's roofline.
    All-reduce time uses the ring bound 2·(n-1)/n · V / BW; reduce-scatter
    and all-gather are each half that.
    """

    def __init__(self, cluster: Optional[ClusterSpec] = None):
        self.cluster = cluster or ClusterSpec()

    # -- pieces --------------------------------------------------------------
    def _allreduce_ms(self, vol_bytes: float, n: int, bw: float,
                      n_launches: float = 1.0) -> float:
        """Ring bound + per-collective launch latency — the latency term is
        what makes fine-grained TP on small models lose to DP (bandwidth
        alone ties them)."""
        if n <= 1 or vol_bytes <= 0:
            return 0.0
        wire = 2.0 * (n - 1) / n * vol_bytes / bw
        return (wire + n_launches * self.cluster.coll_latency) * 1e3

    def _mxu_eff(self, c: Candidate, m: ModelDesc) -> float:
        """Small per-chip contractions can't fill the 128×128 systolic
        array: decay efficiency once hidden/mp (or ffn/mp) tiles drop below
        256 lanes."""
        base = self.cluster.device.mxu_efficiency
        tile = m.hidden / max(c.mp, 1)
        decay = min(1.0, tile / 256.0)
        # tiny per-chip batch also starves the array
        tok = m.global_batch * m.seq_len / (c.dp * c.sep * max(c.pp, 1))
        decay *= min(1.0, tok / 1024.0)
        # the floor only guards against divide-by-zero — it must stay far
        # below any real efficiency so tiny-model candidates still rank by
        # their relative decay instead of all saturating at the floor
        return max(base * decay, 1e-7)

    # -- main ----------------------------------------------------------------
    def estimate(self, m: ModelDesc, c: Candidate):
        """Return (cost_ms, breakdown, mem_bytes) or (None, reason, mem)."""
        cl = self.cluster
        n = c.dp * c.mp * c.pp * c.sep
        if n != cl.n_devices:
            return None, "degree product != device count", 0.0

        # ---- memory feasibility (reference: PlanFilter, planner.py:44) ----
        p_shard = m.params / (c.mp * c.pp)          # TP×PP split the weights
        zdiv = c.dp if c.zero_stage >= 1 else 1
        opt_bytes = m.params / (c.mp * c.pp) / zdiv * m.opt_state_bytes_per_param
        w_bytes = p_shard * m.param_bytes / (zdiv if c.zero_stage >= 3 else 1)
        g_bytes = p_shard * m.param_bytes / (zdiv if c.zero_stage >= 2 else 1)
        # activations: per layer ~ (10·h + attn) bytes/token without remat;
        # remat keeps ~2·h (block boundaries) and recomputes the rest
        tokens_local = (m.global_batch / c.dp) * (m.seq_len / c.sep) \
            / max(c.micro_batches if c.pp > 1 else 1, 1)
        act_per_layer = (2.0 if m.use_remat else 10.0) * m.hidden / c.mp \
            * m.act_bytes * tokens_local
        act_bytes = act_per_layer * (m.layers / c.pp) \
            * (min(c.pp, c.micro_batches) if c.pp > 1 else 1)
        mem = w_bytes + g_bytes + opt_bytes + act_bytes
        if mem > cl.device.hbm_bytes * 0.92:
            return None, f"needs {mem / 1e9:.1f} GB/chip", mem

        # ---- compute ------------------------------------------------------
        tokens = m.global_batch * m.seq_len
        flops = 6.0 * m.params * tokens              # fwd 2PT + bwd 4PT
        if m.use_remat:
            flops *= 4.0 / 3.0                       # recompute fwd once more
        eff = self._mxu_eff(c, m)
        compute_ms = flops / (n * cl.device.flops_bf16 * eff) * 1e3
        if c.pp > 1:
            mb = max(c.micro_batches, 1)
            bubble = (c.pp - 1) / (mb + c.pp - 1)
            compute_ms *= 1.0 / max(1.0 - bubble, 1e-6) - 0.0
        breakdown = {"compute": compute_ms}

        # ---- dp gradient reduction ---------------------------------------
        bw_dp = cl.axis_bandwidth(inner=False)
        grad_vol = m.params / (c.mp * c.pp) * m.param_bytes
        # ZeRO swaps all-reduce for reduce-scatter (+all-gather of updated
        # shards) — same ring volume, so the ring bound is identical. XLA
        # fuses the grad reduction into a handful of launches.
        breakdown["dp_grads"] = self._allreduce_ms(grad_vol, c.dp, bw_dp,
                                                   n_launches=2.0)

        # ---- tp activation all-reduces -----------------------------------
        bw_mp = cl.axis_bandwidth(inner=True)
        if c.mp > 1:
            act_vol = (m.global_batch / c.dp) * (m.seq_len / c.sep) \
                * m.hidden * m.act_bytes
            # 2 all-reduces fwd + 2 bwd per block (megatron pattern),
            # ×4/3 when remat replays the forward
            n_ar = m.layers * 4 * (4.0 / 3.0 if m.use_remat else 1.0)
            if c.pp > 1:
                n_ar /= c.pp  # per-chip layers only
            breakdown["tp_acts"] = self._allreduce_ms(
                act_vol * n_ar, c.mp, bw_mp, n_launches=n_ar
            )

        # ---- pp boundary p2p ---------------------------------------------
        if c.pp > 1:
            mb = max(c.micro_batches, 1)
            vol = (m.global_batch / c.dp) * m.seq_len / c.sep * m.hidden \
                * m.act_bytes / mb
            # each micro crosses pp-1 boundaries fwd + bwd
            n_hops = 2 * (c.pp - 1) * mb
            breakdown["pp_p2p"] = (
                n_hops * vol / bw_mp + n_hops * cl.coll_latency
            ) * 1e3

        # ---- ring attention rotation -------------------------------------
        if c.sep > 1:
            kv_vol = (m.global_batch / c.dp) * m.seq_len * m.hidden \
                / c.mp * m.act_bytes * 2  # k and v
            n_ring = m.layers / c.pp * (4.0 / 3.0 if m.use_remat else 1.0)
            breakdown["ring_kv"] = (
                (c.sep - 1) / c.sep * kv_vol * n_ring / bw_mp
                + n_ring * (c.sep - 1) * cl.coll_latency
            ) * 1e3

        total = sum(breakdown.values())
        return total, breakdown, mem


class Planner:
    """Enumerate mesh factorizations, score with the CostModel, pick argmin.

    Reference analogue: planner.py:826 (Planner.search over PlanSpace via
    MCMC). The TPU candidate space is small enough for exhaustive search.
    """

    def __init__(self, model_desc: ModelDesc,
                 cluster: Optional[ClusterSpec] = None,
                 long_context: bool = False, allow_pp: bool = True,
                 allow_mp: bool = True):
        self.model = model_desc
        self.cluster = cluster or ClusterSpec()
        self.cost_model = CostModel(self.cluster)
        self.long_context = long_context
        self.allow_pp = allow_pp
        self.allow_mp = allow_mp

    def candidates(self) -> List[Candidate]:
        n = self.cluster.n_devices
        m = self.model
        out = []
        for mp in _divisors(n) if self.allow_mp else [1]:
            for pp in _divisors(n // mp) if self.allow_pp else [1]:
                rest = n // (mp * pp)
                seps = [s for s in _divisors(rest)] if self.long_context else [1]
                for sep in seps:
                    dp = rest // sep
                    if pp > m.layers:
                        continue
                    if m.global_batch % (dp or 1):
                        continue
                    if sep > 1 and m.seq_len % sep:
                        continue
                    for zero in (0, 2, 3) if dp > 1 else (0,):
                        micro = max(2 * pp, 1) if pp > 1 else 1
                        # micro must divide the local batch
                        if pp > 1 and (m.global_batch // dp) % micro:
                            micro = math.gcd(m.global_batch // dp, micro)
                        out.append(Candidate(dp=dp, mp=mp, pp=pp, sep=sep,
                                             zero_stage=zero,
                                             micro_batches=micro))
        return out

    def plan(self, verbose: bool = False) -> Plan:
        return self.plan_topk(1, verbose=verbose)[0]

    def plan_topk(self, k: int, verbose: bool = False) -> List[Plan]:
        """The k cheapest feasible plans, best first — the candidate list a
        ProfileTuner can then MEASURE (reference: the planner hands its
        shortlist to the OptimizationTuner's trial loop)."""
        scored = []
        rejected: List[Tuple[Candidate, str]] = []
        for c in self.candidates():
            cost, breakdown, mem = self.cost_model.estimate(self.model, c)
            if cost is None:
                rejected.append((c, breakdown))
                continue
            # near-ties go to the simpler topology: every model-parallel
            # axis adds collectives the analytic model can underestimate
            cost *= 1.0 + 0.01 * (
                (c.mp > 1) + (c.pp > 1) + (c.sep > 1) + (c.zero_stage > 0)
            )
            scored.append((cost, c, breakdown, mem))
        if not scored:
            raise RuntimeError(
                "auto-parallel planner: no feasible candidate — model does "
                "not fit HBM at any factorization; add chips or shrink the "
                f"model (rejections: {rejected[:5]})"
            )
        scored.sort(key=lambda t: t[0])
        plans = [
            Plan(candidate=c, cost_ms=cost, breakdown=bd, mem_bytes=mem,
                 rejected=rejected)
            for cost, c, bd, mem in scored[:max(k, 1)]
        ]
        if verbose:
            for p in plans:
                print(p.log())
        return plans


def _divisors(n: int) -> List[int]:
    return [d for d in range(1, n + 1) if n % d == 0]


def plan_for_model(model, seq_len: int, global_batch: int,
                   cluster: Optional[ClusterSpec] = None,
                   allow_pp: Optional[bool] = None, topk: int = 1):
    """Shared auto-plan entry used by Engine(auto=True) and the fleet's
    strategy.auto path: introspect the model (TP-annotated weights gate mp;
    the pipeline-block protocol gates pp), build the ModelDesc, run the
    Planner, log the chosen spec. topk=1 returns the best Plan; topk>1
    returns the k cheapest Plans best-first (one introspection pass serves
    both the analytic choice and the profile tuner's shortlist)."""
    import jax

    desc = ModelDesc.from_model(model, seq_len=seq_len,
                                global_batch=global_batch)
    has_tp = any(
        getattr(p, "dist_spec", None) for p in model.parameters()
    ) or any(
        type(sub).__name__ in ("ColumnParallelLinear", "RowParallelLinear",
                               "VocabParallelEmbedding")
        for _, sub in model.named_sublayers()
    )
    has_pp = hasattr(model, "pp_blocks") if allow_pp is None else allow_pp
    cluster = cluster or ClusterSpec(n_devices=len(jax.devices()))
    plans = Planner(desc, cluster, allow_pp=has_pp,
                    allow_mp=has_tp).plan_topk(topk)
    print(plans[0].log())
    return plans[0] if topk == 1 else plans


def mesh_degrees_for(candidate: Candidate) -> Dict[str, int]:
    """Candidate → init_mesh degrees. ZeRO shards params/optimizer state
    over the mesh axis NAMED 'sharding' (parallel/sharding.py param_spec),
    so a zero_stage>0 plan moves its data-parallel degree onto that axis —
    otherwise the logged plan would claim ZeRO memory while the state stays
    replicated."""
    c = candidate
    if c.zero_stage > 0:
        return {"dp": 1, "mp": c.mp, "pp": c.pp, "sep": c.sep,
                "sharding": c.dp}
    return {"dp": c.dp, "mp": c.mp, "pp": c.pp, "sep": c.sep, "sharding": 1}
