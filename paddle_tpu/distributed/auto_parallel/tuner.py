"""Cluster description loading, process mapping, and the profile tuner.

Reference analogues:
- cluster.py: Cluster.build_from_file parsing a machines/devices/links
  JSON into a capability graph consumed by the cost model;
- mapper.py: mapping(dist_program, cluster) — place logical ranks onto
  physical devices so the chattiest communicators share the best links;
- tuner/: OptimizationTuner — try candidate strategies, MEASURE, keep the
  best (profile-guided, versus the planner's analytic model).

TPU-native: the capability graph collapses to ClusterSpec (regular pod
topologies); mapping collapses to axis ORDERING over jax.devices() (mp
innermost so TP collectives ride intra-host ICI); the tuner compiles and
times each candidate mesh on the real devices and keeps the fastest —
measurement beats any model when the hardware is in hand.
"""
from __future__ import annotations

import json
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from .planner import Candidate, ClusterSpec, DeviceSpec

__all__ = ["cluster_from_json", "map_processes", "ProfileTuner"]


def cluster_from_json(path: str) -> ClusterSpec:
    """Parse the reference's cluster JSON (machines[].devices[] with
    gflops/memory, links[] with bandwidth) into a ClusterSpec.

    Unknown/missing fields fall back to the v5e defaults; heterogeneous
    clusters take the MINIMUM capability (the straggler sets the pace)."""
    with open(path) as f:
        doc = json.load(f)
    machines = doc.get("machines", [])
    if not machines:
        raise ValueError(f"{path}: no machines in cluster file")
    n_devices = 0
    per_host = []
    flops = []
    mem = []
    for m in machines:
        devs = [d for d in m.get("devices", [])
                if d.get("type", "GPU") not in ("CPU",)]
        per_host.append(len(devs))
        n_devices += len(devs)
        for d in devs:
            # reference stores double-precision gflops; sp_gflops when given
            g = d.get("sp_gflops") or d.get("dp_gflops")
            if g:
                flops.append(float(g) * 1e9)
            if d.get("memory"):
                mem.append(float(d["memory"]) * 1e9)
    intra = [float(l["bandwidth"]) * 1e9
             for l in doc.get("links", [])
             if l.get("type") in ("NVL", "PHB", "ICI")]
    inter = [float(l["bandwidth"]) * 1e9
             for l in doc.get("links", []) if l.get("type") == "NET"]
    dev = DeviceSpec()
    if flops:
        dev = DeviceSpec(flops_bf16=min(flops),
                         hbm_bytes=min(mem) if mem else DeviceSpec().hbm_bytes)
    return ClusterSpec(
        n_devices=n_devices,
        devices_per_host=max(per_host) if per_host else n_devices,
        ici_bw=min(intra) if intra else ClusterSpec().ici_bw,
        dcn_bw=min(inter) if inter else ClusterSpec().dcn_bw,
        device=dev,
    )


def map_processes(candidate: Candidate, devices=None):
    """Order physical devices for the candidate's mesh so the chattiest
    axis sits innermost (reference mapper.py places ranks by link
    bandwidth; on a pod the same goal is axis ordering: mp varies fastest
    over adjacent — intra-host — devices, dp slowest so it can cross
    DCN). Returns an ndarray shaped [pp, dp, sep, mp] of devices."""
    import jax

    devices = list(devices if devices is not None else jax.devices())
    c = candidate
    n = c.dp * c.mp * c.pp * c.sep
    if len(devices) < n:
        raise ValueError(f"candidate needs {n} devices, have {len(devices)}")
    arr = np.empty(n, dtype=object)
    arr[:] = devices[:n]
    # axis order outer->inner: pp, dp, sep, mp (mp adjacency first)
    return arr.reshape(c.pp, c.dp, c.sep, c.mp)


class ProfileTuner:
    """Measure candidate parallelization configs on the real devices and
    keep the fastest (reference: tuner/optimization_tuner.py's
    profile-based trial loop, minus the subprocess farm — one jit per
    candidate in-process)."""

    def __init__(self, model_fn, candidates: Sequence[Candidate],
                 warmup: int = 1, iters: int = 3):
        """model_fn(candidate) -> (step_callable, example_batch_tuple);
        the callable must be ready to run (mesh installed, params placed).
        """
        self.model_fn = model_fn
        self.candidates = list(candidates)
        self.warmup = warmup
        self.iters = iters
        self.records: List[Dict] = []

    def tune(self, verbose: bool = False) -> Candidate:
        best = None
        for cand in self.candidates:
            try:
                step, batch = self.model_fn(cand)
                for _ in range(max(self.warmup, 1)):
                    out = step(*batch)
                float(out)  # sync
                t0 = time.perf_counter()
                for _ in range(self.iters):
                    out = step(*batch)
                    float(out)  # per-step sync: tunnel-safe timing
                dt = (time.perf_counter() - t0) / self.iters
                self.records.append({"candidate": str(cand), "ms": dt * 1e3})
                if verbose:
                    print(f"[tuner] {cand}: {dt * 1e3:.2f} ms/step")
                if best is None or dt < best[0]:
                    best = (dt, cand)
            except Exception as e:  # infeasible candidate: record, move on
                self.records.append({"candidate": str(cand),
                                     "error": repr(e)})
                if verbose:
                    print(f"[tuner] {cand}: failed ({e})")
        if best is None:
            raise RuntimeError(
                f"profile tuner: every candidate failed: {self.records}"
            )
        return best[1]
