"""Cluster description loading, process mapping, and the profile tuner.

Reference analogues:
- cluster.py: Cluster.build_from_file parsing a machines/devices/links
  JSON into a capability graph consumed by the cost model;
- mapper.py: mapping(dist_program, cluster) — place logical ranks onto
  physical devices so the chattiest communicators share the best links;
- tuner/: OptimizationTuner — try candidate strategies, MEASURE, keep the
  best (profile-guided, versus the planner's analytic model).

TPU-native: the capability graph collapses to ClusterSpec (regular pod
topologies); mapping collapses to axis ORDERING over jax.devices() (mp
innermost so TP collectives ride intra-host ICI); the tuner compiles and
times each candidate mesh on the real devices and keeps the fastest —
measurement beats any model when the hardware is in hand.
"""
from __future__ import annotations

import json
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from .planner import Candidate, ClusterSpec, DeviceSpec

__all__ = ["cluster_from_json", "map_processes", "ProfileTuner"]


def cluster_from_json(path: str) -> ClusterSpec:
    """Parse the reference's cluster JSON (machines[].devices[] with
    gflops/memory, links[] with bandwidth) into a ClusterSpec.

    Unknown/missing fields fall back to the v5e defaults; heterogeneous
    clusters take the MINIMUM capability (the straggler sets the pace)."""
    with open(path) as f:
        doc = json.load(f)
    machines = doc.get("machines", [])
    if not machines:
        raise ValueError(f"{path}: no machines in cluster file")
    n_devices = 0
    per_host = []
    flops = []
    mem = []
    for m in machines:
        devs = [d for d in m.get("devices", [])
                if d.get("type", "GPU") not in ("CPU",)]
        per_host.append(len(devs))
        n_devices += len(devs)
        for d in devs:
            # reference stores double-precision gflops; sp_gflops when given
            g = d.get("sp_gflops") or d.get("dp_gflops")
            if g:
                flops.append(float(g) * 1e9)
            if d.get("memory"):
                mem.append(float(d["memory"]) * 1e9)
    intra = [float(l["bandwidth"]) * 1e9
             for l in doc.get("links", [])
             if l.get("type") in ("NVL", "PHB", "ICI")]
    inter = [float(l["bandwidth"]) * 1e9
             for l in doc.get("links", []) if l.get("type") == "NET"]
    dev = DeviceSpec()
    if flops:
        dev = DeviceSpec(flops_bf16=min(flops),
                         hbm_bytes=min(mem) if mem else DeviceSpec().hbm_bytes)
    return ClusterSpec(
        n_devices=n_devices,
        devices_per_host=max(per_host) if per_host else n_devices,
        ici_bw=min(intra) if intra else ClusterSpec().ici_bw,
        dcn_bw=min(inter) if inter else ClusterSpec().dcn_bw,
        device=dev,
    )


def map_processes(candidate: Candidate, devices=None):
    """Order physical devices for the candidate's mesh so the chattiest
    axis sits innermost (reference mapper.py places ranks by link
    bandwidth; on a pod the same goal is axis ordering: mp varies fastest
    over adjacent — intra-host — devices, dp slowest so it can cross
    DCN). Returns an ndarray shaped [pp, dp, sep, mp] of devices."""
    import jax

    devices = list(devices if devices is not None else jax.devices())
    c = candidate
    n = c.dp * c.mp * c.pp * c.sep
    if len(devices) < n:
        raise ValueError(f"candidate needs {n} devices, have {len(devices)}")
    arr = np.empty(n, dtype=object)
    arr[:] = devices[:n]
    # axis order outer->inner: pp, dp, sep, mp (mp adjacency first)
    return arr.reshape(c.pp, c.dp, c.sep, c.mp)


class TrialStateGuard:
    """Host-memory snapshot of model params/buffers + optimizer
    accumulators around profile trials (shared by Engine(tune=True) and
    the fleet auto path — the donation-safety logic must exist ONCE).

    Trial steps DONATE the device buffers and advance optimizer state, so
    device-array references die with the first trial; the snapshot lives
    in host numpy and `restore()` re-uploads it — call it before each
    candidate build and once more in a finally."""

    def __init__(self, model, optimizer):
        import jax as _jax
        import numpy as _np

        self._model = model
        self._opt = optimizer
        self._tensors = [
            (t, _np.asarray(_jax.device_get(t._value)))
            for t in list(model.parameters())
            + [b for _, b in model.named_buffers()]
        ]
        self._acc = {
            pid: {k: _np.asarray(_jax.device_get(v)) for k, v in st.items()}
            for pid, st in getattr(optimizer, "_accumulators", {}).items()
        }
        self._steps = getattr(optimizer, "_step_count", 0)

    def restore(self):
        import jax.numpy as _jnp

        for t, v in self._tensors:
            t._value = _jnp.asarray(v)
        if hasattr(self._opt, "_accumulators"):
            self._opt._accumulators = {
                pid: {k: _jnp.asarray(v) for k, v in st.items()}
                for pid, st in self._acc.items()
            }
            self._opt._step_count = self._steps


def calibration_scale(records, plans):
    """One-probe calibration shared by every measure-then-pick site:
    measured/estimated on the first candidate that both has an analytic
    cost and got measured. Returns (scale, log_line) or (None, None)."""
    measured = {r["candidate"]: r["ms"] for r in records if "ms" in r}
    probe = next(
        (p for p in plans if str(p.candidate) in measured
         and p.cost_ms > 0),
        None,
    )
    if probe is None:
        return None, None
    scale = measured[str(probe.candidate)] / probe.cost_ms
    line = (
        f"[auto-parallel tuner] calibration x{scale:.1f}: "
        + " ".join(f"{p.candidate}~{p.cost_ms * scale:.1f}ms"
                   for p in plans)
    )
    for p in plans:
        p.calibrated_ms = p.cost_ms * scale
    return scale, line


class ProfileTuner:
    """Measure candidate parallelization configs on the real devices and
    keep the fastest (reference: tuner/optimization_tuner.py's
    profile-based trial loop, minus the subprocess farm — one jit per
    candidate in-process)."""

    def __init__(self, model_fn, candidates: Sequence[Candidate],
                 warmup: int = 1, iters: int = 3, interleave: bool = False):
        """model_fn(candidate) -> (step_callable, example_batch_tuple);
        the callable must be ready to run (mesh installed, params placed).

        interleave=True: build every candidate first, then time them in
        round-robin rounds — ambient load drifting across the trial span
        hits all candidates equally instead of whichever ran during the
        bad minute. Requires each candidate to own its params (a SHARED
        model reshared per candidate would be re-placed on every
        cross-candidate call, biasing the timings — keep the sequential
        default there)."""
        self.model_fn = model_fn
        self.candidates = list(candidates)
        self.warmup = warmup
        self.iters = iters
        self.interleave = interleave
        self.records: List[Dict] = []
        self.best_step = None

    def tune(self, verbose: bool = False) -> Candidate:
        self.best_step = None  # the winner's ALREADY-COMPILED step object
        if self.interleave:
            return self._tune_interleaved(verbose)
        best = None  # (dt, cand, step) — losers are dropped immediately so
        # only one trial's executable + placed state is ever held alongside
        # the one being measured (a kept loser could OOM the next build)
        for cand in self.candidates:
            try:
                step, batch = self.model_fn(cand)
                for _ in range(max(self.warmup, 1)):
                    out = step(*batch)
                float(out)  # sync
                # min-of-iters: ambient load only ever slows an iteration,
                # so the minimum is the honest cost (same estimator as
                # bench.py's _best_window)
                dt = float("inf")
                for _ in range(self.iters):
                    t0 = time.perf_counter()
                    out = step(*batch)
                    float(out)  # per-step sync: tunnel-safe timing
                    dt = min(dt, time.perf_counter() - t0)
                self.records.append({"candidate": str(cand), "ms": dt * 1e3})
                if verbose:
                    print(f"[tuner] {cand}: {dt * 1e3:.2f} ms/step")
                if best is None or dt < best[0]:
                    best = (dt, cand, step)
            except Exception as e:  # infeasible candidate: record, move on
                self.records.append({"candidate": str(cand),
                                     "error": repr(e)})
                if verbose:
                    print(f"[tuner] {cand}: failed ({e})")
        if best is None:
            raise RuntimeError(
                f"profile tuner: every candidate failed: {self.records}"
            )
        self.best_step = best[2]
        return best[1]

    def _tune_interleaved(self, verbose: bool) -> Candidate:
        built = []  # [cand, step, batch, min_dt] — failed entries removed
        for cand in self.candidates:
            try:
                step, batch = self.model_fn(cand)
                for _ in range(max(self.warmup, 1)):
                    out = step(*batch)
                float(out)  # sync
                built.append([cand, step, batch, float("inf")])
            except Exception as e:
                self.records.append({"candidate": str(cand),
                                     "error": repr(e)})
                if verbose:
                    print(f"[tuner] {cand}: failed ({e})")
        for _ in range(self.iters):
            for entry in list(built):
                cand, step, batch, _dt = entry
                try:
                    t0 = time.perf_counter()
                    out = step(*batch)
                    float(out)
                    entry[3] = min(entry[3], time.perf_counter() - t0)
                except Exception as e:
                    # steady-state failure (late OOM, async XLA error):
                    # drop this candidate, keep the round-robin going
                    built.remove(entry)
                    self.records.append({"candidate": str(cand),
                                         "error": repr(e)})
                    if verbose:
                        print(f"[tuner] {cand}: failed ({e})")
        built = [e for e in built if e[3] < float("inf")]
        for cand, _s, _b, dt in built:
            self.records.append({"candidate": str(cand), "ms": dt * 1e3})
            if verbose:
                print(f"[tuner] {cand}: {dt * 1e3:.2f} ms/step")
        if not built:
            raise RuntimeError(
                f"profile tuner: every candidate failed: {self.records}"
            )
        best = min(built, key=lambda e: e[3])
        self.best_step = best[1]
        return best[0]
