"""Distributed (sharded, async) checkpointing.

Reference analogue: auto_parallel dist ckpt (dist_saver.py, converter.py for
cross-mesh conversion), fleet.save_persistables, auto_checkpoint
(fluid/incubate/checkpoint/auto_checkpoint.py — epoch-range resume). See
SURVEY.md §5 checkpoint/resume.

TPU-native: orbax-checkpoint handles sharded (per-device) async save/restore
keyed by mesh axes; restoring onto a DIFFERENT mesh re-shards automatically
from the param specs (the converter.py role).
"""
from __future__ import annotations

import os
from typing import Any, Dict, Optional

import jax
import numpy as np

from ..core.dispatch import no_grad
from ..core.tensor import Tensor

try:
    import orbax.checkpoint as ocp

    _HAS_ORBAX = True
except Exception:  # pragma: no cover
    _HAS_ORBAX = False

__all__ = [
    "save_state_dict",
    "load_state_dict",
    "AsyncCheckpointer",
    "TrainingState",
    "restore_training_state",
    "train_epoch_range",
    "train_step_range",
    "training_state",
]

_LATEST = "LATEST"


def _ckpt_io(thunk):
    """Checkpoint IO through the resilience executor: injected faults fire
    here ('checkpoint' site) and transient IO errors retry with backoff."""
    from ..resilience import runtime as _rrt

    return _rrt.execute("checkpoint", thunk)


def _to_arrays(state_dict: Dict[str, Any]):
    return {
        k: (v._value if isinstance(v, Tensor) else v) for k, v in state_dict.items()
    }


def save_state_dict(state_dict: Dict[str, Any], path: str, async_save: bool = False):
    """Sharded save: each host writes only its local shards (orbax).

    Crash-consistent on both backends: orbax commits via its own temp-dir +
    rename protocol; the pickle fallback writes tmp + atomic rename
    (framework.io_utils.save). Transient IO failures retry with backoff."""
    if hasattr(state_dict, "refresh"):
        state_dict.refresh()  # TrainingState: re-snapshot optimizer moments
    if not _HAS_ORBAX:
        from ..framework.io_utils import save as _save

        _ckpt_io(lambda: _save(state_dict, path))
        return None
    ckptr = ocp.StandardCheckpointer()
    arrays = _to_arrays(state_dict)
    path = os.path.abspath(path)
    _ckpt_io(lambda: ckptr.save(path, arrays, force=True))
    if not async_save:
        ckptr.wait_until_finished()
    return ckptr


@no_grad()
def load_state_dict(state_dict: Dict[str, Any], path: str, mesh=None):
    """Restore IN-PLACE into `state_dict`'s tensors, re-sharding each array
    to the destination tensor's current sharding (cross-mesh conversion)."""
    if not _HAS_ORBAX:
        from ..framework.io_utils import load as _load

        loaded = _load(path)
        for k, t in state_dict.items():
            if k in loaded:
                t.set_value(loaded[k])
        return state_dict
    ckptr = ocp.StandardCheckpointer()
    template = {}
    for k, v in state_dict.items():
        val = v._value if isinstance(v, Tensor) else v
        sharding = getattr(val, "sharding", None)
        template[k] = jax.ShapeDtypeStruct(val.shape, val.dtype, sharding=sharding)
    restored = ckptr.restore(os.path.abspath(path), template)
    for k, v in state_dict.items():
        if k in restored:
            if isinstance(v, Tensor):
                v._value = restored[k]
            else:
                state_dict[k] = restored[k]
    return state_dict


class AsyncCheckpointer:
    """Async sharded checkpoint manager with retention (keeps training
    stepping while the previous snapshot flushes — the reference's
    checkpoint_saver.py + HDFS push, minus the filesystem zoo)."""

    def __init__(self, directory: str, max_to_keep: int = 3):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        if _HAS_ORBAX:
            self._mgr = ocp.CheckpointManager(
                self.directory,
                options=ocp.CheckpointManagerOptions(
                    max_to_keep=max_to_keep, enable_async_checkpointing=True
                ),
            )
        else:
            self._mgr = None
        self.max_to_keep = max_to_keep

    # -- crash-consistent commit protocol (fallback backend) ----------------
    # 1. payload → hidden temp file; 2. atomic rename to the numeric name;
    # 3. LATEST pointer updated last (atomic replace). A kill anywhere in
    # the sequence leaves either the previous complete snapshot (pointer
    # untouched) or the new complete one — never a corrupt "latest".
    # Orbax runs its own equivalent temp-dir + rename commit.
    def _write_latest(self, step: int):
        tmp = os.path.join(self.directory, f".{_LATEST}.tmp.{os.getpid()}")
        with open(tmp, "w") as f:
            f.write(str(step))
        os.replace(tmp, os.path.join(self.directory, _LATEST))

    def _read_latest(self) -> Optional[int]:
        try:
            with open(os.path.join(self.directory, _LATEST)) as f:
                return int(f.read().strip())
        except (OSError, ValueError):
            return None

    def _retain(self):
        if self.max_to_keep and self.max_to_keep > 0:
            steps = sorted(
                int(d) for d in os.listdir(self.directory) if d.isdigit()
            )
            for old in steps[: -self.max_to_keep]:
                try:
                    os.remove(os.path.join(self.directory, str(old)))
                except OSError:
                    pass

    def save(self, step: int, state_dict: Dict[str, Any]):
        if hasattr(state_dict, "refresh"):
            state_dict.refresh()  # TrainingState: re-snapshot moments
        if self._mgr is not None:
            arrays = _to_arrays(state_dict)
            _ckpt_io(lambda: self._mgr.save(step, args=ocp.args.StandardSave(arrays)))
            return
        from ..framework.io_utils import save as _save
        from ..resilience import faults as _faults

        def _commit():
            final = os.path.join(self.directory, str(step))
            tmp = os.path.join(self.directory, f".snap.{step}.{os.getpid()}")
            _save(state_dict, tmp)
            # chaos harness kill point: snapshot bytes written but not yet
            # committed — a kill here must leave the previous LATEST intact
            _faults.maybe_kill("checkpoint")
            os.replace(tmp, final)
            self._retain()
            self._write_latest(step)

        _ckpt_io(_commit)

    def restore_latest(self, state_dict: Dict[str, Any]) -> Optional[int]:
        if hasattr(state_dict, "refresh"):
            # TrainingState: materialize missing optimizer accumulators so
            # the restore template covers the saved moment entries
            state_dict.refresh(create=True)
        if self._mgr is not None:
            step = self._mgr.latest_step()
            if step is None:
                return None
            template = {
                k: jax.ShapeDtypeStruct(
                    (v._value if isinstance(v, Tensor) else v).shape,
                    (v._value if isinstance(v, Tensor) else v).dtype,
                    sharding=getattr(v._value if isinstance(v, Tensor) else v, "sharding", None),
                )
                for k, v in state_dict.items()
            }
            restored = self._mgr.restore(step, args=ocp.args.StandardRestore(template))
            with no_grad():
                for k, v in state_dict.items():
                    if k in restored and isinstance(v, Tensor):
                        v._value = restored[k]
            return step
        steps = sorted(int(d) for d in os.listdir(self.directory) if d.isdigit())
        if not steps:
            return None
        from ..framework.io_utils import load as _load

        # prefer the LATEST pointer (committed only after a complete
        # snapshot); fall back through newer→older snapshots, skipping any
        # that fail to load — a kill mid-save never loses the run
        pointed = self._read_latest()
        candidates = sorted(steps, reverse=True)
        if pointed in steps:
            candidates = [pointed] + [s for s in candidates if s != pointed]
        for step in candidates:
            try:
                loaded = _load(os.path.join(self.directory, str(step)))
            except Exception:
                continue  # partial/corrupt snapshot — try the previous one
            with no_grad():
                for k, v in state_dict.items():
                    if k in loaded and isinstance(v, Tensor):
                        v.set_value(loaded[k])
            return step
        return None

    def wait(self):
        if self._mgr is not None:
            self._mgr.wait_until_finished()


def _train_range(count: int, checkpointer, state_dict, save_freq: int,
                 guard, optimizer):
    """Shared restore → yield → boundary-check → periodic-save protocol
    behind train_epoch_range / train_step_range (they differ only in the
    granularity of `count` and the save_freq default)."""
    start = 0
    if checkpointer is not None and state_dict is not None:
        restored = checkpointer.restore_latest(state_dict)
        if restored is not None:
            restore_training_state(state_dict, optimizer=optimizer)
            start = restored + 1
    if guard is not None:
        guard.bind(checkpointer, state_dict)
        guard.install()
    try:
        for i in range(start, count):
            yield i
            if guard is not None:
                guard.step_boundary(i)  # raises Preempted after a signal
            if (checkpointer is not None and state_dict is not None
                    and save_freq and (i + 1) % save_freq == 0):
                checkpointer.save(i, state_dict)
    finally:
        if guard is not None:
            guard.uninstall()
    if checkpointer is not None:
        checkpointer.wait()


def train_epoch_range(max_epoch_num: int, checkpointer: Optional[AsyncCheckpointer] = None,
                      state_dict: Optional[Dict] = None, save_freq: int = 1,
                      guard=None, optimizer=None):
    """reference: auto_checkpoint.py:598 train_epoch_range — a generator
    wrapping the epoch loop that restores the last epoch on (re)start and
    snapshots at each epoch end; pairs with elastic relaunch for resume.

    Pass a `paddle.resilience.PreemptionGuard` as `guard` to make the loop
    preemption-safe: a SIGTERM/SIGINT during an epoch finishes that epoch,
    emergency-saves it, and raises `Preempted` — relaunching resumes at the
    next epoch. When `state_dict` is a `training_state` view (or `optimizer`
    is passed), the optimizer's accumulators are restored too — Adam resumes
    with its real moments, not fresh zeros. For step-granular (≤1 step lost)
    resume use train_step_range."""
    return _train_range(max_epoch_num, checkpointer, state_dict, save_freq,
                        guard, optimizer)


def train_step_range(max_steps: int, checkpointer: Optional[AsyncCheckpointer] = None,
                     state_dict: Optional[Dict] = None, save_freq: int = 0,
                     guard=None, optimizer=None):
    """Step-granular, preemption-safe resume loop (paddle.resilience).

    Restores the latest snapshot on (re)start and yields the remaining step
    indices. With a `PreemptionGuard`, a SIGTERM/SIGINT arriving during a
    step lets that step FINISH, then emergency-saves it and raises
    `Preempted` — a relaunch resumes at the next step, so at most the step
    that was in flight when the process actually died is lost (CheckFreq's
    bound, with frequency-based saves via `save_freq` as the crash
    backstop). Pass `optimizer` to restore its accumulators from the
    snapshot (see `training_state`)."""
    return _train_range(max_steps, checkpointer, state_dict, save_freq,
                        guard, optimizer)


_OPT_PREFIX = "__opt__."


class TrainingState(dict):
    """Live flat checkpoint view over model params + optimizer accumulators.

    Model entries are the LIVE parameter tensors (a restore writes into
    them in place). Optimizer accumulators are REPLACED every step, so the
    view re-snapshots them on `refresh()` — the save/restore paths call it
    automatically (save: fresh moments are packed; restore: `create=True`
    materializes missing accumulators so the snapshot has tensors to land
    in). After a restore, `restore_training_state` pushes the restored
    moment values back into the optimizer."""

    def __init__(self, model, optimizer=None):
        super().__init__()
        self._model = model
        self._optimizer = optimizer
        self.refresh()

    def refresh(self, create: bool = False):
        self.clear()
        self.update(self._model.state_dict())
        opt = self._optimizer
        if opt is not None:
            # keyed by parameter INDEX, not name: auto-generated param names
            # are process-global ("param_7"), so a relaunch's fresh model
            # would never match name-keyed entries
            for i, p in enumerate(opt._param_list()):
                st = opt._accumulators.get(id(p))
                if st is None and create:
                    st = opt._create_state(p)
                    opt._accumulators[id(p)] = st
                for k, v in (st or {}).items():
                    self[f"{_OPT_PREFIX}{i}.{k}"] = (
                        v if isinstance(v, Tensor) else Tensor(v)
                    )
        return self


def training_state(model, optimizer=None) -> TrainingState:
    """Checkpointable state covering model params AND optimizer
    accumulators, for AsyncCheckpointer / save_state_dict / the
    train_step_range resume loop."""
    return TrainingState(model, optimizer)


def restore_training_state(state: Dict[str, Any], optimizer=None):
    """Push the optimizer slice of a restored `training_state` back into
    the optimizer's accumulators (model params restored in place)."""
    if optimizer is None:
        optimizer = getattr(state, "_optimizer", None)
    if optimizer is None:
        return
    for i, p in enumerate(optimizer._param_list()):
        prefix = f"{_OPT_PREFIX}{i}."
        st = {
            k[len(prefix):]: (v._value if isinstance(v, Tensor) else jax.numpy.asarray(np.asarray(v)))
            for k, v in state.items() if k.startswith(prefix)
        }
        if st:
            cur = optimizer._accumulators.get(id(p)) or optimizer._create_state(p)
            cur.update(st)
            optimizer._accumulators[id(p)] = cur
