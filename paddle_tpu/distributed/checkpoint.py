"""Distributed (sharded, async) checkpointing.

Reference analogue: auto_parallel dist ckpt (dist_saver.py, converter.py for
cross-mesh conversion), fleet.save_persistables, auto_checkpoint
(fluid/incubate/checkpoint/auto_checkpoint.py — epoch-range resume). See
SURVEY.md §5 checkpoint/resume.

TPU-native: orbax-checkpoint handles sharded (per-device) async save/restore
keyed by mesh axes; restoring onto a DIFFERENT mesh re-shards automatically
from the param specs (the converter.py role).
"""
from __future__ import annotations

import os
from typing import Any, Dict, Optional

import jax
import numpy as np

from ..core.dispatch import no_grad
from ..core.tensor import Tensor

try:
    import orbax.checkpoint as ocp

    _HAS_ORBAX = True
except Exception:  # pragma: no cover
    _HAS_ORBAX = False

__all__ = ["save_state_dict", "load_state_dict", "AsyncCheckpointer", "train_epoch_range"]


def _to_arrays(state_dict: Dict[str, Any]):
    return {
        k: (v._value if isinstance(v, Tensor) else v) for k, v in state_dict.items()
    }


def save_state_dict(state_dict: Dict[str, Any], path: str, async_save: bool = False):
    """Sharded save: each host writes only its local shards (orbax)."""
    if not _HAS_ORBAX:
        from ..framework.io_utils import save as _save

        _save(state_dict, path)
        return None
    ckptr = ocp.StandardCheckpointer()
    arrays = _to_arrays(state_dict)
    path = os.path.abspath(path)
    ckptr.save(path, arrays, force=True)
    if not async_save:
        ckptr.wait_until_finished()
    return ckptr


@no_grad()
def load_state_dict(state_dict: Dict[str, Any], path: str, mesh=None):
    """Restore IN-PLACE into `state_dict`'s tensors, re-sharding each array
    to the destination tensor's current sharding (cross-mesh conversion)."""
    if not _HAS_ORBAX:
        from ..framework.io_utils import load as _load

        loaded = _load(path)
        for k, t in state_dict.items():
            if k in loaded:
                t.set_value(loaded[k])
        return state_dict
    ckptr = ocp.StandardCheckpointer()
    template = {}
    for k, v in state_dict.items():
        val = v._value if isinstance(v, Tensor) else v
        sharding = getattr(val, "sharding", None)
        template[k] = jax.ShapeDtypeStruct(val.shape, val.dtype, sharding=sharding)
    restored = ckptr.restore(os.path.abspath(path), template)
    for k, v in state_dict.items():
        if k in restored:
            if isinstance(v, Tensor):
                v._value = restored[k]
            else:
                state_dict[k] = restored[k]
    return state_dict


class AsyncCheckpointer:
    """Async sharded checkpoint manager with retention (keeps training
    stepping while the previous snapshot flushes — the reference's
    checkpoint_saver.py + HDFS push, minus the filesystem zoo)."""

    def __init__(self, directory: str, max_to_keep: int = 3):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        if _HAS_ORBAX:
            self._mgr = ocp.CheckpointManager(
                self.directory,
                options=ocp.CheckpointManagerOptions(
                    max_to_keep=max_to_keep, enable_async_checkpointing=True
                ),
            )
        else:
            self._mgr = None
        self.max_to_keep = max_to_keep

    def save(self, step: int, state_dict: Dict[str, Any]):
        arrays = _to_arrays(state_dict)
        if self._mgr is not None:
            self._mgr.save(step, args=ocp.args.StandardSave(arrays))
        else:
            from ..framework.io_utils import save as _save

            _save(state_dict, os.path.join(self.directory, str(step)))

    def restore_latest(self, state_dict: Dict[str, Any]) -> Optional[int]:
        if self._mgr is not None:
            step = self._mgr.latest_step()
            if step is None:
                return None
            template = {
                k: jax.ShapeDtypeStruct(
                    (v._value if isinstance(v, Tensor) else v).shape,
                    (v._value if isinstance(v, Tensor) else v).dtype,
                    sharding=getattr(v._value if isinstance(v, Tensor) else v, "sharding", None),
                )
                for k, v in state_dict.items()
            }
            restored = self._mgr.restore(step, args=ocp.args.StandardRestore(template))
            with no_grad():
                for k, v in state_dict.items():
                    if k in restored and isinstance(v, Tensor):
                        v._value = restored[k]
            return step
        steps = sorted(int(d) for d in os.listdir(self.directory) if d.isdigit())
        if not steps:
            return None
        from ..framework.io_utils import load as _load

        loaded = _load(os.path.join(self.directory, str(steps[-1])))
        with no_grad():
            for k, v in state_dict.items():
                if k in loaded and isinstance(v, Tensor):
                    v.set_value(loaded[k])
        return steps[-1]

    def wait(self):
        if self._mgr is not None:
            self._mgr.wait_until_finished()


def train_epoch_range(max_epoch_num: int, checkpointer: Optional[AsyncCheckpointer] = None,
                      state_dict: Optional[Dict] = None, save_freq: int = 1):
    """reference: auto_checkpoint.py:598 train_epoch_range — a generator
    wrapping the epoch loop that restores the last epoch on (re)start and
    snapshots at each epoch end; pairs with elastic relaunch for resume."""
    start = 0
    if checkpointer is not None and state_dict is not None:
        restored = checkpointer.restore_latest(state_dict)
        if restored is not None:
            start = restored + 1
    for epoch in range(start, max_epoch_num):
        yield epoch
        if checkpointer is not None and state_dict is not None and (epoch + 1) % save_freq == 0:
            checkpointer.save(epoch, state_dict)
    if checkpointer is not None:
        checkpointer.wait()
