"""Distributed (sharded, async) checkpointing.

Reference analogue: auto_parallel dist ckpt (dist_saver.py, converter.py for
cross-mesh conversion), fleet.save_persistables, auto_checkpoint
(fluid/incubate/checkpoint/auto_checkpoint.py — epoch-range resume). See
SURVEY.md §5 checkpoint/resume.

TPU-native: orbax-checkpoint handles sharded (per-device) async save/restore
keyed by mesh axes; restoring onto a DIFFERENT mesh re-shards automatically
from the param specs (the converter.py role).
"""
from __future__ import annotations

import math
import os
import threading
import time
from typing import Any, Dict, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..core import flags
from ..core.dispatch import no_grad
from ..core.tensor import Tensor

try:
    import orbax.checkpoint as ocp

    _HAS_ORBAX = True
except Exception:  # pragma: no cover
    _HAS_ORBAX = False

__all__ = [
    "save_state_dict",
    "load_state_dict",
    "persists_in_flight",
    "AsyncCheckpointer",
    "CadenceTuner",
    "CheckpointCadence",
    "TrainingState",
    "restore_training_state",
    "train_epoch_range",
    "train_step_range",
    "training_state",
]

_LATEST = "LATEST"

# process-wide count of persist phases currently running (sync or on a
# background thread). The perf-regression sentinel reads this: a step slowed
# by an overlapping checkpoint persist is CheckFreq working, not a
# regression, so breaches during a persist are suppressed.
_persists_active = 0
_persists_lock = threading.Lock()


def persists_in_flight() -> int:
    """Number of checkpoint persist phases currently running."""
    return _persists_active


def _ckpt_io(thunk):
    """Checkpoint IO through the resilience executor: injected faults fire
    here ('checkpoint' site) and transient IO errors retry with backoff."""
    from ..resilience import runtime as _rrt

    return _rrt.execute("checkpoint", thunk)


def _counters():
    from ..core import dispatch

    return dispatch._counters


def _counter_add(key: str, n: float):
    """Race-free counter update for the background persist thread (shares
    the reset lock with dispatch.reset_dispatch_counters)."""
    from ..core import dispatch

    dispatch._counter_add(key, n)


def _emit(kind: str, **attrs):
    from ..core import dispatch

    dispatch._emit(kind, site="checkpoint", **attrs)


def _to_arrays(state_dict: Dict[str, Any]):
    return {
        k: (v._value if isinstance(v, Tensor) else v) for k, v in state_dict.items()
    }


# ---------------------------------------------------------------------------
# Snapshot phase (CheckFreq two-phase discipline, phase 1): a cheap
# ON-DEVICE copy of every buffer at the step boundary. The copy must exist
# before the next step runs — under whole-step capture the params and
# optimizer accumulators are DONATED to the next captured program, which
# invalidates the live buffers; a deferred host read would race it. One
# jitted copy program per state structure (jax caches by pytree/avals).
# ---------------------------------------------------------------------------
@jax.jit
def _copy_tree(arrays):
    return jax.tree_util.tree_map(jnp.copy, arrays)


def _device_snapshot(state_dict: Dict[str, Any]) -> Dict[str, Any]:
    """Boundary snapshot: bitwise the state at the moment of the call,
    immune to later in-place donation/mutation of the live buffers."""
    if hasattr(state_dict, "refresh"):
        state_dict.refresh()  # TrainingState: re-snapshot optimizer moments
    from ..core import lazy

    # resolve pending lazy/captured work so the snapshot sees the committed
    # step-boundary values, not a half-flushed segment
    lazy.flush_if_pending("checkpoint_snapshot")
    arrays, other = {}, {}
    for k, v in state_dict.items():
        val = v._value if isinstance(v, Tensor) else v
        if isinstance(val, jax.Array):
            arrays[k] = val
        elif isinstance(val, np.ndarray):
            # host array: plain copy — routing it through the jitted copy
            # would silently downcast int64/float64 under x64-disabled jax
            other[k] = val.copy()
        else:
            other[k] = val
    copied = _copy_tree(arrays) if arrays else {}
    jax.block_until_ready(copied)
    copied = dict(copied)
    copied.update(other)
    return copied


class _SaveJob:
    """One in-flight persist: the boundary snapshot plus completion state."""

    __slots__ = ("step", "snapshot", "tuner", "profiling", "done", "error",
                 "thread")

    def __init__(self, step: int, snapshot: Dict[str, Any], tuner=None,
                 profiling: bool = False):
        self.step = step
        self.snapshot = snapshot
        self.tuner = tuner
        self.profiling = profiling  # first save: costs are one-time, dropped
        self.done = threading.Event()
        self.error: Optional[BaseException] = None
        self.thread: Optional[threading.Thread] = None


def _restore_with_template(restore, template):
    """Run an orbax restore against `template`. Orbax demands an exact
    structure match with the on-disk tree; TrainingState keeps the
    structure stable by always carrying the fixed-shape __data__.blob, so
    the one tolerated mismatch is a PRE-data-state snapshot with no blob —
    retry without it (params/moments restore, iterator state starts
    fresh). Any other failure re-raises the ORIGINAL error; the retry
    never masks a real corruption."""
    try:
        return restore(template)
    except Exception as e:
        if _DATA_KEY not in template or "mismatch" not in str(e).lower():
            raise
        try:
            return restore({k: v for k, v in template.items()
                            if k != _DATA_KEY})
        except Exception:
            raise e


def save_state_dict(state_dict: Dict[str, Any], path: str, async_save: bool = False):
    """Sharded save: each host writes only its local shards (orbax).

    Crash-consistent on both backends: orbax commits via its own temp-dir +
    rename protocol; the pickle fallback writes tmp + atomic rename
    (framework.io_utils.save). Transient IO failures retry with backoff."""
    if hasattr(state_dict, "refresh"):
        state_dict.refresh()  # TrainingState: re-snapshot optimizer moments
    if not _HAS_ORBAX:
        from ..framework.io_utils import save as _save

        _ckpt_io(lambda: _save(state_dict, path))
        return None
    ckptr = ocp.StandardCheckpointer()
    arrays = _to_arrays(state_dict)
    path = os.path.abspath(path)
    _ckpt_io(lambda: ckptr.save(path, arrays, force=True))
    if not async_save:
        ckptr.wait_until_finished()
    return ckptr


@no_grad()
def load_state_dict(state_dict: Dict[str, Any], path: str, mesh=None):
    """Restore IN-PLACE into `state_dict`'s tensors, re-sharding each array
    to the destination tensor's current sharding (cross-mesh conversion)."""
    if not _HAS_ORBAX:
        from ..framework.io_utils import load as _load

        loaded = _load(path)
        for k, t in list(state_dict.items()):
            if k in loaded:
                if isinstance(t, Tensor):
                    t.set_value(loaded[k])
                else:  # host-side entry (e.g. the __data__ iterator blob)
                    state_dict[k] = loaded[k]
        return state_dict
    ckptr = ocp.StandardCheckpointer()
    template = {}
    for k, v in state_dict.items():
        val = v._value if isinstance(v, Tensor) else v
        sharding = getattr(val, "sharding", None)
        template[k] = jax.ShapeDtypeStruct(val.shape, val.dtype, sharding=sharding)
    restored = _restore_with_template(
        lambda t: ckptr.restore(os.path.abspath(path), t), template)
    for k, v in state_dict.items():
        if k in restored:
            if isinstance(v, Tensor):
                v._value = restored[k]
            else:
                state_dict[k] = restored[k]
    return state_dict


class AsyncCheckpointer:
    """Async sharded checkpoint manager with retention (keeps training
    stepping while the previous snapshot flushes — the reference's
    checkpoint_saver.py + HDFS push, minus the filesystem zoo).

    CheckFreq pipeline (FLAGS_ckpt_async, default on): `save()` pays only a
    fast on-device boundary snapshot on the step path; the device→host
    transfer, serialization, and two-phase commit run on a background
    thread overlapping the following steps. The pipeline is single-slot —
    a new save first joins the previous in-flight persist (the stall, if
    any, is counted as checkpoint overhead), so commits land in step order
    and the LATEST pointer can never name a partially-persisted snapshot.
    Set `tuner` to a CadenceTuner to feed it measured snapshot/persist
    costs (save_freq="auto" wiring does this automatically)."""

    def __init__(self, directory: str, max_to_keep: int = 3):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        if _HAS_ORBAX:
            self._mgr = ocp.CheckpointManager(
                self.directory,
                options=ocp.CheckpointManagerOptions(
                    max_to_keep=max_to_keep, enable_async_checkpointing=True
                ),
            )
        else:
            self._mgr = None
        self.max_to_keep = max_to_keep
        self.tuner: Optional["CadenceTuner"] = None
        self._inflight: Optional[_SaveJob] = None
        self._last_error: Optional[BaseException] = None
        # serializes every commit (background persist, sync save, emergency
        # save): concurrent writers must never interleave payload renames
        # with LATEST pointer updates
        self._commit_lock = threading.Lock()

    # -- crash-consistent commit protocol (fallback backend) ----------------
    # 1. payload → hidden temp file; 2. atomic rename to the numeric name;
    # 3. LATEST pointer updated last (atomic replace). A kill anywhere in
    # the sequence leaves either the previous complete snapshot (pointer
    # untouched) or the new complete one — never a corrupt "latest".
    # Orbax runs its own equivalent temp-dir + rename commit.
    def _write_latest(self, step: int):
        # commit order == step order by construction: the single-slot
        # pipeline joins the previous persist before starting the next, and
        # every commit (background, sync, emergency) holds _commit_lock —
        # so an unconditional pointer write can never move backwards within
        # a run, and a REUSED directory's stale pointer is overwritten
        # rather than pinning the old run's snapshot
        tmp = os.path.join(self.directory, f".{_LATEST}.tmp.{os.getpid()}")
        with open(tmp, "w") as f:
            f.write(str(step))
        os.replace(tmp, os.path.join(self.directory, _LATEST))

    def _read_latest(self) -> Optional[int]:
        try:
            with open(os.path.join(self.directory, _LATEST)) as f:
                return int(f.read().strip())
        except (OSError, ValueError):
            return None

    def _retain(self):
        if self.max_to_keep and self.max_to_keep > 0:
            steps = sorted(
                int(d) for d in os.listdir(self.directory) if d.isdigit()
            )
            for old in steps[: -self.max_to_keep]:
                try:
                    os.remove(os.path.join(self.directory, str(old)))
                except OSError:
                    pass

    # -- persist phase (CheckFreq phase 2: transfer + serialize + commit) ---
    def _persist(self, job: _SaveJob):
        global _persists_active
        with _persists_lock:
            _persists_active += 1
        try:
            self._persist_inner(job)
        finally:
            with _persists_lock:
                _persists_active -= 1

    def _persist_inner(self, job: _SaveJob):
        try:
            t0 = time.perf_counter()
            if self._mgr is not None:
                # orbax gets the DEVICE arrays: each host writes only its
                # local shards (gathering to numpy here would break — or
                # silently unshard — multi-host sharded saves); the
                # device→host transfer happens inside orbax's commit, so
                # ckpt_transfer_ms stays 0 on this backend
                t1 = t0

                def _commit_orbax():
                    with self._commit_lock:
                        self._mgr.save(
                            job.step,
                            args=ocp.args.StandardSave(job.snapshot),
                        )
                        self._mgr.wait_until_finished()

                _ckpt_io(_commit_orbax)
            else:
                host = {
                    k: (np.asarray(v) if isinstance(v, jax.Array) else v)
                    for k, v in job.snapshot.items()
                }
                t1 = time.perf_counter()
                _counter_add("ckpt_transfer_ms", (t1 - t0) * 1000.0)
                _emit("ckpt", phase="transfer", step=job.step,
                      ms=round((t1 - t0) * 1000.0, 3))
                from ..framework.io_utils import save as _save
                from ..resilience import faults as _faults

                def _commit():
                    final = os.path.join(self.directory, str(job.step))
                    tmp = os.path.join(
                        self.directory, f".snap.{job.step}.{os.getpid()}"
                    )
                    with self._commit_lock:
                        _save(host, tmp)
                        # chaos harness kill point: snapshot bytes written
                        # but not yet committed — a kill here must leave the
                        # previous LATEST intact
                        _faults.maybe_kill("checkpoint")
                        os.replace(tmp, final)
                        self._retain()
                        self._write_latest(job.step)

                _ckpt_io(_commit)
            t2 = time.perf_counter()
            _counter_add("ckpt_commit_ms", (t2 - t1) * 1000.0)
            _emit("ckpt", phase="commit", step=job.step,
                  ms=round((t2 - t1) * 1000.0, 3))
            if job.tuner is not None:
                job.tuner.observe_persist((t2 - t0) * 1000.0,
                                          profiling=job.profiling)
        except BaseException as e:  # re-raised at the next join/wait
            job.error = e
        finally:
            job.done.set()

    def _join_inflight(self, reraise: bool = True,
                       count_stall: bool = True) -> float:
        """Wait out the in-flight persist; returns the stall in ms. A
        persist error surfaces here (or is parked on `last_error` when the
        caller cannot raise, e.g. restore). `count_stall=False` for drains
        that are not on the step path (wait/restore) — the stall counter
        tracks training-time pipeline stalls only."""
        job = self._inflight
        if job is None:
            return 0.0
        t0 = time.perf_counter()
        job.done.wait()
        if job.thread is not None:
            job.thread.join()
        self._inflight = None
        stall_ms = (time.perf_counter() - t0) * 1000.0
        if count_stall:
            _counters()["ckpt_pipeline_stall_ms"] += stall_ms
            if stall_ms >= 1.0:  # a real wait, not clock noise
                _emit("ckpt", phase="stall", step=job.step,
                      ms=round(stall_ms, 3))
        if job.error is not None:
            self._last_error = job.error
            if reraise:
                raise job.error
        return stall_ms

    @property
    def last_error(self) -> Optional[BaseException]:
        return self._last_error

    def save(self, step: int, state_dict: Dict[str, Any],
             blocking: Optional[bool] = None):
        """Two-phase save: on-device boundary snapshot (step path), then
        persist — in the background when FLAGS_ckpt_async is on and
        `blocking` isn't forced, synchronously otherwise."""
        if blocking is None:
            blocking = not bool(flags.flag("ckpt_async"))
        c = _counters()
        stall_ms = self._join_inflight()  # single-slot pipeline
        t0 = time.perf_counter()
        snapshot = _device_snapshot(state_dict)
        snap_ms = (time.perf_counter() - t0) * 1000.0
        c["ckpt_snapshots"] += 1
        c["ckpt_snapshot_ms"] += snap_ms
        _emit("ckpt", phase="snapshot", step=step, ms=round(snap_ms, 3),
              blocking=bool(blocking))
        tuner = self.tuner
        profiling = tuner is not None and not tuner._profiled
        if tuner is not None:
            # the step path paid the snapshot plus any pipeline stall
            tuner.observe_snapshot(snap_ms, stall_ms)
        job = _SaveJob(step, snapshot, tuner, profiling=profiling)
        if blocking:
            c["ckpt_sync_saves"] += 1
            self._persist(job)
            if job.error is not None:
                self._last_error = job.error
                raise job.error
        else:
            c["ckpt_async_saves"] += 1
            job.thread = threading.Thread(
                target=self._persist, args=(job,), daemon=True,
                name=f"ckpt-persist-{step}",
            )
            self._inflight = job
            job.thread.start()

    def emergency_save(self, step: int, state_dict: Dict[str, Any]):
        """Preemption-path save: join an in-flight persist that already
        covers this boundary instead of redoing it; supersede anything else
        with a synchronous save. Commits stay serialized either way, so the
        LATEST pointer can never name a partially-persisted snapshot."""
        job = self._inflight
        if job is not None and job.step == step:
            try:
                self._join_inflight()
                _counters()["ckpt_emergency_joined_inflight"] += 1
                return
            except Exception:
                pass  # persist failed — fall through to the sync save
        # a stale failure from an EARLIER step's persist must not abort the
        # emergency snapshot — the process is about to exit and this save
        # is the last chance at durability; drain without re-raising
        self._join_inflight(reraise=False, count_stall=False)
        self.save(step, state_dict, blocking=True)

    def restore_latest(self, state_dict: Dict[str, Any]) -> Optional[int]:
        # an in-flight persist may still be writing the newest snapshot;
        # join it first (its failure must not fail the restore — the disk
        # candidates below are the source of truth)
        self._join_inflight(reraise=False, count_stall=False)
        if hasattr(state_dict, "refresh"):
            # TrainingState: materialize missing optimizer accumulators so
            # the restore template covers the saved moment entries
            state_dict.refresh(create=True)
        if self._mgr is not None:
            step = self._mgr.latest_step()
            if step is None:
                return None
            template = {
                k: jax.ShapeDtypeStruct(
                    (v._value if isinstance(v, Tensor) else v).shape,
                    (v._value if isinstance(v, Tensor) else v).dtype,
                    sharding=getattr(v._value if isinstance(v, Tensor) else v, "sharding", None),
                )
                for k, v in state_dict.items()
            }
            restored = _restore_with_template(
                lambda t: self._mgr.restore(
                    step, args=ocp.args.StandardRestore(t)), template)
            with no_grad():
                for k, v in list(state_dict.items()):
                    if k not in restored:
                        continue
                    if isinstance(v, Tensor):
                        v._value = restored[k]
                    else:  # host-side entry (the __data__ iterator blob)
                        state_dict[k] = restored[k]
            return step
        steps = sorted(int(d) for d in os.listdir(self.directory) if d.isdigit())
        if not steps:
            return None
        from ..framework.io_utils import load as _load

        # prefer the LATEST pointer (committed only after a complete
        # snapshot); fall back through newer→older snapshots, skipping any
        # that fail to load — a kill mid-save never loses the run
        pointed = self._read_latest()
        candidates = sorted(steps, reverse=True)
        if pointed in steps:
            candidates = [pointed] + [s for s in candidates if s != pointed]
        for step in candidates:
            try:
                loaded = _load(os.path.join(self.directory, str(step)))
            except Exception:
                continue  # partial/corrupt snapshot — try the previous one
            with no_grad():
                for k, v in list(state_dict.items()):
                    if k not in loaded:
                        continue
                    if isinstance(v, Tensor):
                        v.set_value(loaded[k])
                    else:  # host-side entry (the __data__ iterator blob)
                        state_dict[k] = loaded[k]
            return step
        return None

    def wait(self):
        """Block until every issued save is durably committed; re-raises a
        background persist failure."""
        self._join_inflight(count_stall=False)
        if self._mgr is not None:
            self._mgr.wait_until_finished()


# ---------------------------------------------------------------------------
# CheckFreq auto-tuned cadence: pick save_freq so measured checkpoint
# overhead stays under the FLAGS_ckpt_overhead_pct budget.
#
# Only the snapshot (plus any pipeline stall) runs on the step path, so per
# checkpoint the training loop pays `snapshot_ms`; amortized over
# `save_freq` steps of `step_ms` each, overhead = snapshot_ms /
# (save_freq * step_ms). Solving for the budget:
#
#     save_freq >= snapshot_ms / (budget_frac * step_ms)
#
# A second constraint keeps the pipeline stall-free: the background persist
# of one snapshot must finish before the next save joins it, i.e.
# save_freq >= persist_ms / step_ms. The tuner takes the max of both,
# clamped to [1, FLAGS_ckpt_cadence_max], and re-tunes when the step-time
# EMA drifts more than FLAGS_ckpt_retune_pct from its value at the last
# tune (e.g. a degradation-ladder demotion changed steady-state step time).
#
# Both constraints carry noise headroom: the EMAs predict MEAN costs, so a
# cadence that lands exactly on a constraint in expectation violates it
# whenever a GC pause stretches one snapshot (overhead past the budget) or
# a few steps run faster than their EMA (the persist no longer fits and
# the next save stalls joining it). _BUDGET_HEADROOM tunes to 80% of the
# budget; _PIPELINE_HEADROOM schedules saves 1.25x the persist/step ratio
# apart. Together they keep the REALIZED overhead (what the acceptance
# gate measures) under the configured budget.
# ---------------------------------------------------------------------------
_BUDGET_HEADROOM = 0.8
_PIPELINE_HEADROOM = 1.25
class CadenceTuner:
    """Measures steady-state step time + checkpoint costs and auto-tunes
    the save frequency against an overhead budget (CheckFreq, FAST '21)."""

    def __init__(self, overhead_pct: Optional[float] = None,
                 warmup_steps: int = 3, ema_alpha: float = 0.25):
        from ..profiler import StepTimer

        self.overhead_pct = (
            float(overhead_pct) if overhead_pct is not None
            else float(flags.flag("ckpt_overhead_pct"))
        )
        self.warmup_steps = int(warmup_steps)
        # ema_alpha governs the per-step time EMA; the sparser snapshot /
        # persist cost EMAs use a fixed 0.5 per-save weight (see
        # observe_snapshot)
        self.timer = StepTimer(alpha=ema_alpha)
        self.snapshot_ms: Optional[float] = None  # EMA of step-path cost
        self.persist_ms: Optional[float] = None   # EMA of background persist
        self.save_freq: Optional[int] = None
        self.retunes = 0
        self._since_save = 0
        self._overhead_ms = 0.0
        self._profiled = False  # first save = CheckFreq's profiling phase
        self._lock = threading.Lock()  # persist times arrive off-thread

    # -- observations -------------------------------------------------------
    def observe_step(self, dt_s: float):
        with self._lock:
            self.timer.observe(dt_s)
            if (self.save_freq is not None and self.timer.drift_pct()
                    > float(flags.flag("ckpt_retune_pct"))):
                self._retune(drift=True)

    def observe_snapshot(self, snap_ms: float, stall_ms: float = 0.0):
        """Step-path cost of one save. `snap_ms` (the intrinsic device
        snapshot) feeds the cadence arithmetic; the pipeline stall only
        counts as realized overhead — the persist-fits-between-saves
        constraint is what eliminates it. The first save is the profiling
        measurement: it pays the copy-program jit compile and backend
        setup, one-time costs that would seed the EMA orders of magnitude
        too high (and the cadence correspondingly too long) — it is
        dropped entirely; the SECOND save, with warm caches, seeds the
        steady-state costs. EMA weight is 0.5 per save: cost observations
        are sparse (one per cadence interval), so they adapt fast."""
        with self._lock:
            if not self._profiled:
                self._profiled = True  # profiling save: costs discarded
                return
            self._overhead_ms += snap_ms + stall_ms
            self.snapshot_ms = (
                snap_ms if self.snapshot_ms is None
                else self.snapshot_ms + 0.5 * (snap_ms - self.snapshot_ms)
            )
            self._retune()

    def observe_persist(self, ms: float, profiling: bool = False):
        """Background transfer+serialize+commit duration (off-thread).
        `profiling=True` marks the first save's persist (backend init,
        one-time) — discarded like its snapshot."""
        if profiling:
            return
        with self._lock:
            self.persist_ms = (
                ms if self.persist_ms is None
                else self.persist_ms + 0.5 * (ms - self.persist_ms)
            )
            self._retune()

    # -- policy -------------------------------------------------------------
    def _retune(self, drift: bool = False):
        step_ms = self.timer.ema_ms
        # both costs must be measured before a frequency exists: tuning
        # from the snapshot alone would schedule the next save before the
        # (unknown, possibly much longer) persist can drain — a guaranteed
        # pipeline stall on the step path
        if not step_ms or self.snapshot_ms is None or self.persist_ms is None:
            return
        budget_frac = max(self.overhead_pct, 1e-6) / 100.0 * _BUDGET_HEADROOM
        freq = math.ceil(self.snapshot_ms / (budget_frac * step_ms))
        if self.persist_ms:
            freq = max(freq, math.ceil(
                self.persist_ms * _PIPELINE_HEADROOM / step_ms))
        freq = max(1, min(freq, int(flags.flag("ckpt_cadence_max"))))
        # `retunes` counts step-time-drift re-tunes (the ladder-demotion
        # signal), not routine cost-EMA refinement between adjacent freqs.
        # _retune also runs on the background persist thread
        # (observe_persist), so these counter writes take the locked path
        if drift and freq != self.save_freq:
            self.retunes += 1
            _counter_add("ckpt_cadence_retunes", 1)
        self.save_freq = freq
        self.timer.mark()
        from ..core import dispatch as _dispatch

        _dispatch._counter_set("ckpt_auto_save_freq", freq)

    def should_save(self) -> bool:
        """Call once per step boundary (after observe_step)."""
        with self._lock:
            self._since_save += 1
            if self.save_freq is None:
                # bootstrap: one early save measures snapshot/persist cost;
                # until then there is nothing to tune against
                if self.timer.count >= self.warmup_steps:
                    self._since_save = 0
                    return True
                return False
            if self._since_save >= self.save_freq:
                self._since_save = 0
                return True
            return False

    def measured_overhead_pct(self) -> float:
        """Realized step-path checkpoint overhead vs total compute."""
        with self._lock:
            if not self.timer.total_ms:
                return 0.0
            return self._overhead_ms / self.timer.total_ms * 100.0

    def state(self) -> Dict[str, Any]:
        return {
            "budget_pct": self.overhead_pct,
            "step_time_ms": round(self.timer.ema_ms or 0.0, 3),
            "snapshot_ms": round(self.snapshot_ms or 0.0, 3),
            "persist_ms": round(self.persist_ms or 0.0, 3),
            "save_freq": self.save_freq,
            "retunes": self.retunes,
            "measured_overhead_pct": round(self.measured_overhead_pct(), 3),
        }


class CheckpointCadence:
    """Boundary-save policy shared by train_step_range / train_epoch_range,
    hapi `Model.fit` and the `ModelCheckpoint` callback: a fixed integer
    `save_freq` (0 = never), or `"auto"` for CheckFreq cadence tuning under
    the FLAGS_ckpt_overhead_pct budget."""

    def __init__(self, checkpointer, state_dict,
                 save_freq: Union[int, str, None]):
        self.checkpointer = checkpointer
        self.state_dict = state_dict
        self.enabled = checkpointer is not None and state_dict is not None
        self.tuner: Optional[CadenceTuner] = None
        if isinstance(save_freq, str):
            if save_freq != "auto":
                raise ValueError(
                    f"save_freq must be an int or 'auto', got {save_freq!r}"
                )
            self.save_freq: Union[int, str] = "auto"
            if self.enabled:
                self.tuner = CadenceTuner()
                checkpointer.tuner = self.tuner
        else:
            self.save_freq = int(save_freq or 0)

    def boundary(self, index: int, dt_s: float) -> bool:
        """Step/epoch-boundary tick: feeds the tuner and fires the save
        when the cadence says so. Returns True when a save was issued."""
        if not self.enabled:
            return False
        if self.tuner is not None:
            self.tuner.observe_step(dt_s)
            if not self.tuner.should_save():
                return False
            inflight = getattr(self.checkpointer, "_inflight", None)
            if (self.tuner.save_freq is None and inflight is not None
                    and not inflight.done.is_set()):
                # bootstrap: the profiling save's persist is still
                # flushing — issuing the seeding save now would stall the
                # step path joining it and poison the overhead account;
                # wait for an idle pipeline (the seeding costs must be
                # steady-state ones)
                return False
        elif not (self.save_freq and (index + 1) % self.save_freq == 0):
            return False
        self.checkpointer.save(index, self.state_dict)
        return True


def _train_range(count: int, checkpointer, state_dict, save_freq,
                 guard, optimizer, data=None):
    """Shared restore → yield → boundary-check → cadenced-save protocol
    behind train_epoch_range / train_step_range (they differ only in the
    granularity of `count` and the save_freq default)."""
    if (data is not None and hasattr(state_dict, "refresh")
            and getattr(state_dict, "_data", None) is None):
        # late-attach the data iterator so its epoch/cursor/RNG ride every
        # snapshot (and the restore below pushes them back)
        state_dict._data = data
        state_dict.refresh()
    cadence = CheckpointCadence(checkpointer, state_dict, save_freq)
    start = 0
    if checkpointer is not None and state_dict is not None:
        restored = checkpointer.restore_latest(state_dict)
        if restored is not None:
            restore_training_state(state_dict, optimizer=optimizer,
                                   data=data)
            start = restored + 1
    if guard is not None:
        guard.bind(checkpointer, state_dict)
        guard.install()
    try:
        for i in range(start, count):
            t0 = time.perf_counter()
            yield i
            dt = time.perf_counter() - t0
            if guard is not None:
                guard.step_boundary(i)  # raises Preempted after a signal
            cadence.boundary(i, dt)
        if checkpointer is not None:
            checkpointer.wait()  # normal path: surface persist failures
    finally:
        if guard is not None:
            guard.uninstall()
        # the loop is over — no more step heartbeats will arrive, which is
        # indistinguishable from a stall; stand the TRAIN source down so a
        # cleanly finished run never dumps a spurious stall postmortem (a
        # co-resident serving engine's heartbeat stays armed)
        try:
            from ..profiler import trace as _trace

            _trace.watchdog_disarm("train")
        except Exception:
            pass
        if checkpointer is not None:
            # break/exception path: the last async save still runs on a
            # daemon thread — drain it so the commit lands before the
            # consumer moves on (swallow: a persist error must not mask
            # the propagating exception / GeneratorExit)
            try:
                checkpointer.wait()
            except Exception:
                pass


def train_epoch_range(max_epoch_num: int, checkpointer: Optional[AsyncCheckpointer] = None,
                      state_dict: Optional[Dict] = None,
                      save_freq: Union[int, str] = 1,
                      guard=None, optimizer=None, data=None):
    """reference: auto_checkpoint.py:598 train_epoch_range — a generator
    wrapping the epoch loop that restores the last epoch on (re)start and
    snapshots at each epoch end; pairs with elastic relaunch for resume.

    Pass a `paddle.resilience.PreemptionGuard` as `guard` to make the loop
    preemption-safe: a SIGTERM/SIGINT during an epoch finishes that epoch,
    emergency-saves it, and raises `Preempted` — relaunching resumes at the
    next epoch. When `state_dict` is a `training_state` view (or `optimizer`
    is passed), the optimizer's accumulators are restored too — Adam resumes
    with its real moments, not fresh zeros. Pass `data=` (a sampler or
    DataLoader with state_dict/load_state_dict) to checkpoint the data
    iterator alongside: a resumed run continues the sample stream where
    the last commit cut it instead of re-reading the epoch from the top.
    For step-granular (≤1 step lost) resume use train_step_range."""
    return _train_range(max_epoch_num, checkpointer, state_dict, save_freq,
                        guard, optimizer, data=data)


def train_step_range(max_steps: int, checkpointer: Optional[AsyncCheckpointer] = None,
                     state_dict: Optional[Dict] = None,
                     save_freq: Union[int, str] = 0,
                     guard=None, optimizer=None, data=None):
    """Step-granular, preemption-safe resume loop (paddle.resilience).

    Restores the latest snapshot on (re)start and yields the remaining step
    indices. With a `PreemptionGuard`, a SIGTERM/SIGINT arriving during a
    step lets that step FINISH, then emergency-saves it and raises
    `Preempted` — a relaunch resumes at the next step, so at most the step
    that was in flight when the process actually died is lost (CheckFreq's
    bound, with frequency-based saves via `save_freq` as the crash
    backstop). `save_freq="auto"` turns on CheckFreq cadence tuning: a
    CadenceTuner measures steady-state step time and the per-save
    snapshot/persist cost, then picks the frequency that keeps measured
    checkpoint overhead under FLAGS_ckpt_overhead_pct, re-tuning when step
    time drifts. Pass `optimizer` to restore its accumulators from the
    snapshot (see `training_state`), and `data=` (sampler / DataLoader
    with state_dict) to checkpoint the data-iterator state with them —
    resume then consumes each sample exactly once."""
    return _train_range(max_steps, checkpointer, state_dict, save_freq,
                        guard, optimizer, data=data)


_OPT_PREFIX = "__opt__."
_DATA_KEY = "__data__.blob"
# fixed-size blob: orbax restore templates are built from the CURRENT
# entry shapes, so the serialized iterator state must have a stable shape
# across save and restore — length-prefixed pickle in a zero-padded buffer
_DATA_BLOB_BYTES = 8192


def _pack_data_state(doc: Dict[str, Any]) -> np.ndarray:
    import pickle
    import struct

    payload = pickle.dumps(doc, protocol=2)
    if len(payload) + 8 > _DATA_BLOB_BYTES:
        raise ValueError(
            f"data-iterator state is {len(payload)} bytes — does not fit "
            f"the {_DATA_BLOB_BYTES}-byte checkpoint blob (keep sampler "
            "state to epoch/cursor/RNG scalars, not data)")
    buf = np.zeros(_DATA_BLOB_BYTES, dtype=np.uint8)
    buf[:8] = np.frombuffer(struct.pack("<q", len(payload)), dtype=np.uint8)
    buf[8:8 + len(payload)] = np.frombuffer(payload, dtype=np.uint8)
    return buf


def _unpack_data_state(arr) -> Optional[Dict[str, Any]]:
    import pickle
    import struct

    raw = np.asarray(arr, dtype=np.uint8).tobytes()
    (n,) = struct.unpack("<q", raw[:8])
    if n <= 0:
        return None  # empty blob: the snapshot came from a data-less run
    return pickle.loads(raw[8:8 + n])


class TrainingState(dict):
    """Live flat checkpoint view over model params + optimizer accumulators.

    Model entries are the LIVE parameter tensors (a restore writes into
    them in place). Optimizer accumulators are REPLACED every step, so the
    view re-snapshots them on `refresh()` — the save/restore paths call it
    automatically (save: fresh moments are packed; restore: `create=True`
    materializes missing accumulators so the snapshot has tensors to land
    in). After a restore, `restore_training_state` pushes the restored
    moment values back into the optimizer.

    `data` adds the DATA-ITERATOR state to the same two-phase commit: any
    object with `state_dict()`/`load_state_dict()` (GlobalStepSampler,
    DistributedBatchSampler, DataLoader) has its epoch/cursor/RNG packed
    as a fixed-size `__data__.blob` entry at every refresh, so a resumed
    `train_step_range` continues the sample stream exactly where the
    committed boundary cut it — each sample consumed exactly once, no
    replay from the top of the epoch."""

    def __init__(self, model, optimizer=None, data=None):
        super().__init__()
        self._model = model
        self._optimizer = optimizer
        self._data = data
        self.refresh()

    def refresh(self, create: bool = False):
        self.clear()
        self.update(self._model.state_dict())
        opt = self._optimizer
        if opt is not None:
            # commit point: a compiled step's stacked moments and the host-
            # offload scheduler's parked groups both write back through this
            # hook before the snapshot reads a single accumulator — restore
            # is exact no matter where a moment physically lived
            sync = getattr(opt, "_lazy_state_sync", None)
            if sync is not None:
                sync()
            # keyed by parameter INDEX, not name: auto-generated param names
            # are process-global ("param_7"), so a relaunch's fresh model
            # would never match name-keyed entries
            for i, p in enumerate(opt._param_list()):
                st = opt._accumulators.get(id(p))
                if st is None and create:
                    st = opt._create_state(p)
                    opt._accumulators[id(p)] = st
                for k, v in (st or {}).items():
                    self[f"{_OPT_PREFIX}{i}.{k}"] = (
                        v if isinstance(v, Tensor) else Tensor(v)
                    )
        if self._data is not None and hasattr(self._data, "state_dict"):
            self[_DATA_KEY] = _pack_data_state(self._data.state_dict())
        else:
            # stable snapshot structure: data-less states carry an EMPTY
            # (all-zeros) blob so orbax's exact-structure restore matches
            # between data= and data-less runs in both directions
            self[_DATA_KEY] = np.zeros(_DATA_BLOB_BYTES, dtype=np.uint8)
        return self


def training_state(model, optimizer=None, data=None) -> TrainingState:
    """Checkpointable state covering model params, optimizer accumulators
    AND (with `data=`) the data-iterator state, for AsyncCheckpointer /
    save_state_dict / the train_step_range resume loop."""
    return TrainingState(model, optimizer, data=data)


def restore_training_state(state: Dict[str, Any], optimizer=None,
                           data=None):
    """Push the optimizer slice of a restored `training_state` back into
    the optimizer's accumulators (model params restored in place), and the
    `__data__.blob` iterator state back into the sampler/loader."""
    if optimizer is None:
        optimizer = getattr(state, "_optimizer", None)
    if optimizer is not None:
        for i, p in enumerate(optimizer._param_list()):
            prefix = f"{_OPT_PREFIX}{i}."
            st = {
                k[len(prefix):]: (v._value if isinstance(v, Tensor) else jax.numpy.asarray(np.asarray(v)))
                for k, v in state.items() if k.startswith(prefix)
            }
            if st:
                cur = optimizer._accumulators.get(id(p)) or optimizer._create_state(p)
                cur.update(st)
                optimizer._accumulators[id(p)] = cur
    if data is None:
        data = getattr(state, "_data", None)
    if data is not None and _DATA_KEY in state and hasattr(
            data, "load_state_dict"):
        blob = state[_DATA_KEY]
        blob = blob._value if isinstance(blob, Tensor) else blob
        doc = _unpack_data_state(blob)
        if doc is not None:
            data.load_state_dict(doc)
