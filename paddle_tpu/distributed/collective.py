"""Collective communication API.

Reference analogue: python/paddle/distributed/collective.py (new_group:314,
all_reduce:580, all_gather:798, alltoall:1696, ...) over ProcessGroupNCCL
(paddle/fluid/distributed/collective/ProcessGroup.h:53) and the 143 graph
collective ops (paddle/fluid/operators/collective/).

TPU-native semantics: a Group names a mesh axis. Inside a compiled/sharded
region (shard_map / pjit trace) these functions lower to the XLA HLO
collectives (psum/all_gather/ppermute/all_to_all) over that axis — executed
on ICI with replica_groups derived from the mesh, replacing NCCL rings.
Called eagerly in a single-process (single-controller) context they operate
on the global array view: all_reduce of an already-global value is the
identity, matching the reference's semantics where the eager tensor holds
the local shard and the collective materializes the group result.
"""
from __future__ import annotations

import threading
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply
from ..core.tensor import Tensor

__all__ = [
    "ReduceOp",
    "Group",
    "new_group",
    "get_group",
    "is_initialized",
    "destroy_process_group",
    "all_reduce",
    "all_gather",
    "all_gather_object",
    "broadcast",
    "reduce",
    "scatter",
    "reduce_scatter",
    "alltoall",
    "alltoall_single",
    "ppermute",
    "shift",
    "send",
    "recv",
    "isend",
    "irecv",
    "barrier",
    "wait",
]


class ReduceOp:
    SUM = 0
    MAX = 1
    MIN = 2
    PROD = 3
    AVG = 4


class Group:
    """A communication group = a set of ranks + (on TPU) a mesh axis name."""

    _next_id = [0]

    def __init__(self, ranks: List[int], axis_name: Optional[str] = None, pg=None):
        self.ranks = list(ranks)
        self.nranks = len(ranks)
        self.axis_name = axis_name
        self.id = Group._next_id[0]
        Group._next_id[0] += 1

    @property
    def rank(self):
        from . import get_rank

        r = get_rank()
        return self.ranks.index(r) if r in self.ranks else -1

    @property
    def world_size(self):
        return self.nranks

    def get_group_rank(self, rank):
        return self.ranks.index(rank) if rank in self.ranks else -1

    @property
    def process_group(self):
        return self

    def __repr__(self):
        return f"Group(id={self.id}, nranks={self.nranks}, axis={self.axis_name})"


_groups = {}
_default_group: Optional[Group] = None


def _ensure_default() -> Group:
    global _default_group
    if _default_group is None:
        from . import get_world_size

        _default_group = Group(list(range(get_world_size())), axis_name=None)
        _groups[0] = _default_group
    return _default_group


def get_group(gid: int = 0) -> Group:
    _ensure_default()
    return _groups.get(gid)


def new_group(ranks=None, backend=None, timeout=None) -> Group:
    """reference: collective.py:314 new_group — builds a comm ring; here a
    rank-set handle (a mesh axis when created by the topology layer)."""
    from . import get_world_size

    g = Group(list(ranks) if ranks is not None else list(range(get_world_size())))
    _groups[g.id] = g
    return g


def is_initialized() -> bool:
    return _default_group is not None


def destroy_process_group(group=None):
    global _default_group
    if group is None:
        _groups.clear()
        _default_group = None


def _is_traced(val) -> bool:
    return isinstance(val, jax.core.Tracer)


def _axis(group: Optional[Group]):
    g = group or _ensure_default()
    return g.axis_name


def _group_size(group: Optional[Group]):
    g = group or _ensure_default()
    return g.nranks


# ---------------------------------------------------------------------------
# collectives
# ---------------------------------------------------------------------------
def all_reduce(tensor: Tensor, op=ReduceOp.SUM, group: Optional[Group] = None,
               sync_op=True):
    """reference: collective.py:580 → c_allreduce_{sum,max,min,prod}.
    In-place on `tensor` (paddle semantics); returns the tensor."""
    val = tensor._value
    axis = _axis(group)
    if _is_traced(val) and axis is not None:
        if op == ReduceOp.SUM:
            out = jax.lax.psum(val, axis)
        elif op == ReduceOp.MAX:
            out = jax.lax.pmax(val, axis)
        elif op == ReduceOp.MIN:
            out = jax.lax.pmin(val, axis)
        elif op == ReduceOp.AVG:
            out = jax.lax.pmean(val, axis)
        else:
            raise NotImplementedError("PROD allreduce inside trace")
        tensor._value = out
        return tensor
    if _group_size(group) == 1 or axis is None:
        return tensor
    raise RuntimeError(
        "eager cross-rank all_reduce outside a compiled region requires a "
        "multi-process launch (paddle.distributed.launch); inside "
        "shard_map/pjit it lowers to an XLA psum"
    )


def all_gather(tensor_list, tensor: Tensor, group: Optional[Group] = None,
               sync_op=True):
    """reference: collective.py:798 → c_allgather."""
    val = tensor._value
    axis = _axis(group)
    if _is_traced(val) and axis is not None:
        out = jax.lax.all_gather(val, axis)  # [group, ...]
        n = _group_size(group)
        if isinstance(tensor_list, list):
            tensor_list.extend(Tensor(out[i], stop_gradient=True) for i in range(n))
        return Tensor(out, stop_gradient=True)
    n = _group_size(group)
    if n == 1 or axis is None:
        if isinstance(tensor_list, list):
            tensor_list.append(tensor.clone())
        return tensor
    raise RuntimeError("eager all_gather requires a compiled region or 1 rank")


def all_gather_object(object_list, obj, group=None):
    object_list.append(obj)


def broadcast(tensor: Tensor, src: int = 0, group: Optional[Group] = None,
              sync_op=True):
    """reference: collective.py:494 → c_broadcast. In a shard_map trace the
    per-rank values may genuinely differ, so broadcast is mask-and-psum
    (ppermute cannot express one-to-all: duplicate sources are invalid).
    Eager single-controller global values are already consistent → no-op."""
    val = tensor._value
    axis = _axis(group)
    if _is_traced(val) and axis is not None:
        # where (not multiply-by-mask): inf/nan on non-source ranks is exactly
        # the garbage broadcast must overwrite, and 0*inf would poison psum
        masked = jnp.where(jax.lax.axis_index(axis) == src, val, jnp.zeros_like(val))
        tensor._value = jax.lax.psum(masked, axis)
        return tensor
    return tensor


def reduce(tensor: Tensor, dst: int = 0, op=ReduceOp.SUM,
           group: Optional[Group] = None, sync_op=True):
    return all_reduce(tensor, op, group)


def scatter(tensor: Tensor, tensor_list=None, src: int = 0,
            group: Optional[Group] = None, sync_op=True):
    """reference: collective.py:895 — rank takes its slice."""
    axis = _axis(group)
    val = tensor._value
    if _is_traced(val) and axis is not None and tensor_list is not None:
        stacked = jnp.stack([t._value if isinstance(t, Tensor) else t for t in tensor_list])
        idx = jax.lax.axis_index(axis)
        tensor._value = jnp.take(stacked, idx, axis=0)
        return tensor
    if _group_size(group) == 1:
        if tensor_list:
            tensor.set_value(tensor_list[0])
        return tensor
    raise RuntimeError("eager scatter requires a compiled region or 1 rank")


def reduce_scatter(tensor: Tensor, tensor_or_tensor_list,
                   op=ReduceOp.SUM, group: Optional[Group] = None, sync_op=True):
    """reference: c_reducescatter op."""
    axis = _axis(group)
    inp = tensor_or_tensor_list
    if isinstance(inp, list):
        val = jnp.concatenate(
            [t._value if isinstance(t, Tensor) else t for t in inp], axis=0
        )
    else:
        val = inp._value if isinstance(inp, Tensor) else inp
    if _is_traced(val) and axis is not None:
        out = jax.lax.psum_scatter(val, axis, scatter_dimension=0, tiled=True)
        tensor._value = out
        return tensor
    if _group_size(group) == 1:
        tensor.set_value(val)
        return tensor
    raise RuntimeError("eager reduce_scatter requires a compiled region or 1 rank")


def alltoall(in_tensor_list, out_tensor_list=None, group: Optional[Group] = None,
             sync_op=True):
    """reference: collective.py:1696 → alltoall op; the MoE dispatch
    primitive (global_scatter/global_gather)."""
    axis = _axis(group)
    if isinstance(in_tensor_list, list):
        val = jnp.stack([t._value if isinstance(t, Tensor) else t for t in in_tensor_list])
    else:
        val = in_tensor_list._value
    if _is_traced(val) and axis is not None:
        out = jax.lax.all_to_all(val, axis, split_axis=0, concat_axis=0, tiled=False)
        res = [Tensor(out[i], stop_gradient=True) for i in range(out.shape[0])]
        if isinstance(out_tensor_list, list):
            out_tensor_list.extend(res)
        return res
    if _group_size(group) == 1:
        res = [Tensor(val[i], stop_gradient=True) for i in range(val.shape[0])]
        if isinstance(out_tensor_list, list):
            out_tensor_list.extend(res)
        return res
    raise RuntimeError("eager alltoall requires a compiled region or 1 rank")


def alltoall_single(in_tensor, out_tensor=None, in_split_sizes=None,
                    out_split_sizes=None, group: Optional[Group] = None, sync_op=True):
    axis = _axis(group)
    val = in_tensor._value if isinstance(in_tensor, Tensor) else in_tensor
    if _is_traced(val) and axis is not None:
        out = jax.lax.all_to_all(val, axis, split_axis=0, concat_axis=0, tiled=True)
        if isinstance(out_tensor, Tensor):
            out_tensor._value = out
            return out_tensor
        return Tensor(out, stop_gradient=True)
    if _group_size(group) == 1:
        if isinstance(out_tensor, Tensor):
            out_tensor.set_value(val)
            return out_tensor
        return Tensor(val, stop_gradient=True)
    raise RuntimeError("eager alltoall_single requires a compiled region or 1 rank")


def ppermute(tensor: Tensor, perm, group: Optional[Group] = None):
    """Raw collective-permute over the group's mesh axis. `perm` is a list of
    (src, dst) pairs; sources and destinations must each be distinct (XLA
    CollectivePermute contract). Ranks not named as a destination receive
    zeros. This is the TPU p2p primitive the pipeline schedule is built on
    (reference send_v2/recv_v2 ops → paddle/fluid/operators/collective/)."""
    axis = _axis(group)
    val = tensor._value if isinstance(tensor, Tensor) else tensor
    if _is_traced(val) and axis is not None:
        return Tensor(jax.lax.ppermute(val, axis, perm), stop_gradient=True)
    if _group_size(group) == 1:
        # match traced semantics: rank 0 receives its value only when (0, 0)
        # is in the perm; otherwise it was not a destination → zeros
        if (0, 0) in [tuple(p) for p in perm]:
            return tensor if isinstance(tensor, Tensor) else Tensor(tensor)
        return Tensor(jnp.zeros_like(val), stop_gradient=True)
    raise RuntimeError("eager ppermute requires a compiled region")


def shift(tensor: Tensor, offset: int = 1, group: Optional[Group] = None,
          wrap: bool = False):
    """Shift values along the group axis by `offset` ranks: rank i's value
    goes to rank i+offset. Without wrap, edge ranks receive zeros — exactly
    the boundary a pipeline stage wants. This is the valid permutation form
    of p2p (every source and destination distinct)."""
    n = _group_size(group)
    if wrap:
        perm = [(i, (i + offset) % n) for i in range(n)]
    else:
        perm = [(i, i + offset) for i in range(n) if 0 <= i + offset < n]
    return ppermute(tensor, perm, group)


def send(tensor: Tensor, dst: int = 0, group: Optional[Group] = None, sync_op=True):
    """reference: collective.py:1793 send → send_v2 op.

    Paired send/recv with per-rank control flow only exists in the
    multi-process world; a single-program SPMD trace cannot express "this
    rank sends" (every rank runs the same trace). In-trace p2p must instead
    be written as one data movement: `shift` / `ppermute` above (used by
    parallel/pipeline.py)."""
    if _group_size(group) == 1:
        return tensor
    if _is_traced(tensor._value):
        raise RuntimeError(
            "send/recv have per-rank control flow and cannot appear inside a "
            "single-program SPMD trace; express the transfer as "
            "paddle.distributed.shift(x, offset) or ppermute(x, [(src, dst)])"
        )
    raise RuntimeError("eager send requires a multi-process launch")


def recv(tensor: Tensor, src: int = 0, group: Optional[Group] = None, sync_op=True):
    if _group_size(group) == 1:
        return tensor
    if _is_traced(tensor._value):
        raise RuntimeError(
            "send/recv have per-rank control flow and cannot appear inside a "
            "single-program SPMD trace; express the transfer as "
            "paddle.distributed.shift(x, offset) or ppermute(x, [(src, dst)])"
        )
    raise RuntimeError("eager recv requires a multi-process launch")


isend = send
irecv = recv


def barrier(group: Optional[Group] = None):
    """reference: barrier op — XLA programs are bulk-synchronous; eager
    single-controller needs only a device sync."""
    jax.effects_barrier() if hasattr(jax, "effects_barrier") else None


def wait(tensor, group=None, use_calc_stream=True):
    """reference: c_wait_compute/c_wait_comm — stream sync is XLA's job; we
    block on the value for API parity."""
    if isinstance(tensor, Tensor) and not _is_traced(tensor._value):
        tensor._value.block_until_ready()
    return tensor
