"""Built-in distributed passes.

Reference analogue: python/paddle/distributed/passes/{auto_parallel_fp16,
auto_parallel_gradient_merge, auto_parallel_recompute, fuse_all_reduce}.py
— each is a Program-rewrite registered with @register_pass and chained by
PassManager.

TPU-native design: the unit a pass rewrites is the DistProgram — the
mutable pre-compile description of a training step (model, loss, optimizer,
precision context, accumulation, sharding knobs). GSPMD owns the op-level
rewriting the reference passes do by hand; what remains pass-shaped is
everything that must be DECIDED before the one XLA compile: precision
policy, gradient accumulation, recompute boundaries, and which parameters
are too small to be worth sharding (the fuse_all_reduce/fuse_grad_size
bucketing capability).
"""
from __future__ import annotations

from typing import Callable, List, Optional

import numpy as np

from ..compat import PassBase, PassContext, register_pass

__all__ = ["DistProgram"]


class DistProgram:
    """What a distributed pass rewrites: the step description that
    `build()` hands to the compiled SPMD pipeline (parallel/sharding.py).
    Plays the role of the reference's (main_program, startup_program)
    pair."""

    def __init__(self, model, loss_fn, optimizer, zero_stage: int = 0,
                 accumulate_steps: int = 1,
                 forward_ctx: Optional[Callable] = None,
                 loss_scale: float = 1.0):
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.zero_stage = zero_stage
        self.accumulate_steps = accumulate_steps
        self.forward_ctx = forward_ctx
        self.loss_scale = loss_scale
        self.applied_passes: List[str] = []

    def build(self):
        from ...parallel.sharding import sharded_train_step

        return sharded_train_step(
            self.model, self.loss_fn, self.optimizer,
            zero_stage=self.zero_stage, forward_ctx=self.forward_ctx,
            accumulate_steps=self.accumulate_steps,
            loss_scale=self.loss_scale,
        )


@register_pass("auto_parallel_fp16")
class FP16Pass(PassBase):
    """Install the low-precision forward policy (reference:
    auto_parallel_fp16.py rewrites every op to fp16 with black/white
    lists; here the policy is an autocast context compiled into the step).
    attrs: dtype ('bfloat16'|'float16'), init_loss_scaling,
    custom_white_list, custom_black_list."""

    def check_before_apply(self, main_program, startup_program, context):
        return isinstance(main_program, DistProgram) and \
            self.get_attr("dtype", "bfloat16") in ("bfloat16", "float16")

    def _apply_single(self, prog, startup, context):
        from ... import amp as _amp

        dtype = self.get_attr("dtype", "bfloat16")
        white = self.get_attr("custom_white_list", None)
        black = self.get_attr("custom_black_list", None)

        def ctx(_d=dtype, _w=white, _b=black):
            return _amp.auto_cast(enable=True, custom_white_list=_w,
                                  custom_black_list=_b, level="O2", dtype=_d)

        prog.forward_ctx = ctx
        if dtype == "float16":
            prog.loss_scale = float(
                self.get_attr("init_loss_scaling", 32768.0)
            )
        prog.applied_passes.append(self.name)

    def apply(self, main_programs, startup_programs, context=None):
        context = context or PassContext()
        mains = main_programs if isinstance(main_programs, list) \
            else [main_programs]
        for m in mains:
            self._apply_single(m, None, context)
        return context


@register_pass("auto_parallel_gradient_merge")
class GradientMergePass(PassBase):
    """k-step compiled gradient accumulation (reference:
    auto_parallel_gradient_merge.py). attrs: k_steps."""

    def check_before_apply(self, main_program, startup_program, context):
        return isinstance(main_program, DistProgram) and \
            int(self.get_attr("k_steps", 1)) >= 1

    def apply(self, main_programs, startup_programs, context=None):
        context = context or PassContext()
        mains = main_programs if isinstance(main_programs, list) \
            else [main_programs]
        for m in mains:
            m.accumulate_steps = int(self.get_attr("k_steps", 1))
            m.applied_passes.append(self.name)
        return context


@register_pass("auto_parallel_recompute")
class RecomputePass(PassBase):
    """Wrap the named sublayers in jax.checkpoint (reference:
    auto_parallel_recompute.py inserts recompute subgraphs at the
    checkpoint vars). attrs: checkpoints = [sublayer names]."""

    def check_before_apply(self, main_program, startup_program, context):
        return isinstance(main_program, DistProgram)

    def apply(self, main_programs, startup_programs, context=None):
        from ..fleet import _apply_strategy_recompute

        context = context or PassContext()
        mains = main_programs if isinstance(main_programs, list) \
            else [main_programs]
        cps = list(self.get_attr("checkpoints", []) or [])
        for m in mains:
            _apply_strategy_recompute(m.model, cps)
            m.applied_passes.append(self.name)
        context.set_attr("recompute_wrapped", len(cps))
        return context


@register_pass("fuse_all_reduce")
class FuseAllReducePass(PassBase):
    """Small-parameter coalescing (reference: fuse_all_reduce.py groups
    gradients into fused buckets so tiny tensors don't pay per-collective
    latency). On TPU XLA already fuses same-spec collectives, so the
    remaining lever is the SPEC: parameters smaller than `size_threshold`
    bytes get pinned to a REPLICATED spec — their grads ride the one big
    fused all-reduce instead of each paying a ZeRO gather/scatter pair.
    attrs: size_threshold (bytes, default 1 MiB = fuse_grad_size_in_MB's
    unit)."""

    def check_before_apply(self, main_program, startup_program, context):
        return isinstance(main_program, DistProgram)

    def apply(self, main_programs, startup_programs, context=None):
        context = context or PassContext()
        mains = main_programs if isinstance(main_programs, list) \
            else [main_programs]
        threshold = int(self.get_attr("size_threshold", 1 << 20))
        pinned = []
        for m in mains:
            for name, p in m.model.named_parameters():
                if p.stop_gradient:
                    continue
                nbytes = int(np.prod(p.shape)) * 4
                has_tp = getattr(p, "dist_spec", None) is not None and any(
                    s is not None for s in tuple(p.dist_spec)
                )
                if nbytes < threshold and not has_tp:
                    # param_spec honors this pin ahead of ZeRO sharding
                    p.fuse_replicated = True
                    pinned.append(name)
            m.applied_passes.append(self.name)
        context.set_attr("replicated_params", pinned)
        return context
