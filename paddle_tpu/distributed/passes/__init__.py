"""paddle.distributed.passes — program-rewrite pass framework.

Reference analogue: python/paddle/distributed/passes/ (pass_base.py +
auto_parallel/ps passes). On this stack program rewriting is GSPMD's job;
the framework is provided so pass-based reference workflows (auto_parallel
custom passes, PS pass pipelines) can register and chain passes.
"""
from ..compat import (  # noqa: F401
    PassBase,
    PassContext,
    PassManager,
    new_pass,
    register_pass,
)
from .builtin import DistProgram  # noqa: F401  (registers builtin passes)

__all__ = ["new_pass", "PassManager", "PassContext", "PassBase",
           "register_pass", "DistProgram"]
