"""paddle.distributed — collective API, fleet, launch.

Reference analogue: python/paddle/distributed/ (69.8k LoC Python) +
paddle/fluid/distributed/ (36.8k C++). See SURVEY.md §2.C/D and the
TPU-native mapping: mesh axes replace comm rings, XLA collectives over
ICI/DCN replace NCCL, the JAX coordination service replaces TCPStore.
"""
from . import collective  # noqa: F401
from . import fleet  # noqa: F401
from .collective import (  # noqa: F401
    Group,
    ReduceOp,
    all_gather,
    all_gather_object,
    all_reduce,
    alltoall,
    alltoall_single,
    barrier,
    broadcast,
    destroy_process_group,
    get_group,
    irecv,
    is_initialized,
    isend,
    new_group,
    ppermute,
    recv,
    reduce,
    reduce_scatter,
    scatter,
    send,
    shift,
    wait,
)
from .parallel import (  # noqa: F401
    DataParallel,
    ParallelEnv,
    get_rank,
    get_world_size,
    init_parallel_env,
    spawn,
)
from . import auto_parallel  # noqa: F401
from .auto_parallel import (  # noqa: F401
    Engine,
    ProcessMesh,
    shard_op,
    shard_tensor,
)


def split(x, size, operation, axis=0, num_partitions=1, gather_out=True, weight_attr=None, bias_attr=None, name=None):
    """reference: collective.py:1483 paddle.distributed.split — auto-sharded
    Linear/Embedding; superseded by fleet.meta_parallel layers on TPU."""
    from .fleet import meta_parallel as mp

    if operation == "linear":
        if axis == 0:
            return mp.RowParallelLinear(size[0], size[1], input_is_parallel=False)
        return mp.ColumnParallelLinear(size[0], size[1], gather_output=gather_out)
    if operation == "embedding":
        return mp.VocabParallelEmbedding(size[0], size[1])
    raise ValueError(f"unsupported split operation {operation!r}")

# surface completion (reference: python/paddle/distributed/__init__.py)
from .compat import (  # noqa: E402,F401
    CountFilterEntry,
    ParallelMode,
    ProbabilityEntry,
    ShowClickEntry,
    gloo_barrier,
    gloo_init_parallel_env,
    gloo_release,
)
from . import launch  # noqa: E402,F401
from . import passes  # noqa: E402,F401
from . import sharding  # noqa: E402,F401
from . import utils  # noqa: E402,F401
from .fleet.dataset import InMemoryDataset, QueueDataset  # noqa: E402,F401
from . import ps  # noqa: E402,F401
