"""Flagship model families (training-scale).

Reference analogue: the fleet example models the reference targets (GPT /
BERT / ERNIE collective configs, SURVEY.md §6) — the reference keeps them in
external repos (PaddleNLP/FleetX); here they are first-class so the
distributed engine has in-tree users.
"""
from .gpt import (  # noqa: F401
    CacheOverflow,
    GPTConfig,
    GPTForPretraining,
    GPTModel,
    GPTPretrainingCriterion,
    gpt2_small,
    gpt2_medium,
    gpt2_345m,
)
from .bert import BertConfig, BertForPretraining, BertModel  # noqa: F401
