"""BERT model family (BASELINE config 3: BERT-base pretraining).

Reference analogue: the Fleet BERT pretraining configs (model code upstream
in PaddleNLP). TPU-first: TP-ready encoder built on the same meta_parallel
layers as GPT; MLM + NSP heads.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import paddle_tpu as paddle

from .. import nn
from ..nn import functional as F
from ..nn import initializer as I
from ..distributed.fleet.meta_parallel import (
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
)
from ..parallel.sharding import with_sharding_constraint


@dataclass
class BertConfig:
    vocab_size: int = 30528
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    ffn_hidden_size: Optional[int] = None
    max_seq_len: int = 512
    type_vocab_size: int = 2
    dropout: float = 0.1
    attn_dropout: float = 0.1
    initializer_range: float = 0.02

    def __post_init__(self):
        if self.ffn_hidden_size is None:
            self.ffn_hidden_size = 4 * self.hidden_size


class BertEmbeddings(nn.Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        init = I.Normal(0.0, cfg.initializer_range)
        self.word_embeddings = VocabParallelEmbedding(
            cfg.vocab_size, cfg.hidden_size, weight_attr=init
        )
        self.position_embeddings = nn.Embedding(cfg.max_seq_len, cfg.hidden_size, weight_attr=init)
        self.token_type_embeddings = nn.Embedding(cfg.type_vocab_size, cfg.hidden_size, weight_attr=init)
        self.layer_norm = nn.LayerNorm(cfg.hidden_size)
        self.dropout = nn.Dropout(cfg.dropout)

    def forward(self, input_ids, token_type_ids=None):
        s = input_ids.shape[1]
        pos = paddle.arange(s, dtype="int64").unsqueeze(0)
        h = self.word_embeddings(input_ids) + self.position_embeddings(pos)
        if token_type_ids is not None:
            h = h + self.token_type_embeddings(token_type_ids)
        return self.dropout(self.layer_norm(h))


class BertEncoderLayer(nn.Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.cfg = cfg
        self.num_heads = cfg.num_heads
        self.head_dim = cfg.hidden_size // cfg.num_heads
        init = I.Normal(0.0, cfg.initializer_range)
        self.qkv_proj = ColumnParallelLinear(
            cfg.hidden_size, 3 * cfg.hidden_size, weight_attr=init, gather_output=False
        )
        self.out_proj = RowParallelLinear(
            cfg.hidden_size, cfg.hidden_size, weight_attr=init, input_is_parallel=True
        )
        self.fc1 = ColumnParallelLinear(
            cfg.hidden_size, cfg.ffn_hidden_size, weight_attr=init, gather_output=False
        )
        self.fc2 = RowParallelLinear(
            cfg.ffn_hidden_size, cfg.hidden_size, weight_attr=init, input_is_parallel=True
        )
        self.ln1 = nn.LayerNorm(cfg.hidden_size)
        self.ln2 = nn.LayerNorm(cfg.hidden_size)
        self.dropout = nn.Dropout(cfg.dropout)

    def forward(self, x, attn_mask=None):
        b, s = x.shape[0], x.shape[1]
        qkv = self.qkv_proj(x).reshape([b, s, 3, self.num_heads, self.head_dim])
        q, k, v = qkv.unstack(axis=2)
        attn = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask,
            dropout_p=self.cfg.attn_dropout if self.training else 0.0,
            training=self.training,
        )
        attn = attn.reshape([b, s, self.num_heads * self.head_dim])
        x = self.ln1(x + self.dropout(self.out_proj(attn)))
        h = self.fc2(F.gelu(self.fc1(x), approximate=True))
        return self.ln2(x + self.dropout(h))


class BertModel(nn.Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.cfg = cfg
        self.embeddings = BertEmbeddings(cfg)
        self.layers = nn.LayerList([BertEncoderLayer(cfg) for _ in range(cfg.num_layers)])
        self.pooler = nn.Linear(cfg.hidden_size, cfg.hidden_size)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        mask = None
        if attention_mask is not None:
            # [b, s] 1/0 → additive [b, 1, 1, s]
            mask = (1.0 - attention_mask.astype("float32")) * -1e9
            mask = mask.unsqueeze([1, 2])
        h = self.embeddings(input_ids, token_type_ids)
        for layer in self.layers:
            h = layer(h, mask)
        pooled = F.tanh(self.pooler(h[:, 0]))
        return h, pooled


class BertForPretraining(nn.Layer):
    """MLM + NSP heads with tied decoder weight."""

    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.cfg = cfg
        self.bert = BertModel(cfg)
        self.mlm_transform = nn.Linear(cfg.hidden_size, cfg.hidden_size)
        self.mlm_ln = nn.LayerNorm(cfg.hidden_size)
        self.mlm_bias = self.create_parameter([cfg.vocab_size], is_bias=True)
        self.nsp = nn.Linear(cfg.hidden_size, 2)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        seq, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        h = self.mlm_ln(F.gelu(self.mlm_transform(seq), approximate=True))
        w = self.bert.embeddings.word_embeddings.weight
        mlm_logits = paddle.matmul(h, w, transpose_y=True) + self.mlm_bias
        nsp_logits = self.nsp(pooled)
        return mlm_logits, nsp_logits


class BertPretrainingCriterion(nn.Layer):
    def __init__(self, vocab_size=None):
        super().__init__()

    def forward(self, mlm_logits, nsp_logits, mlm_labels, nsp_labels, mlm_mask=None):
        mlm_loss = F.cross_entropy(mlm_logits, mlm_labels, reduction="none", ignore_index=-100)
        if mlm_mask is not None:
            mlm_loss = (mlm_loss * mlm_mask).sum() / mlm_mask.sum().clip(min=1.0)
        else:
            mlm_loss = mlm_loss.mean()
        nsp_loss = F.cross_entropy(nsp_logits, nsp_labels)
        return mlm_loss + nsp_loss
