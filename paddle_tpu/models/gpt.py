"""GPT model family — the flagship decoder-only LM.

Reference analogue: the GPT configs the reference's fleet stack trains
(BASELINE config 4: GPT-2 345M hybrid TP+PP) — model code lives in
PaddleNLP upstream; rebuilt here TPU-first:
  - attention/MLP built from fleet.meta_parallel TP layers (Column/Row
    parallel with mp sharding specs — mp_layers.py analogues);
  - sequence parallelism via `sep`-axis sharding constraints on the token
    axis (capability gap in the reference — SURVEY.md §5 long-context);
  - causal attention through ops.nn_ops.scaled_dot_product_attention (XLA
    flash-pattern fusion; Pallas kernel in ops/pallas for long sequences);
  - weight-tied LM head (SharedLayerDesc semantics) with vocab-parallel
    cross entropy.

Everything is shape-static and scan-friendly: one compiled step trains it
under any mesh (dp / mp / sharding / sep) via fleet.distributed_train_step.
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional

import paddle_tpu as paddle

from .. import nn
from ..nn import functional as F
from ..nn import initializer as I
from ..distributed.fleet.meta_parallel import (
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
)
from ..parallel.sharding import with_sharding_constraint


@dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    ffn_hidden_size: Optional[int] = None
    max_seq_len: int = 1024
    dropout: float = 0.1
    attn_dropout: float = 0.1
    initializer_range: float = 0.02
    sequence_parallel: bool = False
    # how seq-sharded attention is computed when sequence_parallel and the
    # sep axis > 1: "gspmd" (compiler-inserted gathers), "ring" (ppermute KV
    # rotation — O(S/P) memory, the long-context path), "ulysses" (alltoall
    # heads<->seq). Reference has none of these (SURVEY §5 gap-fill).
    sequence_parallel_mode: str = "gspmd"
    use_recompute: bool = False
    dtype: str = "float32"

    def __post_init__(self):
        if self.ffn_hidden_size is None:
            self.ffn_hidden_size = 4 * self.hidden_size


def _sp(x, cfg, *spec):
    """Activation sharding hint; batch on dp(+sharding), seq on sep."""
    return with_sharding_constraint(x, *spec)


class CacheOverflow(ValueError):
    """Structured KV-cache overflow: a generation step would write past the
    cache capacity. A REQUEST-level verdict, not a run-killer — the serving
    scheduler (paddle.serving) catches it and answers the offending request
    with an error response while the rest of the batch keeps decoding.
    Subclasses ValueError so pre-existing callers that caught the old
    ValueError keep working."""

    def __init__(self, need: int, capacity: int, detail: str = ""):
        self.need = int(need)
        self.capacity = int(capacity)
        suffix = f" ({detail})" if detail else ""
        super().__init__(
            f"KV cache overflow: need {need} positions > capacity "
            f"{capacity}{suffix}"
        )


def convert_legacy_qkv_state_dict(state_dict, num_heads: int):
    """One-time converter for checkpoints saved before the fused-qkv layout
    switched from 3-major ([h, 3, H, hd] over the output dim) to heads-major
    ([h, H, 3, hd], Megatron-style — see GPTAttention.forward). Old
    checkpoints LOAD WITHOUT ERROR but silently permute q/k/v; run them
    through this once. Operates on any key containing 'qkv_proj'; returns a
    new dict."""
    import numpy as np

    out = {}
    for k, v in state_dict.items():
        if "qkv_proj" in k:
            arr = np.asarray(v.numpy() if hasattr(v, "numpy") else v)
            three_h = arr.shape[-1]
            hd = three_h // (3 * num_heads)
            # [..., 3*H*hd] 3-major -> heads-major
            arr = arr.reshape(arr.shape[:-1] + (3, num_heads, hd))
            arr = np.swapaxes(arr, -3, -2).reshape(arr.shape[:-3] + (three_h,))
            out[k] = arr
        else:
            out[k] = v
    return out


class GPTAttention(nn.Layer):
    """Fused-qkv layout is heads-major (state_dict layout v2); checkpoints
    from the 3-major era must pass through convert_legacy_qkv_state_dict."""

    QKV_LAYOUT_VERSION = 2

    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        self.num_heads = cfg.num_heads
        self.head_dim = cfg.hidden_size // cfg.num_heads
        init = I.Normal(0.0, cfg.initializer_range)
        self.qkv_proj = ColumnParallelLinear(
            cfg.hidden_size, 3 * cfg.hidden_size, weight_attr=init,
            gather_output=False,
        )
        self.out_proj = RowParallelLinear(
            cfg.hidden_size, cfg.hidden_size, weight_attr=init,
            input_is_parallel=True,
        )

    def forward(self, x, cache=None):
        cfg = self.cfg
        b, s = x.shape[0], x.shape[1]
        qkv = self.qkv_proj(x)  # [b, s, 3h] sharded on mp
        if cache is not None and not isinstance(cache, dict):
            # paged KV view (paddle.serving.PagedCacheView): block storage,
            # per-row lengths, and the block table live in the view; the
            # attention math is the paged_decode_attention analogue of
            # cached_attention below (bitwise-equal over the same context)
            qkv = qkv.reshape([b, s, self.num_heads, 3, self.head_dim])
            q, k, v = qkv.unstack(axis=3)
            out = cache.append_attend(
                q, k, v, scale=1.0 / math.sqrt(self.head_dim)
            )
            out = out.reshape([b, s, self.num_heads * self.head_dim])
            return self.out_proj(out)
        # heads-major fused-qkv layout (Megatron-style): 3h splits as
        # H x 3 x hd so the mp sharding of the fused dim lands on the
        # HEADS subdim (divisible by mp). The 3-major layout put mp on the
        # size-3 subdim — GSPMD could only replicate-then-repartition,
        # the 'Involuntary full rematerialization' churn in the backward.
        qkv = qkv.reshape([b, s, self.num_heads, 3, self.head_dim])
        q, k, v = qkv.unstack(axis=3)
        if cache is not None:
            # incremental decode over a PREALLOCATED fixed-shape cache:
            # every step reuses one compiled program (ops/nn_ops.py
            # cached_attention), with a prefix+causal mask that stays
            # correct for multi-token chunks too.
            import numpy as _np

            from ..core.dispatch import apply as _apply
            from ..ops import nn_ops as _ops

            if cache.get("k") is None:
                cache["k"] = paddle.zeros(
                    [b, cfg.max_seq_len, self.num_heads, self.head_dim],
                    dtype=str(k._value.dtype),
                )
                cache["v"] = paddle.zeros(
                    [b, cfg.max_seq_len, self.num_heads, self.head_dim],
                    dtype=str(v._value.dtype),
                )
                cache["len"] = 0
            if cache["len"] + s > cfg.max_seq_len:
                raise CacheOverflow(
                    cache["len"] + s, cfg.max_seq_len,
                    detail=f"cached {cache['len']} + new {s} > max_seq_len",
                )
            cur = paddle.Tensor(_np.int32(cache["len"]), stop_gradient=True)
            out, nk, nv = _apply(
                _ops.cached_attention, q, cache["k"], cache["v"], k, v, cur,
                scale=1.0 / math.sqrt(self.head_dim),
                op_name="cached_attention",
            )
            cache["k"], cache["v"] = nk, nv
            cache["len"] += s
            out = out.reshape([b, s, self.num_heads * self.head_dim])
            return self.out_proj(out)
        ring_mode = cfg.sequence_parallel and cfg.sequence_parallel_mode in (
            "ring", "ulysses"
        )
        if ring_mode:
            from ..core.dispatch import apply as _apply
            from ..ops import ring_attention as _ra
            from ..parallel.topology import axis_size, get_mesh

            if axis_size("sep") > 1:
                if self.training and cfg.attn_dropout > 0.0:
                    raise NotImplementedError(
                        "ring/ulysses attention has no attention-dropout "
                        "path; set attn_dropout=0.0 (hidden-state dropout "
                        "still applies) or use sequence_parallel_mode='gspmd'"
                    )
                # KV stay seq-sharded: the ring/alltoall moves them, not GSPMD
                q = _sp(q, cfg, ("dp", "sharding"), "sep", "mp", None)
                k = _sp(k, cfg, ("dp", "sharding"), "sep", "mp", None)
                v = _sp(v, cfg, ("dp", "sharding"), "sep", "mp", None)
                fn = (
                    _ra.ring_attention
                    if cfg.sequence_parallel_mode == "ring"
                    else _ra.ulysses_attention
                )
                # module-level fn + hashable static kwargs → per-op jit cache
                # applies (a closure here would defeat it — dispatch refuses
                # to cache closures)
                out = _apply(
                    fn, q, k, v, mesh=get_mesh(), causal=True,
                    op_name=f"{cfg.sequence_parallel_mode}_attention",
                )
                out = out.reshape([b, s, self.num_heads * self.head_dim])
                return self.out_proj(out)
        # heads axis is the mp-sharded axis (TP attention)
        q = _sp(q, cfg, ("dp", "sharding"), "sep", "mp", None)
        k = _sp(k, cfg, ("dp", "sharding"), None, "mp", None)
        v = _sp(v, cfg, ("dp", "sharding"), None, "mp", None)
        out = F.scaled_dot_product_attention(
            q, k, v, is_causal=True,
            dropout_p=cfg.attn_dropout if self.training else 0.0,
            training=self.training,
        )
        out = out.reshape([b, s, self.num_heads * self.head_dim])
        return self.out_proj(out)


class GPTMLP(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        init = I.Normal(0.0, cfg.initializer_range)
        out_init = I.Normal(
            0.0, cfg.initializer_range / math.sqrt(2.0 * cfg.num_layers)
        )
        self.fc1 = ColumnParallelLinear(
            cfg.hidden_size, cfg.ffn_hidden_size, weight_attr=init,
            gather_output=False,
        )
        self.fc2 = RowParallelLinear(
            cfg.ffn_hidden_size, cfg.hidden_size, weight_attr=out_init,
            input_is_parallel=True,
        )

    def forward(self, x):
        return self.fc2(F.gelu(self.fc1(x), approximate=True))


class GPTDecoderLayer(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        self.ln1 = nn.LayerNorm(cfg.hidden_size)
        self.attn = GPTAttention(cfg)
        self.ln2 = nn.LayerNorm(cfg.hidden_size)
        self.mlp = GPTMLP(cfg)
        self.dropout = nn.Dropout(cfg.dropout)

    def _block(self, x, cache=None):
        x = x + self.dropout(self.attn(self.ln1(x), cache=cache))
        x = x + self.dropout(self.mlp(self.ln2(x)))
        return _sp(x, self.cfg, ("dp", "sharding"), "sep", None)

    def forward(self, x, cache=None):
        if self.cfg.use_recompute and cache is None:
            from ..incubate.recompute import recompute

            return recompute(self._block, x)
        return self._block(x, cache=cache)


class GPTEmbeddings(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        init = I.Normal(0.0, cfg.initializer_range)
        self.word_embeddings = VocabParallelEmbedding(
            cfg.vocab_size, cfg.hidden_size, weight_attr=init
        )
        self.position_embeddings = nn.Embedding(
            cfg.max_seq_len, cfg.hidden_size, weight_attr=init
        )
        self.dropout = nn.Dropout(cfg.dropout)

    def forward(self, input_ids, pos_offset=0):
        s = input_ids.shape[1]
        pos = paddle.arange(s, dtype="int64").unsqueeze(0)
        if isinstance(pos_offset, paddle.Tensor):
            # per-row offsets (continuous-batching decode: every sequence in
            # the batch sits at its own position) — [b] broadcasts to [b, s]
            pos = pos + pos_offset.astype("int64").unsqueeze(-1)
        else:
            pos = pos + pos_offset
        h = self.word_embeddings(input_ids) + self.position_embeddings(pos)
        h = _sp(h, self.cfg, ("dp", "sharding"), "sep", None)
        return self.dropout(h)


class GPTModel(nn.Layer):
    """Decoder-only transformer trunk."""

    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        self.embeddings = GPTEmbeddings(cfg)
        self.layers = nn.LayerList([GPTDecoderLayer(cfg) for _ in range(cfg.num_layers)])
        self.final_ln = nn.LayerNorm(cfg.hidden_size)

    def forward(self, input_ids, caches=None, pos_offset: int = 0):
        h = self.embeddings(input_ids, pos_offset=pos_offset)
        for i, layer in enumerate(self.layers):
            h = layer(h, cache=None if caches is None else caches[i])
        return self.final_ln(h)


class GPTForPretraining(nn.Layer):
    """Trunk + weight-tied vocab-parallel LM head."""

    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        self.gpt = GPTModel(cfg)

    def forward(self, input_ids, caches=None, pos_offset: int = 0):
        # the same three phases the pipeline schedule runs, so the eager and
        # pipelined computations cannot diverge
        if caches is not None:
            h = self.gpt(input_ids, caches=caches, pos_offset=pos_offset)
            return self._tied_head(h)
        h = self.pp_embed(input_ids)
        for layer in self.gpt.layers:
            h = layer(h)
        return self.pp_head(h)

    def _tied_head(self, h):
        w = self.gpt.embeddings.word_embeddings.weight
        logits = paddle.matmul(h, w, transpose_y=True)
        return _sp(logits, self.cfg, ("dp", "sharding"), "sep", "mp")

    # pipeline-partition protocol (parallel/pipeline.py): homogeneous middle
    # = the decoder stack; embedding/head replicated across pp stages
    def pp_embed(self, input_ids):
        return self.gpt.embeddings(input_ids)

    @property
    def pp_blocks(self):
        return list(self.gpt.layers)

    def pp_head(self, h):
        return self._tied_head(self.gpt.final_ln(h))

    @paddle.no_grad()
    def generate(self, input_ids, max_new_tokens: int = 32,
                 temperature: float = 1.0, top_k: Optional[int] = None,
                 eos_token_id: Optional[int] = None):
        """Autoregressive decoding (greedy, or top-k sampling when top_k set).

        Fixed-shape incremental decode: the sequence buffer is padded to
        prompt+max_new_tokens once, so every step re-runs ONE compiled
        forward (causal masking makes the not-yet-written tail irrelevant to
        the current position's logits). O(T·forward) — flash attention keeps
        that cheap; a KV-cache decode path is the optimization on top, not a
        correctness requirement. Reference analogue: generation loops live
        upstream (PaddleNLP) — provided here so the flagship model is usable
        end to end.
        """
        import numpy as np

        was_training = self.training
        self.eval()
        try:
            ids = np.asarray(
                input_ids.numpy() if isinstance(input_ids, paddle.Tensor) else input_ids,
                np.int64,
            )
            if ids.ndim == 1:
                ids = ids[None, :]
            b, prompt_len = ids.shape
            if prompt_len >= self.cfg.max_seq_len:
                raise ValueError(
                    f"prompt length {prompt_len} leaves no room to generate "
                    f"within max_seq_len={self.cfg.max_seq_len}; truncate the "
                    "prompt (keep its most recent tokens) before calling"
                )
            total = min(prompt_len + max_new_tokens, self.cfg.max_seq_len)
            buf = np.zeros((b, total), np.int64)
            buf[:, :prompt_len] = ids[:, :total]
            done = np.zeros((b,), bool)

            from ..parallel.topology import get_mesh

            mesh = get_mesh()
            sharded = mesh is not None and mesh.devices.size > 1
            # KV-cache incremental decode: prefill once over the prompt,
            # then one single-token forward per step — O(T) tokens instead
            # of O(T) full-sequence forwards. Sharded meshes keep the
            # fixed-shape path (growing cache shapes fight GSPMD layouts).
            caches = (
                None if sharded
                else [{"k": None, "v": None} for _ in self.gpt.layers]
            )

            def _feed(arr):
                # under a live mesh the params are sharded: feed ids
                # replicated so GSPMD can re-shard activations per layer
                if sharded:
                    import jax as _jax
                    from jax.sharding import NamedSharding, PartitionSpec

                    return paddle.Tensor(
                        _jax.device_put(arr, NamedSharding(mesh, PartitionSpec())),
                        stop_gradient=True,
                    )
                return paddle.to_tensor(arr)

            for cur in range(prompt_len, total):
                if caches is not None:
                    if cur == prompt_len:  # prefill the whole prompt
                        logits = self(
                            _feed(buf[:, :prompt_len]), caches=caches, pos_offset=0
                        )
                        step_t = logits[:, -1, :]
                    else:  # one new token
                        logits = self(
                            _feed(buf[:, cur - 1 : cur]), caches=caches,
                            pos_offset=cur - 1,
                        )
                        step_t = logits[:, 0, :]
                else:
                    logits = self(_feed(buf))  # [b, total, vocab]
                    # slice the current position ON DEVICE before the host
                    # copy (full [b, total, vocab] D2H would dominate)
                    step_t = logits[:, cur - 1, :]
                if top_k is not None:
                    t = max(float(temperature), 1e-6)
                    k_eff = min(int(top_k), step_t.shape[-1])
                    vals, idx = paddle.topk(step_t / t, k_eff, axis=-1)
                    probs = F.softmax(vals, axis=-1)
                    # multinomial draws through the framework generator, so
                    # paddle.seed reproduces runs while successive calls
                    # yield different samples
                    choice = paddle.multinomial(probs, num_samples=1)
                    nxt = np.take_along_axis(
                        idx.numpy(), choice.numpy().astype(np.int64), axis=-1
                    )[:, 0]
                else:
                    nxt = step_t.numpy().argmax(-1)
                nxt = np.where(done, buf[:, cur - 1], nxt)
                buf[:, cur] = nxt
                if eos_token_id is not None:
                    done |= nxt == eos_token_id
                    if done.all():
                        buf = buf[:, : cur + 1]
                        break
            return paddle.to_tensor(buf)
        finally:
            if was_training:
                self.train()


class GPTPretrainingCriterion(nn.Layer):
    """reference: ParallelCrossEntropy (mp_layers.py:249) over shifted LM
    labels, masked mean."""

    def __init__(self, cfg: Optional[GPTConfig] = None):
        super().__init__()

    def forward(self, logits, labels, loss_mask=None):
        loss = F.cross_entropy(logits, labels, reduction="none")
        if loss_mask is not None:
            loss = loss * loss_mask
            return loss.sum() / loss_mask.sum().clip(min=1.0)
        return loss.mean()


def gpt2_small(**kw) -> GPTConfig:
    return GPTConfig(hidden_size=768, num_layers=12, num_heads=12, **kw)


def gpt2_medium(**kw) -> GPTConfig:
    return GPTConfig(hidden_size=1024, num_layers=24, num_heads=16, **kw)


def gpt2_345m(**kw) -> GPTConfig:
    """BASELINE config 4: GPT-2 345M."""
    return GPTConfig(hidden_size=1024, num_layers=24, num_heads=16, **kw)
