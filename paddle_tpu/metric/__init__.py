"""paddle.metric — Metric base + Accuracy/Precision/Recall/Auc.

Reference analogue: python/paddle/metric/metrics.py.
"""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor


def _np(x):
    return x.numpy() if isinstance(x, Tensor) else np.asarray(x)


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        return self.__class__.__name__.lower()

    def compute(self, *args):
        return args


class Accuracy(Metric):
    """reference: metrics.py Accuracy (top-k)."""

    def __init__(self, topk=(1,), name=None):
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def reset(self):
        self.total = np.zeros(len(self.topk))
        self.count = np.zeros(len(self.topk))

    def compute(self, pred, label, *args):
        pred = _np(pred)
        label = _np(label)
        if label.ndim == pred.ndim and label.shape[-1] == 1:
            label = label[..., 0]
        order = np.argsort(-pred, axis=-1)[..., : self.maxk]
        correct = order == label[..., None]
        return correct

    def update(self, correct, *args):
        correct = _np(correct)
        accs = []
        for i, k in enumerate(self.topk):
            num = correct[..., :k].sum()
            self.total[i] += num
            self.count[i] += correct.shape[0]
            accs.append(num / max(1, correct.shape[0]))
        return accs[0] if len(accs) == 1 else accs

    def accumulate(self):
        res = [t / max(1, c) for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return self._name
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name="precision"):
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = np.rint(_np(preds)).astype(np.int64).reshape(-1)
        labels = _np(labels).astype(np.int64).reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fp += int(((preds == 1) & (labels == 0)).sum())

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall"):
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = np.rint(_np(preds)).astype(np.int64).reshape(-1)
        labels = _np(labels).astype(np.int64).reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fn += int(((preds == 0) & (labels == 1)).sum())

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    """reference: metrics.py Auc (threshold-bucketed ROC AUC, PS-friendly)."""

    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        self._name = name
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        preds = _np(preds)
        labels = _np(labels).reshape(-1)
        if preds.ndim == 2:
            preds = preds[:, -1]
        buckets = np.minimum(
            (preds * self.num_thresholds).astype(np.int64), self.num_thresholds
        )
        for b, l in zip(buckets, labels):
            if l:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def accumulate(self):
        tot_pos = 0.0
        tot_neg = 0.0
        auc = 0.0
        for i in range(self.num_thresholds, -1, -1):
            pos = self._stat_pos[i]
            neg = self._stat_neg[i]
            auc += neg * tot_pos + pos * neg / 2.0
            tot_pos += pos
            tot_neg += neg
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        return auc / (tot_pos * tot_neg)

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """Functional top-k accuracy (reference: paddle.metric.accuracy)."""
    import paddle_tpu as paddle

    pred = _np(input)
    lab = _np(label)
    if lab.ndim == 2 and lab.shape[-1] == 1:
        lab = lab[:, 0]
    order = np.argsort(-pred, axis=-1)[:, :k]
    corr = (order == lab[:, None]).any(axis=1).mean()
    return paddle.to_tensor(np.float32(corr))
