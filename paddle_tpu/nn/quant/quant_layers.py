"""paddle.nn.quant.quant_layers — the reference's QAT layer names.

Reference analogue: python/paddle/nn/quant/quant_layers.py. The working
implementations live in paddle_tpu.quantization (fake-quant STE ops +
quantized Linear/Conv2D); this module maps the reference class names onto
them and provides the thin observer/stub layers the reference also exports.
"""
from __future__ import annotations

from ...quantization import (  # noqa: F401
    QuantedConv2D as QuantizedConv2D,
    QuantedLinear as QuantizedLinear,
    fake_quant_abs_max,
    fake_quant_channel_wise_abs_max,
)
from ..layer_base import Layer

__all__ = [
    "FakeQuantAbsMax",
    "FakeQuantChannelWiseAbsMax",
    "FakeQuantMovingAverageAbsMax",
    "FakeQuantMAOutputScaleLayer",
    "MAOutputScaleLayer",
    "MovingAverageAbsMaxScale",
    "QuantStub",
    "QuantizedConv2D",
    "QuantizedConv2DTranspose",
    "QuantizedLinear",
]


class FakeQuantAbsMax(Layer):
    """reference: quant_layers.py FakeQuantAbsMax."""

    def __init__(self, name=None, quant_bits=8, dtype="float32",
                 quant_on_weight=False):
        super().__init__()
        self.quant_bits = quant_bits

    def forward(self, x):
        return fake_quant_abs_max(x, bits=self.quant_bits)


class FakeQuantChannelWiseAbsMax(Layer):
    def __init__(self, name=None, channel_num=None, quant_bits=8,
                 dtype="float32", quant_on_weight=False, quant_axis=0):
        super().__init__()
        self.quant_bits = quant_bits
        self.quant_axis = quant_axis

    def forward(self, x):
        return fake_quant_channel_wise_abs_max(
            x, bits=self.quant_bits, axis=self.quant_axis
        )


class FakeQuantMovingAverageAbsMax(Layer):
    """reference: quant_layers.py FakeQuantMovingAverageAbsMax — activation
    fake-quant with EMA-tracked scale."""

    def __init__(self, name=None, moving_rate=0.9, quant_bits=8,
                 dtype="float32"):
        super().__init__()
        import paddle_tpu as paddle

        self.moving_rate = moving_rate
        self.quant_bits = quant_bits
        self.register_buffer("scale", paddle.to_tensor(0.0))

    def forward(self, x):
        import paddle_tpu as paddle

        from ...quantization import _fq_moving_avg
        from ...core.dispatch import apply

        out, new_scale = apply(
            _fq_moving_avg, x, self.scale, bits=self.quant_bits,
            rate=self.moving_rate, op_name="fake_quant_moving_avg",
        )
        if self.training:
            with paddle.no_grad():
                self.scale.set_value(new_scale._value)
        return out


class MovingAverageAbsMaxScale(Layer):
    """Observer: track abs-max scale without quantizing (reference:
    quant_layers.py MovingAverageAbsMaxScale)."""

    def __init__(self, name=None, moving_rate=0.9, dtype="float32"):
        super().__init__()
        import paddle_tpu as paddle

        self.moving_rate = moving_rate
        self.register_buffer("scale", paddle.to_tensor(0.0))

    def forward(self, x):
        import paddle_tpu as paddle

        if self.training:
            with paddle.no_grad():
                cur = float(x.abs().max())
                prev = float(self.scale)
                new = cur if prev == 0.0 else (
                    self.moving_rate * prev + (1 - self.moving_rate) * cur
                )
                self.scale.set_value(
                    paddle.to_tensor(new, dtype=str(self.scale.dtype))._value
                )
        return x


class MAOutputScaleLayer(Layer):
    """Wrap a layer, observing its output scale (reference:
    quant_layers.py MAOutputScaleLayer)."""

    def __init__(self, layer=None, moving_rate=0.9, name=None, dtype="float32"):
        super().__init__()
        self._layer = layer
        self._ma_output_scale = MovingAverageAbsMaxScale(
            moving_rate=moving_rate, dtype=dtype
        )

    def forward(self, *inputs, **kwargs):
        out = self._layer(*inputs, **kwargs)
        if isinstance(out, (list, tuple)):
            return out
        return self._ma_output_scale(out)


class FakeQuantMAOutputScaleLayer(Layer):
    """Wrap a layer, fake-quantizing its output (reference:
    quant_layers.py FakeQuantMAOutputScaleLayer)."""

    def __init__(self, layer, weight_bits=8, activation_bits=8,
                 moving_rate=0.9, name=None, *args, **kwargs):
        super().__init__()
        self._layer = layer
        self._fake_quant_output = FakeQuantMovingAverageAbsMax(
            moving_rate=moving_rate, quant_bits=activation_bits,
        )

    def forward(self, *inputs, **kwargs):
        out = self._layer(*inputs, **kwargs)
        if isinstance(out, (list, tuple)):
            return out
        return self._fake_quant_output(out)


class QuantStub(Layer):
    """Identity marker where quantization begins (reference:
    quant_layers.py QuantStub)."""

    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        return x


class QuantizedConv2DTranspose(Layer):
    """QAT wrapper over Conv2DTranspose (reference: quant_layers.py
    QuantizedConv2DTranspose): fake-quant input + weight, then the float op."""

    def __init__(self, layer, weight_bits=8, activation_bits=8,
                 moving_rate=0.9, *args, **kwargs):
        super().__init__()
        self._conv = layer
        self._fake_quant_input = FakeQuantMovingAverageAbsMax(
            moving_rate=moving_rate, quant_bits=activation_bits,
        )
        self._weight_bits = weight_bits

    def forward(self, x, output_size=None):
        import paddle_tpu.nn.functional as F

        x = self._fake_quant_input(x)
        w = fake_quant_channel_wise_abs_max(
            self._conv.weight, bits=self._weight_bits, axis=0
        )
        return F.conv2d_transpose(
            x, w, self._conv.bias, self._conv._stride, self._conv._padding,
            self._conv._output_padding, self._conv._groups,
            self._conv._dilation, self._conv._data_format, output_size,
        )
