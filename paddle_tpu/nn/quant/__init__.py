"""paddle.nn.quant — quantization-aware layers (reference surface)."""
from . import quant_layers  # noqa: F401
