"""Gradient clipping. Reference: python/paddle/fluid/clip.py
(ClipGradByValue, ClipGradByNorm, ClipGradByGlobalNorm — used by optimizers
via grad_clip=...).

Each built-in clip is defined by ONE pure function over the raw grad arrays
(`_pure()`), used by both callers:

  - the eager path (`Optimizer.step()` -> `_clip(params_grads)`) applies it
    to concrete grads between backward() and the fused update;
  - the whole-step capture controller (core/lazy.py) folds the SAME
    function into the captured forward+backward+update trace, so a step
    with grad clipping still replays as one donated XLA program, bitwise
    equal to the eager composition.

`clip_fingerprint()` is the capture controller's hashable identity of a
clip config (type + hyperparameters); it returns None for custom
subclasses (anything overriding `_clip`), which keeps them on the eager
3-program path rather than mis-capturing unknown semantics.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import no_grad
from ..core.tensor import Tensor

__all__ = [
    "ClipGradBase",
    "ClipGradByValue",
    "ClipGradByNorm",
    "ClipGradByGlobalNorm",
    "capture_clip_fn",
    "clip_fingerprint",
]


class ClipGradBase:
    def _pure(self):
        """Pure `list[grad arrays] -> list[clipped arrays]`, or None when
        the clip has no pure form (custom subclasses)."""
        return None

    def _fingerprint(self):
        """Hashable (type tag, hypers) identity, or None."""
        return None

    @no_grad()
    def _clip(self, params_grads):
        fn = self._pure()
        if fn is None:
            raise NotImplementedError
        # run the pure clip as ONE jitted program (cached on the instance;
        # retraces per grad-aval set). Besides costing one dispatch instead
        # of several, this keeps the eager clip bitwise-identical to the
        # SAME function inlined into the captured whole-step trace — XLA
        # fuses a jitted elementwise chain the same way in both, while
        # op-by-op eager execution could differ in the low bits. The cache
        # is keyed by the fingerprint: _pure() closes over the hypers, so a
        # mutated clip_norm must rebuild (the capture path re-fingerprints
        # live values and the two must stay in lockstep).
        fp = self._fingerprint()
        cached = self.__dict__.get("_jit_pure")
        jfn = cached[1] if cached is not None and cached[0] == fp else None
        if jfn is None:
            jfn = jax.jit(fn)
            self._jit_pure = (fp, jfn)
        from ..core.lazy import materialize

        clipped = jfn(
            [materialize(g._value) for _, g in params_grads if g is not None]
        )
        out, i = [], 0
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
            else:
                out.append((p, Tensor(clipped[i])))
                i += 1
        return out

    def __call__(self, params_grads):
        return self._clip(params_grads)


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def _pure(self):
        lo, hi = self.min, self.max

        def fn(g_vals):
            return [jnp.clip(g, lo, hi) for g in g_vals]

        return fn

    def _fingerprint(self):
        return ("value", self.min, self.max)


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _pure(self):
        clip_norm = self.clip_norm

        def fn(g_vals):
            out = []
            for g in g_vals:
                norm = jnp.sqrt(jnp.sum(jnp.square(g)))
                scale = jnp.where(norm > clip_norm, clip_norm / norm, 1.0)
                out.append(g * scale)
            return out

        return fn

    def _fingerprint(self):
        return ("norm", self.clip_norm)


class ClipGradByGlobalNorm(ClipGradBase):
    """reference: fluid/clip.py ClipGradByGlobalNorm; TP-aware variant lives
    in distributed.fleet (HybridParallelClipGrad)."""

    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = float(clip_norm)

    def _pure(self):
        clip_norm = self.clip_norm

        def fn(g_vals):
            sq = [jnp.sum(jnp.square(g.astype(jnp.float32))) for g in g_vals]
            if not sq:
                return []
            global_norm = jnp.sqrt(sum(sq))
            scale = clip_norm / jnp.maximum(global_norm, clip_norm)
            return [(g * scale).astype(g.dtype) for g in g_vals]

        return fn

    def _fingerprint(self):
        return ("global_norm", self.clip_norm)


_BUILTIN_CLIPS = (ClipGradByValue, ClipGradByNorm, ClipGradByGlobalNorm)


def _is_builtin(clip) -> bool:
    # exact type AND the stock _clip: a subclass (or an instance of a
    # builtin with an overridden _clip) has semantics the pure form does
    # not cover — such clips stay on the eager path
    return type(clip) in _BUILTIN_CLIPS and type(clip)._clip is ClipGradBase._clip


def capture_clip_fn(clip):
    """The pure clip function for the capture trace, or None when `clip` is
    not one of the stock clip configs."""
    if clip is None or not _is_builtin(clip):
        return None
    return clip._pure()


def clip_fingerprint(clip):
    """Hashable identity of a clip config for the capture step signature:
    ("none",) for no clip, (tag, hypers...) for the three built-in clips,
    None when the clip is custom (step is then never armed for capture)."""
    if clip is None:
        return ("none",)
    if not _is_builtin(clip):
        return None
    return clip._fingerprint()


GradientClipByValue = ClipGradByValue
GradientClipByNorm = ClipGradByNorm
GradientClipByGlobalNorm = ClipGradByGlobalNorm
