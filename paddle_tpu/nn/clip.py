"""Gradient clipping. Reference: python/paddle/fluid/clip.py
(ClipGradByValue, ClipGradByNorm, ClipGradByGlobalNorm — used by optimizers
via grad_clip=...)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.dispatch import no_grad
from ..core.tensor import Tensor


class ClipGradBase:
    def _clip(self, params_grads):
        raise NotImplementedError

    def __call__(self, params_grads):
        return self._clip(params_grads)


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    @no_grad()
    def _clip(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            out.append((p, Tensor(jnp.clip(g._value, self.min, self.max))))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    @no_grad()
    def _clip(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            norm = jnp.sqrt(jnp.sum(jnp.square(g._value)))
            scale = jnp.where(norm > self.clip_norm, self.clip_norm / norm, 1.0)
            out.append((p, Tensor(g._value * scale)))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    """reference: fluid/clip.py ClipGradByGlobalNorm; TP-aware variant lives
    in distributed.fleet (HybridParallelClipGrad)."""

    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = float(clip_norm)

    @no_grad()
    def _clip(self, params_grads):
        sq = [
            jnp.sum(jnp.square(g._value.astype(jnp.float32)))
            for _, g in params_grads
            if g is not None
        ]
        if not sq:
            return params_grads
        global_norm = jnp.sqrt(sum(sq))
        scale = self.clip_norm / jnp.maximum(global_norm, self.clip_norm)
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
            else:
                out.append((p, Tensor((g._value * scale).astype(g._value.dtype))))
        return out


GradientClipByValue = ClipGradByValue
GradientClipByNorm = ClipGradByNorm
GradientClipByGlobalNorm = ClipGradByGlobalNorm
