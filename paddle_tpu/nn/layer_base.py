"""nn.Layer — the module/parameter container.

Reference analogue: python/paddle/fluid/dygraph/layers.py:83 (Layer,
__call__:920 with hooks, create_parameter, sublayers, state_dict) and
framework.ParamBase. Parameters are Tensors with stop_gradient=False plus
trainable metadata; buffers mirror register_buffer semantics.
"""
from __future__ import annotations

import collections
from typing import Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

from ..core.dispatch import no_grad
from ..core.dtype import get_default_dtype
from ..core.tensor import Tensor


class HookRemoveHelper:
    def __init__(self, hooks, hook_id):
        self._hooks = hooks
        self._hook_id = hook_id

    def remove(self):
        self._hooks.pop(self._hook_id, None)


_unique_id = [0]
_hook_id_counter = iter(range(1 << 62))


def _name(prefix):
    _unique_id[0] += 1
    return f"{prefix}_{_unique_id[0]}"


class Parameter(Tensor):
    """Trainable tensor (reference: framework.ParamBase / EagerParamBase)."""

    def __init__(self, value, trainable=True, name=None):
        super().__init__(value, stop_gradient=not trainable, name=name or _name("param"))
        self.is_parameter = True
        self.persistable = True

    @property
    def trainable(self):
        return not self.stop_gradient

    @trainable.setter
    def trainable(self, v):
        self.stop_gradient = not v

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()


class Layer:
    """Base class for all network layers (reference: dygraph/layers.py:83)."""

    def __init__(self, name_scope=None, dtype=None):
        self.training = True
        self._dtype = dtype or get_default_dtype()
        self._parameters: "collections.OrderedDict[str, Parameter]" = collections.OrderedDict()
        self._sub_layers: "collections.OrderedDict[str, Layer]" = collections.OrderedDict()
        self._buffers: "collections.OrderedDict[str, Tensor]" = collections.OrderedDict()
        self._non_persistable_buffer_names = set()
        self._forward_pre_hooks = collections.OrderedDict()
        self._forward_post_hooks = collections.OrderedDict()
        self._casted_dtype = None  # set by amp O2 decorate / .to(dtype)
        self._full_name = name_scope or self.__class__.__name__.lower()

    # -- construction --------------------------------------------------------
    def create_parameter(
        self,
        shape,
        attr=None,
        dtype=None,
        is_bias=False,
        default_initializer=None,
    ) -> Parameter:
        """reference: layers.py create_parameter + LayerHelper; initializer
        defaults mirror fluid (Xavier for weights via layer classes, zeros
        for bias)."""
        from . import initializer as I

        dtype = dtype or self._dtype
        init = default_initializer
        trainable = True
        name = None
        if attr is not None and attr is not False:
            from .param_attr import ParamAttr

            if isinstance(attr, ParamAttr):
                init = attr.initializer or init
                trainable = attr.trainable
                name = attr.name
            elif isinstance(attr, I.Initializer):
                init = attr
        # set_global_initializer overrides layer defaults (but never an
        # explicit ParamAttr initializer) — reference fluid/initializer.py
        g = I._global_bias_init if is_bias else I._global_weight_init
        attr_init = init is not default_initializer or (
            attr is not None and getattr(attr, "initializer", None) is not None
        )
        if g is not None and not attr_init:
            init = g
        if init is None:
            init = I.Constant(0.0) if is_bias else I.XavierNormal()
        value = init._generate(tuple(int(s) for s in shape), dtype)
        return Parameter(value, trainable=trainable, name=name)

    def add_parameter(self, name, parameter):
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    # -- attribute magic -----------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ before assigning parameters")
            for d in (layers, buffers):
                if d is not None:
                    d.pop(name, None)
            params[name] = value
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call Layer.__init__ before assigning sublayers")
            for d in (params, buffers):
                if d is not None:
                    d.pop(name, None)
            layers[name] = value
        elif isinstance(value, Tensor) and buffers is not None and name in buffers:
            buffers[name] = value
        else:
            for d in (params, layers, buffers):
                if d is not None:
                    d.pop(name, None)
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for dname in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(dname)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute '{name}'"
        )

    def __delattr__(self, name):
        for dname in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(dname)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        return list(super().__dir__()) + list(self._parameters) + list(
            self._sub_layers
        ) + list(self._buffers)

    # -- traversal -----------------------------------------------------------
    def parameters(self, include_sublayers=True) -> List[Parameter]:
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_parameters(self, prefix="", include_sublayers=True):
        seen = set()
        for name, layer_prefix, layer in self._walk(prefix, include_sublayers):
            for pname, p in layer._parameters.items():
                if p is not None and id(p) not in seen:
                    seen.add(id(p))
                    yield (f"{layer_prefix}{pname}", p)

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        seen = set()
        for name, layer_prefix, layer in self._walk(prefix, include_sublayers):
            for bname, b in layer._buffers.items():
                if b is not None and id(b) not in seen:
                    seen.add(id(b))
                    yield (f"{layer_prefix}{bname}", b)

    def _walk(self, prefix="", include_sublayers=True):
        yield ("", prefix, self)
        if include_sublayers:
            for name, sub in self._sub_layers.items():
                if sub is None:
                    continue
                for item in sub._walk(f"{prefix}{name}.", True):
                    yield item

    def sublayers(self, include_self=False):
        out = [self] if include_self else []
        for _, sub in self.named_sublayers():
            out.append(sub)
        return out

    def named_sublayers(self, prefix="", include_self=False):
        if include_self:
            yield (prefix.rstrip("."), self)
        for name, sub in self._sub_layers.items():
            if sub is None:
                continue
            p = f"{prefix}{name}"
            yield (p, sub)
            for n2, s2 in sub.named_sublayers(prefix=p + "."):
                yield (n2, s2)

    def children(self):
        return [s for s in self._sub_layers.values() if s is not None]

    def named_children(self):
        return [(n, s) for n, s in self._sub_layers.items() if s is not None]

    def apply(self, fn):
        for layer in self.sublayers(include_self=True):
            fn(layer)
        return self

    def full_name(self):
        return self._full_name

    # -- modes ---------------------------------------------------------------
    def train(self):
        self.training = True
        for sub in self.children():
            sub.train()
        return self

    def eval(self):
        self.training = False
        for sub in self.children():
            sub.eval()
        return self

    # -- hooks ---------------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        hid = next(_hook_id_counter)
        self._forward_pre_hooks[hid] = hook
        return HookRemoveHelper(self._forward_pre_hooks, hid)

    def register_forward_post_hook(self, hook):
        hid = next(_hook_id_counter)
        self._forward_post_hooks[hid] = hook
        return HookRemoveHelper(self._forward_post_hooks, hid)

    # -- call ----------------------------------------------------------------
    def __call__(self, *inputs, **kwargs):
        """reference: layers.py:920 __call__ → _dygraph_call_func:887."""
        for hook in self._forward_pre_hooks.values():
            result = hook(self, inputs)
            if result is not None:
                inputs = result if isinstance(result, tuple) else (result,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            result = hook(self, inputs, outputs)
            if result is not None:
                outputs = result
        return outputs

    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    # -- state dict ----------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True, use_hook=True):
        # a compiled step may hold the authoritative (e.g. stage-stacked)
        # weights; let it materialize them into the live params first
        sync = getattr(self, "_lazy_param_sync", None)
        if sync is not None:
            sync()
        out = collections.OrderedDict() if destination is None else destination
        for name, p in self.named_parameters(include_sublayers=include_sublayers):
            out[name] = p
        seen = set()
        for _, layer_prefix, layer in self._walk("", include_sublayers):
            for bname, b in layer._buffers.items():
                if (
                    b is not None
                    and id(b) not in seen
                    # persistability is owned by the layer that registered it
                    and bname not in layer._non_persistable_buffer_names
                ):
                    seen.add(id(b))
                    out[f"{layer_prefix}{bname}"] = b
        return out

    def set_state_dict(self, state_dict, use_structured_name=True):
        """reference: layers.py set_state_dict — in-place set_value so
        optimizer references stay valid."""
        current = self.state_dict()
        missing, unexpected = [], []
        with no_grad():
            for name, tensor in current.items():
                if name in state_dict:
                    val = state_dict[name]
                    arr = val.numpy() if isinstance(val, Tensor) else np.asarray(val)
                    tensor.set_value(arr)
                else:
                    missing.append(name)
        for name in state_dict:
            if name not in current:
                unexpected.append(name)
        return missing, unexpected

    set_dict = set_state_dict
    load_dict = set_state_dict

    # -- dtype movement ------------------------------------------------------
    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            with no_grad():
                for p in self.parameters():
                    if p.dtype.is_floating_point:
                        p._value = p._value.astype(
                            __import__("paddle_tpu").core.dtype.to_np_dtype(dtype)
                        )
                for b in self.buffers():
                    if b.dtype.is_floating_point:
                        b._value = b._value.astype(
                            __import__("paddle_tpu").core.dtype.to_np_dtype(dtype)
                        )
        return self

    def float(self):
        return self.to(dtype="float32")

    def half(self):
        return self.to(dtype="float16")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, sub in self._sub_layers.items():
            sub_repr = repr(sub).split("\n")
            sub_repr = [sub_repr[0]] + ["  " + l for l in sub_repr[1:]]
            lines.append(f"  ({name}): " + "\n".join(sub_repr))
        main = f"{type(self).__name__}({extra}"
        if lines:
            return main + "\n" + "\n".join(lines) + "\n)"
        return main + ")"
