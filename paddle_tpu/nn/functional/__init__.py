"""paddle.nn.functional — functional neural-net API.

Reference analogue: python/paddle/nn/functional/ (activation.py, common.py,
conv.py, loss.py, norm.py, pooling.py, input.py). Dispatches to
paddle_tpu.ops.nn_ops through the autograd-aware dispatcher.
"""
from __future__ import annotations

import numpy as np

from ...core import random as _random
from ...core.dispatch import apply, is_grad_enabled
from ...core.tensor import Tensor, to_tensor
from ...ops import nn_ops as _nn
from ...ops import manipulation as _mp

__all__ = []  # populated at bottom


def _t(v):
    return tuple(v) if isinstance(v, (list, tuple)) else v


# ----------------------------- activations ---------------------------------
def relu(x, name=None):
    return apply(_nn.relu, x, op_name="relu")


def relu_(x, name=None):
    out = relu(x)
    x._value = out._value
    if out._grad_node is not None:
        x._grad_node = out._grad_node
        x._out_index = out._out_index
        x.stop_gradient = out.stop_gradient
    x._bump_version()
    return x


def relu6(x, name=None):
    return apply(_nn.relu6, x)


def leaky_relu(x, negative_slope=0.01, name=None):
    return apply(_nn.leaky_relu, x, negative_slope=negative_slope)


def prelu(x, weight, data_format="NCHW", name=None):
    w = weight
    if isinstance(w, Tensor) and w.size > 1 and x.ndim > 1:
        shape = [1] * x.ndim
        c_axis = 1 if data_format.startswith("NC") else x.ndim - 1
        shape[c_axis] = w.size
        w = w.reshape(shape)
    return apply(_nn.prelu, x, w)


def elu(x, alpha=1.0, name=None):
    return apply(_nn.elu, x, alpha=alpha)


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return apply(_nn.selu, x, scale=scale, alpha=alpha)


def celu(x, alpha=1.0, name=None):
    return apply(_nn.celu, x, alpha=alpha)


def gelu(x, approximate=False, name=None):
    return apply(_nn.gelu, x, approximate=approximate, op_name="gelu")


def sigmoid(x, name=None):
    return apply(_nn.sigmoid, x, op_name="sigmoid")


def silu(x, name=None):
    return apply(_nn.silu, x)


def swish(x, name=None):
    return apply(_nn.swish, x)


def mish(x, name=None):
    return apply(_nn.mish, x)


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return apply(_nn.softplus, x, beta=beta, threshold=threshold)


def softsign(x, name=None):
    return apply(_nn.softsign, x)


def softshrink(x, threshold=0.5, name=None):
    return apply(_nn.softshrink, x, threshold=threshold)


def hardshrink(x, threshold=0.5, name=None):
    return apply(_nn.hardshrink, x, threshold=threshold)


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return apply(_nn.hardtanh, x, min=min, max=max)


def hardsigmoid(x, slope=1.0 / 6.0, offset=0.5, name=None):
    return apply(_nn.hardsigmoid, x, slope=slope, offset=offset)


def hardswish(x, name=None):
    return apply(_nn.hardswish, x)


def tanhshrink(x, name=None):
    return apply(_nn.tanhshrink, x)


def thresholded_relu(x, threshold=1.0, name=None):
    return apply(_nn.thresholded_relu, x, threshold=threshold)


def log_sigmoid(x, name=None):
    return apply(_nn.log_sigmoid, x)


def maxout(x, groups, axis=1, name=None):
    return apply(_nn.maxout, x, groups=groups, axis=axis)


def glu(x, axis=-1, name=None):
    return apply(_nn.glu, x, axis=axis)


def tanh(x, name=None):
    import jax.numpy as jnp

    return apply(jnp.tanh, x, op_name="tanh")


def softmax(x, axis=-1, dtype=None, name=None):
    out = apply(_nn.softmax, x, axis=axis, op_name="softmax")
    return out.astype(dtype) if dtype is not None else out


def log_softmax(x, axis=-1, dtype=None, name=None):
    out = apply(_nn.log_softmax, x, axis=axis)
    return out.astype(dtype) if dtype is not None else out


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    return apply(
        _nn.gumbel_softmax, x, _random.next_key(), temperature=temperature,
        hard=hard, axis=axis,
    )


# ----------------------------- linear/conv ----------------------------------
def linear(x, weight, bias=None, name=None):
    if bias is None:
        return apply(_nn.linear, x, weight, op_name="linear")
    return apply(_nn.linear, x, weight, bias, op_name="linear")


def conv2d(
    x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
    data_format="NCHW", name=None,
):
    args = (x, weight) if bias is None else (x, weight, bias)
    return apply(
        _nn.conv2d, *args, stride=_t(stride), padding=_t(padding),
        dilation=_t(dilation), groups=groups, data_format=data_format,
        op_name="conv2d",
    )


def conv1d(
    x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
    data_format="NCL", name=None,
):
    args = (x, weight) if bias is None else (x, weight, bias)
    return apply(
        _nn.conv1d, *args, stride=_t(stride), padding=_t(padding),
        dilation=_t(dilation), groups=groups, data_format=data_format,
    )


def conv3d(
    x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
    data_format="NCDHW", name=None,
):
    args = (x, weight) if bias is None else (x, weight, bias)
    return apply(
        _nn.conv3d, *args, stride=_t(stride), padding=_t(padding),
        dilation=_t(dilation), groups=groups, data_format=data_format,
    )


def conv2d_transpose(
    x, weight, bias=None, stride=1, padding=0, output_padding=0, groups=1,
    dilation=1, data_format="NCHW", output_size=None, name=None,
):
    if output_size is not None:
        spatial = (
            tuple(x.shape[2:4]) if data_format == "NCHW" else tuple(x.shape[1:3])
        )
        output_padding = _transpose_out_padding(
            output_size, spatial, tuple(weight.shape[-2:]), stride, padding,
            dilation, output_padding, 2,
        )
    args = (x, weight) if bias is None else (x, weight, bias)
    return apply(
        _nn.conv2d_transpose, *args, stride=_t(stride), padding=_t(padding),
        output_padding=_t(output_padding), dilation=_t(dilation), groups=groups,
        data_format=data_format,
    )


# ----------------------------- pooling --------------------------------------
def max_pool2d(
    x, kernel_size, stride=None, padding=0, ceil_mode=False,
    return_mask=False, data_format="NCHW", name=None,
):
    if return_mask:
        if data_format != "NCHW":
            raise ValueError("return_mask requires NCHW (reference kernel layout)")
        return apply(
            _nn.max_pool2d_with_index, x, kernel_size=_t(kernel_size),
            stride=_t(stride), padding=_t(padding), ceil_mode=ceil_mode,
            op_name="max_pool2d_with_index",
        )
    return apply(
        _nn.max_pool2d, x, kernel_size=_t(kernel_size), stride=_t(stride),
        padding=_t(padding), ceil_mode=ceil_mode, data_format=data_format,
        op_name="max_pool2d",
    )


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    if data_format != "NCHW":
        raise ValueError("max_unpool2d requires NCHW")
    out_sz = tuple(output_size) if output_size is not None else None
    return apply(
        _nn.max_unpool2d, x, indices, kernel_size=_t(kernel_size),
        stride=_t(stride), padding=_t(padding), output_size=out_sz,
        op_name="max_unpool2d",
    )


def avg_pool2d(
    x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True,
    divisor_override=None, data_format="NCHW", name=None,
):
    return apply(
        _nn.avg_pool2d, x, kernel_size=_t(kernel_size), stride=_t(stride),
        padding=_t(padding), ceil_mode=ceil_mode, exclusive=exclusive,
        divisor_override=divisor_override, data_format=data_format,
        op_name="avg_pool2d",
    )


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return apply(
        _nn.adaptive_avg_pool2d, x, output_size=_t(output_size),
        data_format=data_format,
    )


def max_pool1d(x, kernel_size, stride=None, padding=0, ceil_mode=False, name=None):
    return apply(
        _nn.max_pool1d, x, kernel_size=_t(kernel_size), stride=_t(stride),
        padding=_t(padding), ceil_mode=ceil_mode,
    )


def adaptive_avg_pool1d(x, output_size, name=None):
    return apply(_nn.adaptive_avg_pool1d, x, output_size=output_size)


# ----------------------------- norm ------------------------------------------
def batch_norm(
    x, running_mean, running_var, weight, bias, training=False,
    momentum=0.9, epsilon=1e-05, data_format="NCHW", use_global_stats=None, name=None,
):
    """reference: nn/functional/norm.py batch_norm; running stats updated
    in-place like the reference's BatchNorm kernels (momentum semantics:
    running = momentum*running + (1-momentum)*batch)."""
    if use_global_stats is None:
        use_global_stats = not training
    if use_global_stats:
        return apply(
            _nn.batch_norm_infer, x, running_mean, running_var, weight, bias,
            epsilon=epsilon, data_format=data_format, op_name="batch_norm_infer",
        )
    out, bm, bv = apply(
        _nn.batch_norm_train, x, weight, bias, epsilon=epsilon,
        data_format=data_format, op_name="batch_norm",
    )
    # update running stats (no tape). Works under a jit trace too: traced
    # buffer values are threaded out of the compiled program by
    # StaticFunction / CompiledTrainStep (paddle_tpu.jit).
    if isinstance(running_mean, Tensor):
        with __import__("paddle_tpu").no_grad():
            running_mean._value = (
                running_mean._value * momentum + bm._value * (1 - momentum)
            )
            # the reference accumulates the *biased* batch variance into
            # running_var (phi/kernels/cpu/batch_norm_kernel.cc:152) — no
            # Bessel correction, so eval-mode outputs match it exactly
            running_var._value = (
                running_var._value * momentum + bv._value * (1 - momentum)
            )
    return out


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-05, name=None):
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    begin = x.ndim - len(normalized_shape)
    # None weight/bias pass straight through apply (empty pytree under jit)
    return apply(
        _nn.layer_norm, x, weight, bias, epsilon=epsilon, begin_norm_axis=begin,
        op_name="layer_norm",
    )


def group_norm(x, num_groups, epsilon=1e-05, weight=None, bias=None, data_format="NCHW", name=None):
    return apply(
        _nn.group_norm, x, weight, bias, num_groups=num_groups, epsilon=epsilon,
        data_format=data_format,
    )


def instance_norm(
    x, running_mean=None, running_var=None, weight=None, bias=None,
    use_input_stats=True, momentum=0.9, eps=1e-05, data_format="NCHW", name=None,
):
    args = [x]
    if weight is not None:
        args += [weight, bias]
    return apply(_nn.instance_norm, *args, epsilon=eps)


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    import jax.numpy as jnp

    def _norm(v, p, axis, epsilon):
        n = jnp.sum(jnp.abs(v) ** p, axis=axis, keepdims=True) ** (1.0 / p)
        return v / jnp.maximum(n, epsilon)

    return apply(_norm, x, p=float(p), axis=axis, epsilon=epsilon)


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW", name=None):
    import jax.numpy as jnp

    def _lrn(v, size, alpha, beta, k):
        sq = jnp.square(v)
        half = size // 2
        pads = [(0, 0)] * v.ndim
        pads[1] = (half, size - half - 1)
        padded = jnp.pad(sq, pads)
        acc = sum(
            padded[:, i : i + v.shape[1]] for i in range(size)
        )
        return v / jnp.power(k + alpha * acc / size, beta)

    return apply(_lrn, x, size=size, alpha=alpha, beta=beta, k=k)


# ----------------------------- dropout ---------------------------------------
def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    if not training or p == 0.0:
        # downscale_in_infer (the fluid-era default) scales at INFERENCE:
        # out = x * (1-p) in eval, unscaled masking in train
        if mode == "downscale_in_infer" and p > 0.0:
            return x * (1.0 - p)
        return x if isinstance(x, Tensor) else to_tensor(x)
    mask_shape = None
    if axis is not None:
        ndim = len(x.shape)
        axes = {a % ndim for a in ([axis] if isinstance(axis, int) else axis)}
        mask_shape = tuple(
            int(d) if i in axes else 1 for i, d in enumerate(x.shape)
        )
    return apply(
        _nn.dropout, x, _random.next_key(), p=float(p), mode=mode,
        mask_shape=mask_shape, op_name="dropout",
    )


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    if not training or p == 0.0:
        return x
    import jax
    import jax.numpy as jnp

    def _d2(v, key, *, p, data_format):
        if data_format == "NCHW":
            shape = (v.shape[0], v.shape[1], 1, 1)
        else:  # NHWC: channel last
            shape = (v.shape[0], 1, 1, v.shape[3])
        keep = jax.random.bernoulli(key, 1.0 - p, shape)
        return jnp.where(keep, v / (1.0 - p), 0.0).astype(v.dtype)

    return apply(_d2, x, _random.next_key(), p=float(p), data_format=data_format)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return x
    import jax
    import jax.numpy as jnp

    def _ad(v, key, p):
        alpha = 1.6732632423543772
        scale = 1.0507009873554805
        neg = -alpha * scale
        keep = jax.random.bernoulli(key, 1.0 - p, v.shape)
        a = (1.0 / (scale * ((1 - p) * (1 + p * alpha**2)) ** 0.5))
        b = -a * neg * p
        return a * jnp.where(keep, v, neg) + b

    return apply(_ad, x, _random.next_key(), p=float(p))


# ----------------------------- losses ----------------------------------------
def _reduce(loss, reduction):
    if reduction == "mean":
        return loss.mean()
    if reduction == "sum":
        return loss.sum()
    return loss


def cross_entropy(
    input, label, weight=None, ignore_index=-100, reduction="mean",
    soft_label=False, axis=-1, use_softmax=True, label_smoothing=0.0, name=None,
):
    """reference: nn/functional/loss.py cross_entropy →
    softmax_with_cross_entropy op (operators/softmax_with_cross_entropy_op)."""
    if label_smoothing > 0.0:
        num = input.shape[axis]
        if not soft_label:
            import paddle_tpu as paddle

            label = paddle.nn.functional.one_hot(label, num)
            soft_label = True
        label = label * (1.0 - label_smoothing) + label_smoothing / num
    # mean with a real ignore_index divides by the VALID count (handled in
    # the tail below); one predicate gates both that branch and the
    # fused-reduction exclusion so they cannot drift apart
    mean_needs_valid_count = (
        reduction == "mean" and ignore_index != -100 and not soft_label
    )
    if not use_softmax:
        lg = apply(
            lambda p: __import__("jax.numpy", fromlist=["log"]).log(
                __import__("jax.numpy", fromlist=["clip"]).clip(p, 1e-12, None)
            ),
            input,
        )
        loss = nll_from_logprob(lg, label, soft_label, ignore_index, axis)
    else:
        # fold mean/sum into the one fused op when no post-scaling applies —
        # the whole loss is then a single dispatched program (fwd and bwd)
        if (
            weight is None
            and reduction in ("mean", "sum")
            and not mean_needs_valid_count
        ):
            return apply(
                _nn.softmax_with_cross_entropy, input, label, soft_label=soft_label,
                ignore_index=ignore_index, axis=axis, reduction=reduction,
                op_name="softmax_with_cross_entropy",
            )
        loss = apply(
            _nn.softmax_with_cross_entropy, input, label, soft_label=soft_label,
            ignore_index=ignore_index, axis=axis, op_name="softmax_with_cross_entropy",
        )
    loss = loss.squeeze(axis) if loss.ndim > max(input.ndim - 1, 1) - 0 else loss
    if weight is not None and not soft_label:
        import jax.numpy as jnp

        def _w(wt, lb, *, ignore_index):
            w = jnp.take(wt, jnp.clip(lb, 0, None))
            # ignored positions contribute neither loss nor denominator
            return jnp.where(lb != ignore_index, w, 0.0)

        w = apply(_w, weight, label, ignore_index=ignore_index)
        loss = loss * w
        if reduction == "mean":
            return loss.sum() / w.sum().clip(min=1e-12)
    if mean_needs_valid_count:
        valid = (label != ignore_index).astype(loss.dtype)
        denom = valid.sum().clip(min=1.0)
        return loss.sum() / denom
    return _reduce(loss, reduction)


def nll_from_logprob(logp, label, soft_label, ignore_index, axis):
    import jax.numpy as jnp

    if soft_label:
        return apply(
            lambda lp, lb, axis: -jnp.sum(lb * lp, axis=axis), logp, label, axis=axis
        )
    return apply(
        lambda lp, lb, axis, ignore_index: jnp.where(
            lb != ignore_index,
            -jnp.take_along_axis(
                lp, jnp.expand_dims(jnp.clip(lb, 0, None).astype(jnp.int32), axis), axis=axis
            ).squeeze(axis),
            0.0,
        ),
        logp, label, axis=axis, ignore_index=ignore_index,
    )


def softmax_with_cross_entropy(
    logits, label, soft_label=False, ignore_index=-100, numeric_stable_mode=True,
    return_softmax=False, axis=-1,
):
    loss = apply(
        _nn.softmax_with_cross_entropy, logits, label, soft_label=soft_label,
        ignore_index=ignore_index, axis=axis,
    )
    if return_softmax:
        return loss, softmax(logits, axis=axis)
    return loss


def mse_loss(input, label, reduction="mean", name=None):
    return _reduce(apply(_nn.mse_loss, input, label), reduction)


def l1_loss(input, label, reduction="mean", name=None):
    return _reduce(apply(_nn.l1_loss, input, label), reduction)


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    return _reduce(apply(_nn.smooth_l1_loss, input, label, delta=delta), reduction)


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    loss = apply(_nn.bce_loss, input, label)
    if weight is not None:
        loss = loss * weight
    return _reduce(loss, reduction)


def binary_cross_entropy_with_logits(
    logit, label, weight=None, reduction="mean", pos_weight=None, name=None
):
    if pos_weight is not None:
        loss = apply(_nn.bce_with_logits, logit, label, pos_weight)
    else:
        loss = apply(_nn.bce_with_logits, logit, label)
    if weight is not None:
        loss = loss * weight
    return _reduce(loss, reduction)


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):
    if weight is not None:
        loss = apply(_nn.nll_loss, input, label, weight, ignore_index=ignore_index)
    else:
        loss = apply(_nn.nll_loss, input, label, ignore_index=ignore_index)
    return _reduce(loss, reduction)


def kl_div(input, label, reduction="mean", name=None):
    loss = apply(_nn.kl_div, input, label)
    if reduction == "batchmean":
        return loss.sum() / input.shape[0]
    return _reduce(loss, reduction)


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):
    return _reduce(
        apply(_nn.margin_ranking_loss, input, other, label, margin=margin), reduction
    )


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    return _reduce(
        apply(_nn.hinge_embedding_loss, input, label, margin=margin), reduction
    )


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    return apply(_nn.cosine_similarity, x1, x2, axis=axis, eps=eps)


def sigmoid_focal_loss(
    logit, label, normalizer=None, alpha=0.25, gamma=2.0, reduction="sum", name=None
):
    import jax
    import jax.numpy as jnp

    def _focal(lg, lb, alpha, gamma):
        p = jax.nn.sigmoid(lg)
        ce = _nn.bce_with_logits(lg, lb)
        p_t = p * lb + (1 - p) * (1 - lb)
        a_t = alpha * lb + (1 - alpha) * (1 - lb)
        return a_t * ((1 - p_t) ** gamma) * ce

    loss = apply(_focal, logit, label, alpha=alpha, gamma=gamma)
    if normalizer is not None:
        loss = loss / normalizer
    return _reduce(loss, reduction)


# ----------------------------- embedding / inputs ----------------------------
def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    return apply(_nn.embedding, x, weight, padding_idx=padding_idx, op_name="embedding")


def one_hot(x, num_classes, name=None):
    from ...ops import creation as _c

    return apply(_c.one_hot, x, num_classes=num_classes, differentiable=False)


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    return apply(_nn.label_smooth, label, epsilon=epsilon)


# ----------------------------- shape / vision --------------------------------
def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    return apply(
        _mp.pad, x, pad=tuple(int(p) for p in pad), mode=mode, value=value,
        data_format=data_format, op_name="pad",
    )


def interpolate(
    x, size=None, scale_factor=None, mode="nearest", align_corners=False,
    align_mode=0, data_format="NCHW", name=None,
):
    return apply(
        _nn.interpolate, x,
        size=None if size is None else tuple(int(s) for s in size),
        scale_factor=_t(scale_factor), mode=mode, align_corners=align_corners,
        data_format=data_format,
    )


def upsample(x, size=None, scale_factor=None, mode="nearest", align_corners=False,
             align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode, data_format)


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    return apply(_nn.pixel_shuffle, x, upscale_factor=upscale_factor, data_format=data_format)


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros", align_corners=True, name=None):
    return apply(
        _nn.grid_sample, x, grid, mode=mode, padding_mode=padding_mode,
        align_corners=align_corners,
    )


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    return apply(
        _mp.unfold, x, kernel_sizes=_t(kernel_sizes), strides=_t(strides),
        paddings=_t(paddings), dilations=_t(dilations),
    )


# ----------------------------- attention -------------------------------------
def scaled_dot_product_attention(
    query, key, value, attn_mask=None, dropout_p=0.0, is_causal=False,
    training=True, name=None,
):
    dropout_key = (
        _random.next_key() if (dropout_p > 0.0 and training) else None
    )
    # pick the lowering HERE (not inside the op) so the per-op jit cache
    # keys on distinct function objects and FLAGS_use_flash_attention
    # toggles take effect immediately
    from ...core import flags as _flags
    from ...parallel import topology as _topo

    # a pallas_call has no GSPMD partitioning rule: under a >1-device mesh
    # XLA would replicate q/k/v (all-gathering sharded batch/seq/heads), so
    # sharded programs keep the dense einsum path, which GSPMD partitions.
    _mesh = _topo.get_mesh()
    _single_device = _mesh is None or _mesh.devices.size == 1
    if (
        _flags.flag("use_flash_attention")
        and _single_device
        and attn_mask is None
        and dropout_key is None
        and _nn.flash_attention_eligible(query.shape, key.shape, value.shape)
    ):
        return apply(
            _nn.flash_scaled_dot_product_attention, query, key, value,
            is_causal=is_causal, op_name="flash_sdpa",
        )
    return apply(
        _nn.scaled_dot_product_attention, query, key, value, attn_mask,
        dropout_key, is_causal=is_causal, dropout_p=dropout_p, op_name="sdpa",
    )


__all__ = [n for n in dir() if not n.startswith("_")]


# ---------------------------------------------------------------------------
# N-d pooling / conv-transpose / fold + misc surface completion
# (reference: nn/functional/{pooling,conv,common,loss,extension}.py)
# ---------------------------------------------------------------------------
def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, name=None):
    return apply(
        _nn.avg_pool1d, x, kernel_size=_t(kernel_size), stride=_t(stride),
        padding=_t(padding), ceil_mode=ceil_mode, exclusive=exclusive,
        op_name="avg_pool1d",
    )


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    return apply(
        _nn.avg_pool3d, x, kernel_size=_t(kernel_size), stride=_t(stride),
        padding=_t(padding), ceil_mode=ceil_mode, exclusive=exclusive,
        divisor_override=divisor_override, data_format=data_format,
        op_name="avg_pool3d",
    )


def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCDHW", name=None):
    if return_mask:
        raise NotImplementedError(
            "max_pool3d(return_mask=True): 3-D argmax masks are not "
            "implemented; use max_pool2d(return_mask=True) per-slice"
        )
    return apply(
        _nn.max_pool3d, x, kernel_size=_t(kernel_size), stride=_t(stride),
        padding=_t(padding), ceil_mode=ceil_mode, data_format=data_format,
        op_name="max_pool3d",
    )


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return apply(
        _nn.adaptive_avg_pool3d, x, output_size=_t(output_size),
        data_format=data_format, op_name="adaptive_avg_pool3d",
    )


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    if return_mask:
        raise NotImplementedError("adaptive_max_pool1d(return_mask=True)")
    return apply(
        _nn.adaptive_max_pool1d, x, output_size=_t(output_size),
        op_name="adaptive_max_pool1d",
    )


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    if return_mask:
        raise NotImplementedError("adaptive_max_pool2d(return_mask=True)")
    return apply(
        _nn.adaptive_max_pool2d, x, output_size=_t(output_size),
        op_name="adaptive_max_pool2d",
    )


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    if return_mask:
        raise NotImplementedError("adaptive_max_pool3d(return_mask=True)")
    return apply(
        _nn.adaptive_max_pool3d, x, output_size=_t(output_size),
        op_name="adaptive_max_pool3d",
    )


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format="NCL", name=None):
    if data_format != "NCL":
        raise ValueError(
            f"max_unpool1d supports NCL only (reference unpool kernel "
            f"layout), got {data_format}"
        )
    return apply(
        _nn.max_unpool1d, x, indices, kernel_size=_t(kernel_size),
        stride=_t(stride), padding=_t(padding),
        output_size=None if output_size is None else tuple(output_size),
        op_name="max_unpool1d",
    )


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format="NCDHW", name=None):
    if data_format != "NCDHW":
        raise ValueError(
            f"max_unpool3d supports NCDHW only (reference unpool kernel "
            f"layout), got {data_format}"
        )
    return apply(
        _nn.max_unpool3d, x, indices, kernel_size=_t(kernel_size),
        stride=_t(stride), padding=_t(padding),
        output_size=None if output_size is None else tuple(output_size),
        op_name="max_unpool3d",
    )


def _transpose_out_padding(output_size, in_spatial, k, stride, padding,
                           dilation, output_padding, nd):
    """Derive output_padding from a requested output_size (reference:
    conv_transpose output_size semantics: out = (in-1)*s - 2p + d*(k-1) + 1
    + output_padding, with 0 <= output_padding < stride)."""
    def tup(v):
        return tuple(v) if isinstance(v, (tuple, list)) else (v,) * nd

    if output_size is None:
        return _t(output_padding)
    if hasattr(output_size, "numpy"):
        output_size = [int(v) for v in output_size.numpy()]
    want = tuple(int(v) for v in output_size)[-nd:]
    s, p, d = tup(stride), tup(padding), tup(dilation)
    out_pad = []
    for i in range(nd):
        base = (in_spatial[i] - 1) * s[i] - 2 * p[i] + d[i] * (k[i] - 1) + 1
        extra = want[i] - base
        # valid range mirrors the reference: 0 <= output_padding < max(s, d)
        if not (0 <= extra < max(s[i], d[i], 1)):
            raise ValueError(
                f"output_size {want} unreachable from input spatial "
                f"{tuple(in_spatial)} (base {base}, stride {s[i]})"
            )
        out_pad.append(extra)
    return tuple(out_pad)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCL", name=None):
    if output_size is not None:
        output_padding = _transpose_out_padding(
            output_size, (x.shape[2] if data_format == "NCL" else x.shape[1],),
            (weight.shape[-1],), stride, padding, dilation, output_padding, 1,
        )
    return apply(
        _nn.conv1d_transpose, x, weight, bias, stride=_t(stride),
        padding=_t(padding), output_padding=_t(output_padding),
        dilation=_t(dilation), groups=groups, data_format=data_format,
        op_name="conv1d_transpose",
    )


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCDHW", name=None):
    if output_size is not None:
        spatial = (
            tuple(x.shape[2:5]) if data_format == "NCDHW" else tuple(x.shape[1:4])
        )
        output_padding = _transpose_out_padding(
            output_size, spatial, tuple(weight.shape[-3:]), stride, padding,
            dilation, output_padding, 3,
        )
    return apply(
        _nn.conv3d_transpose, x, weight, bias, stride=_t(stride),
        padding=_t(padding), output_padding=_t(output_padding),
        dilation=_t(dilation), groups=groups, data_format=data_format,
        op_name="conv3d_transpose",
    )


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1,
         name=None):
    return apply(
        _nn.fold, x, output_sizes=_t(output_sizes),
        kernel_sizes=_t(kernel_sizes), strides=_t(strides),
        paddings=_t(paddings), dilations=_t(dilations), op_name="fold",
    )


def diag_embed(x, offset=0, dim1=-2, dim2=-1, name=None):
    return apply(
        _nn.diag_embed, x, offset=offset, dim1=dim1, dim2=dim2,
        op_name="diag_embed",
    )


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    if maxlen is None:
        maxlen = int(np.asarray(x.numpy()).max())
    return apply(
        _nn.sequence_mask, x, maxlen=int(maxlen), dtype=str(dtype),
        differentiable=False, op_name="sequence_mask",
    )


def gather_tree(ids, parents):
    return apply(_nn.gather_tree, ids, parents, differentiable=False,
                 op_name="gather_tree")


def temporal_shift(x, seg_num, shift_ratio=0.25, name=None,
                   data_format="NCHW"):
    return apply(
        _nn.temporal_shift, x, seg_num=int(seg_num),
        shift_ratio=float(shift_ratio), data_format=data_format,
        op_name="temporal_shift",
    )


def affine_grid(theta, out_shape, align_corners=True, name=None):
    if hasattr(out_shape, "numpy"):
        out_shape = [int(v) for v in out_shape.numpy()]
    return apply(
        _nn.affine_grid, theta, out_shape=tuple(int(v) for v in out_shape),
        align_corners=align_corners, op_name="affine_grid",
    )


def bilinear(x1, x2, weight, bias=None, name=None):
    return apply(_nn.bilinear, x1, x2, weight, bias, op_name="bilinear")


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    return apply(
        _nn.pixel_unshuffle, x, downscale_factor=int(downscale_factor),
        data_format=data_format, op_name="pixel_unshuffle",
    )


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    """Drop whole 3-D channel volumes (reference: nn/functional/common.py
    dropout3d)."""
    if not training or p == 0.0:
        return x
    import jax
    import jax.numpy as jnp

    def _d3(v, key, *, p, data_format):
        if data_format == "NCDHW":
            shape = (v.shape[0], v.shape[1], 1, 1, 1)
        else:
            shape = (v.shape[0], 1, 1, 1, v.shape[4])
        keep = jax.random.bernoulli(key, 1.0 - p, shape)
        return jnp.where(keep, v / (1.0 - p), 0.0).astype(v.dtype)

    return apply(
        _d3, x, _random.next_key(), p=float(p), data_format=data_format,
        op_name="dropout3d",
    )


def zeropad2d(x, padding, data_format="NCHW", name=None):
    return pad(x, padding, mode="constant", value=0.0,
               data_format=data_format)


# in-place activation variants (reference: *_ in nn/functional/activation.py)
def _make_inplace(fn):
    def inner(x, *args, **kwargs):
        out = fn(x, *args, **kwargs)
        x._value = out._value
        if out._grad_node is not None:
            x._grad_node = out._grad_node
            x._out_index = out._out_index
            x.stop_gradient = out.stop_gradient
        x._bump_version()
        return x

    return inner


elu_ = _make_inplace(elu)
tanh_ = _make_inplace(tanh)
softmax_ = _make_inplace(softmax)


# losses
def square_error_cost(input, label):
    return apply(_nn.square_error_cost, input, label,
                 op_name="square_error_cost")


def log_loss(input, label, epsilon=1e-4, name=None):
    return apply(_nn.log_loss, input, label, epsilon=float(epsilon),
                 op_name="log_loss")


def dice_loss(input, label, epsilon=1e-5, name=None):
    return apply(_nn.dice_loss, input, label, epsilon=float(epsilon),
                 op_name="dice_loss")


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    return apply(_nn.npair_loss, anchor, positive, labels,
                 l2_reg=float(l2_reg), op_name="npair_loss")


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC loss over [T, B, C] logits (reference: nn/functional/loss.py
    ctc_loss → warpctc, which softmaxes internally — so raw logits in)."""
    lp = log_softmax(log_probs, axis=-1)
    loss = apply(
        _nn.ctc_loss_per_sample, lp, labels, input_lengths, label_lengths,
        blank=int(blank), op_name="ctc_loss",
    )
    if norm_by_times:
        loss = loss / input_lengths.astype(loss.dtype)
    if reduction == "mean":
        # reference divides each sample by its label length before averaging
        denom = label_lengths.astype(loss.dtype).clip(min=1.0)
        return (loss / denom).mean()
    if reduction == "sum":
        return loss.sum()
    return loss


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    return apply(
        _nn.hsigmoid_loss_op, input, label, weight, bias,
        path_table, path_code, num_classes=int(num_classes),
        op_name="hsigmoid_loss",
    )


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean"):
    loss, sm = apply(
        _nn.margin_cross_entropy_op, logits, label, margin1=float(margin1),
        margin2=float(margin2), margin3=float(margin3), scale=float(scale),
        op_name="margin_cross_entropy",
    )
    if reduction == "mean":
        loss = loss.mean()
    elif reduction == "sum":
        loss = loss.sum()
    return (loss, sm) if return_softmax else loss


def sparse_attention(query, key, value, sparse_csr_offset,
                     sparse_csr_columns, key_padding_mask=None,
                     attn_mask=None, name=None):
    if key_padding_mask is not None or attn_mask is not None:
        raise NotImplementedError(
            "sparse_attention masks beyond the CSR pattern"
        )
    return apply(
        _nn.sparse_attention_op, query, key, value, sparse_csr_offset,
        sparse_csr_columns, op_name="sparse_attention",
    )


def class_center_sample(label, num_classes, num_samples, group=None):
    """Sample negative class centers (reference:
    operators/class_center_sample_op.cu): keep all positive classes, fill
    with sampled negatives up to num_samples; remap labels into the sampled
    index space. Data-dependent sizes → host-side op."""
    lab = np.asarray(label.numpy()).reshape(-1)
    pos = np.unique(lab)
    rest = num_samples - len(pos)
    if rest > 0:
        import jax as _jax

        neg_pool = np.setdiff1d(np.arange(num_classes), pos)
        # draw through the framework generator so paddle.seed reproduces runs
        seed = int(_jax.random.randint(_random.next_key(), (), 0, 2**31 - 1))
        rng = np.random.default_rng(seed)
        sampled = np.concatenate([pos, rng.permutation(neg_pool)[:rest]])
    else:
        sampled = pos
    remap = np.full(num_classes, -1, np.int64)
    remap[sampled] = np.arange(len(sampled))
    return to_tensor(remap[lab]), to_tensor(sampled.astype(np.int64))


__all__ = [n for n in dir() if not n.startswith("_")]
