"""paddle.nn.utils — parametrization helpers + parameter/vector utilities.

Reference analogue: python/paddle/nn/utils/ (weight_norm_hook.py,
spectral_norm_hook.py, transform_parameters.py, clip_grad_norm_.py).
"""
from __future__ import annotations

import numpy as np

from ..utils_fns import (  # noqa: F401
    clip_grad_norm_,
    clip_grad_value_,
    parameters_to_vector,
    vector_to_parameters,
)

__all__ = [
    "weight_norm",
    "remove_weight_norm",
    "spectral_norm",
    "parameters_to_vector",
    "vector_to_parameters",
    "clip_grad_norm_",
    "clip_grad_value_",
]


def _norm_except_dim(v, dim):
    import paddle_tpu as paddle

    if dim is None or v.ndim == 1:
        return paddle.sqrt((v * v).sum())
    axes = [i for i in range(v.ndim) if i != dim]
    shape = [1] * v.ndim
    shape[dim] = v.shape[dim]
    return paddle.sqrt((v * v).sum(axis=axes)).reshape(shape)


class _WeightNormHook:
    """reference: nn/utils/weight_norm_hook.py WeightNorm — reparameterize
    `name` as g * v / ||v|| recomputed on every forward."""

    def __init__(self, name, dim):
        self.name = name
        self.dim = dim

    def compute_weight(self, layer):
        g = getattr(layer, self.name + "_g")
        v = getattr(layer, self.name + "_v")
        return g * (v / _norm_except_dim(v, self.dim))

    def __call__(self, layer, inputs):
        setattr(layer, self.name, self.compute_weight(layer))
        return inputs


def weight_norm(layer, name="weight", dim=0):
    """Apply weight normalization to `layer.name` (reference:
    nn/utils/weight_norm_hook.py weight_norm)."""
    import paddle_tpu as paddle

    w = getattr(layer, name)
    if dim is not None and dim < 0:
        dim += w.ndim
    hook = _WeightNormHook(name, dim)
    with paddle.no_grad():
        g0 = _norm_except_dim(w, dim)
    # replace the parameter with (g, v) and keep `name` a plain attribute
    del layer._parameters[name]
    layer.add_parameter(name + "_g", paddle.nn.Parameter(g0._value))
    layer.add_parameter(name + "_v", paddle.nn.Parameter(w._value))
    setattr(layer, name, hook.compute_weight(layer))
    handle = layer.register_forward_pre_hook(hook)
    layer._weight_norm_hooks = getattr(layer, "_weight_norm_hooks", {})
    layer._weight_norm_hooks[name] = (hook, handle)
    return layer


def remove_weight_norm(layer, name="weight"):
    """Fold g*v/||v|| back into a single parameter (reference:
    weight_norm_hook.py remove_weight_norm)."""
    import paddle_tpu as paddle

    hooks = getattr(layer, "_weight_norm_hooks", {})
    if name not in hooks:
        raise ValueError(f"weight_norm of '{name}' not found on {layer}")
    hook, handle = hooks.pop(name)
    with paddle.no_grad():
        w = hook.compute_weight(layer)
    handle.remove()
    del layer._parameters[name + "_g"]
    del layer._parameters[name + "_v"]
    if name in layer.__dict__:
        del layer.__dict__[name]
    layer.add_parameter(name, paddle.nn.Parameter(w._value))
    return layer


class _SpectralNormHook:
    """reference: nn/utils/spectral_norm_hook.py SpectralNorm — divide the
    weight by its top singular value, estimated by power iteration on a
    persistent u buffer."""

    def __init__(self, name, n_power_iterations, eps, dim):
        self.name = name
        self.power_iters = n_power_iterations
        self.eps = eps
        self.dim = dim

    def _mat(self, w):
        import paddle_tpu as paddle

        if self.dim != 0:
            perm = [self.dim] + [i for i in range(w.ndim) if i != self.dim]
            w = w.transpose(perm)
        return w.reshape([w.shape[0], -1])

    def compute_weight(self, layer):
        import paddle_tpu as paddle

        w_orig = getattr(layer, self.name + "_orig")
        u = getattr(layer, self.name + "_u")
        mat = self._mat(w_orig)
        with paddle.no_grad():
            v = None
            for _ in range(max(1, self.power_iters)):
                v = paddle.matmul(mat, u, transpose_x=True)
                v = v / (paddle.norm(v) + self.eps)
                u_new = paddle.matmul(mat, v)
                u_new = u_new / (paddle.norm(u_new) + self.eps)
                u.set_value(u_new._value)
        sigma = paddle.matmul(u.detach().unsqueeze(0),
                              paddle.matmul(mat, v.detach().unsqueeze(1)))
        return w_orig / sigma.reshape([])

    def __call__(self, layer, inputs):
        setattr(layer, self.name, self.compute_weight(layer))
        return inputs


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12,
                  dim=None):
    """Apply spectral normalization to `layer.name` (reference:
    nn/utils/spectral_norm_hook.py spectral_norm)."""
    import paddle_tpu as paddle

    w = getattr(layer, name)
    if dim is None:
        # reference default: dim 1 for Linear (in,out layout), else 0
        dim = 1 if type(layer).__name__ in ("Linear",) else 0
    hook = _SpectralNormHook(name, n_power_iterations, eps, dim)
    h = w.shape[dim]
    del layer._parameters[name]
    layer.add_parameter(name + "_orig", paddle.nn.Parameter(w._value))
    u0 = paddle.randn([h])
    u0 = u0 / (paddle.norm(u0) + eps)
    layer.register_buffer(name + "_u", u0)
    setattr(layer, name, hook.compute_weight(layer))
    handle = layer.register_forward_pre_hook(hook)
    layer._spectral_norm_hooks = getattr(layer, "_spectral_norm_hooks", {})
    layer._spectral_norm_hooks[name] = (hook, handle)
    return layer
