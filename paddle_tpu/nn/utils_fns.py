"""nn.utils — clip_grad_norm_, clip_grad_value_, parameters_to_vector.

Reference: python/paddle/nn/utils/clip_grad_norm_.py etc.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.dispatch import no_grad
from ..core.tensor import Tensor


@no_grad()
def clip_grad_norm_(parameters, max_norm, norm_type=2.0, error_if_nonfinite=False):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    grads = [p.grad for p in parameters if p.grad is not None]
    if not grads:
        return Tensor(jnp.zeros(()))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(g._value)) for g in grads]))
    else:
        total = jnp.sum(
            jnp.stack([jnp.sum(jnp.abs(g._value) ** norm_type) for g in grads])
        ) ** (1.0 / norm_type)
    if error_if_nonfinite and not bool(jnp.isfinite(total)):
        raise RuntimeError("non-finite total norm")
    scale = jnp.minimum(max_norm / (total + 1e-6), 1.0)
    for g in grads:
        g._value = g._value * scale
    return Tensor(total)


@no_grad()
def clip_grad_value_(parameters, clip_value):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    for p in parameters:
        if p.grad is not None:
            p.grad._value = jnp.clip(p.grad._value, -clip_value, clip_value)


def parameters_to_vector(parameters, name=None):
    vals = [p._value.reshape(-1) for p in parameters]
    return Tensor(jnp.concatenate(vals))


def vector_to_parameters(vec, parameters, name=None):
    offset = 0
    for p in parameters:
        n = p.size
        p.set_value(vec._value[offset : offset + n].reshape(tuple(p.shape)))
        offset += n
