"""Transformer layers.

Reference analogue: python/paddle/nn/layer/transformer.py (MultiHeadAttention,
TransformerEncoder/Decoder[Layer], Transformer). The attention core lowers to
ops/nn_ops.scaled_dot_product_attention (XLA-fused; Pallas flash-attention
kernel used by the models/ GPT path for long sequences).
"""
from __future__ import annotations

import collections

from .. import functional as F
from ..layer_base import Layer
from .common import Dropout, Linear
from .norm import LayerNorm


def _convert_attention_mask(attn_mask, dtype):
    if attn_mask is None:
        return None
    if attn_mask.dtype.name == "bool":
        import paddle_tpu as paddle

        zero = paddle.zeros_like(attn_mask.astype(dtype))
        neg = paddle.full_like(zero, -1e9 if dtype != "bfloat16" else -1e9)
        return paddle.where(attn_mask, zero, neg)
    return attn_mask.astype(dtype)


class MultiHeadAttention(Layer):
    """reference: nn/layer/transformer.py MultiHeadAttention."""

    Cache = collections.namedtuple("Cache", ["k", "v"])
    StaticCache = collections.namedtuple("StaticCache", ["k", "v"])

    def __init__(self, embed_dim, num_heads, dropout=0.0, kdim=None, vdim=None,
                 need_weights=False, weight_attr=None, bias_attr=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        assert self.head_dim * num_heads == embed_dim
        self.dropout = dropout
        self.need_weights = need_weights
        kdim = kdim or embed_dim
        vdim = vdim or embed_dim
        self.q_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        self.k_proj = Linear(kdim, embed_dim, weight_attr, bias_attr)
        self.v_proj = Linear(vdim, embed_dim, weight_attr, bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        import paddle_tpu as paddle

        key = query if key is None else key
        value = query if value is None else value
        b, qlen = query.shape[0], query.shape[1]
        q = self.q_proj(query).reshape([b, qlen, self.num_heads, self.head_dim])
        if isinstance(cache, self.StaticCache):
            k, v = cache.k, cache.v
        else:
            klen = key.shape[1]
            k = self.k_proj(key).reshape([b, klen, self.num_heads, self.head_dim])
            v = self.v_proj(value).reshape([b, klen, self.num_heads, self.head_dim])
            if isinstance(cache, self.Cache):
                k = paddle.concat([cache.k, k], axis=1)
                v = paddle.concat([cache.v, v], axis=1)
                cache = self.Cache(k, v)

        mask = _convert_attention_mask(attn_mask, q.dtype.name)
        out = F.scaled_dot_product_attention(q, k, v, attn_mask=mask)
        out = out.reshape([b, qlen, self.embed_dim])
        out = self.out_proj(out)
        if self.dropout and self.training:
            out = F.dropout(out, self.dropout, training=True)
        outs = [out]
        if self.need_weights:
            outs.append(None)
        if cache is not None and isinstance(cache, self.Cache):
            outs.append(cache)
        return out if len(outs) == 1 else tuple(outs)

    def gen_cache(self, key, value=None, type=None):
        import paddle_tpu as paddle

        if type == MultiHeadAttention.StaticCache:
            b, klen = key.shape[0], key.shape[1]
            k = self.k_proj(key).reshape([b, klen, self.num_heads, self.head_dim])
            v = self.v_proj(value if value is not None else key).reshape(
                [b, klen, self.num_heads, self.head_dim]
            )
            return self.StaticCache(k, v)
        b = key.shape[0]
        k = paddle.zeros([b, 0, self.num_heads, self.head_dim], dtype="float32")
        return self.Cache(k, k)


class TransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(
            d_model, nhead, dropout=attn_dropout if attn_dropout is not None else dropout,
            weight_attr=weight_attr, bias_attr=bias_attr,
        )
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.dropout_act = Dropout(act_dropout if act_dropout is not None else dropout)
        self.activation = activation

    def forward(self, src, src_mask=None, cache=None):
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        if cache is None:
            src = self.self_attn(src, src, src, src_mask)
        else:
            src, cache = self.self_attn(src, src, src, src_mask, cache)
        src = residual + self.dropout1(src)
        if not self.normalize_before:
            src = self.norm1(src)
        residual = src
        if self.normalize_before:
            src = self.norm2(src)
        src = self.linear2(self.dropout_act(getattr(F, self.activation)(self.linear1(src))))
        src = residual + self.dropout2(src)
        if not self.normalize_before:
            src = self.norm2(src)
        return src if cache is None else (src, cache)


class TransformerEncoder(Layer):
    def __init__(self, encoder_layer, num_layers, norm=None):
        super().__init__()
        from .common import LayerList
        import copy

        self.layers = LayerList(
            [encoder_layer] + [copy.deepcopy(encoder_layer) for _ in range(num_layers - 1)]
        )
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, src, src_mask=None, cache=None):
        out = src
        new_caches = []
        for i, layer in enumerate(self.layers):
            if cache is None:
                out = layer(out, src_mask)
            else:
                out, c = layer(out, src_mask, cache[i])
                new_caches.append(c)
        if self.norm is not None:
            out = self.norm(out)
        return out if cache is None else (out, new_caches)


class TransformerDecoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead,
                                            dropout=attn_dropout if attn_dropout is not None else dropout)
        self.cross_attn = MultiHeadAttention(d_model, nhead,
                                             dropout=attn_dropout if attn_dropout is not None else dropout)
        self.linear1 = Linear(d_model, dim_feedforward)
        self.linear2 = Linear(dim_feedforward, d_model)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.norm3 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.dropout3 = Dropout(dropout)
        self.activation = activation

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None, cache=None):
        residual = tgt
        if self.normalize_before:
            tgt = self.norm1(tgt)
        tgt = self.self_attn(tgt, tgt, tgt, tgt_mask)
        tgt = residual + self.dropout1(tgt)
        if not self.normalize_before:
            tgt = self.norm1(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm2(tgt)
        tgt = self.cross_attn(tgt, memory, memory, memory_mask)
        tgt = residual + self.dropout2(tgt)
        if not self.normalize_before:
            tgt = self.norm2(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm3(tgt)
        tgt = self.linear2(self.dropout3(getattr(F, self.activation)(self.linear1(tgt))))
        tgt = residual + self.dropout3(tgt)
        if not self.normalize_before:
            tgt = self.norm3(tgt)
        return tgt


class TransformerDecoder(Layer):
    def __init__(self, decoder_layer, num_layers, norm=None):
        super().__init__()
        from .common import LayerList
        import copy

        self.layers = LayerList(
            [decoder_layer] + [copy.deepcopy(decoder_layer) for _ in range(num_layers - 1)]
        )
        self.norm = norm

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None, cache=None):
        out = tgt
        for layer in self.layers:
            out = layer(out, memory, tgt_mask, memory_mask)
        if self.norm is not None:
            out = self.norm(out)
        return out


class Transformer(Layer):
    def __init__(self, d_model=512, nhead=8, num_encoder_layers=6,
                 num_decoder_layers=6, dim_feedforward=2048, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 custom_encoder=None, custom_decoder=None):
        super().__init__()
        self.encoder = custom_encoder or TransformerEncoder(
            TransformerEncoderLayer(d_model, nhead, dim_feedforward, dropout,
                                    activation, attn_dropout, act_dropout,
                                    normalize_before),
            num_encoder_layers,
            LayerNorm(d_model) if normalize_before else None,
        )
        self.decoder = custom_decoder or TransformerDecoder(
            TransformerDecoderLayer(d_model, nhead, dim_feedforward, dropout,
                                    activation, attn_dropout, act_dropout,
                                    normalize_before),
            num_decoder_layers,
            LayerNorm(d_model) if normalize_before else None,
        )
        self.d_model = d_model
        self.nhead = nhead

    def forward(self, src, tgt, src_mask=None, tgt_mask=None, memory_mask=None):
        memory = self.encoder(src, src_mask)
        return self.decoder(tgt, memory, tgt_mask, memory_mask)

    @staticmethod
    def generate_square_subsequent_mask(length):
        import paddle_tpu as paddle

        return paddle.tril(paddle.ones([length, length], dtype="bool"))
