"""Recurrent layers: SimpleRNN / LSTM / GRU (+ cells, RNN/BiRNN wrappers).

Reference analogue: python/paddle/nn/layer/rnn.py — SimpleRNNCell:263,
LSTMCell:399 (gate order i,f,c,o), GRUCell:556 (r,z,c with reset applied
after the hidden matmul), RNN:707/BiRNN:782 wrappers, RNNBase:861 with
num_layers/direction/time_major/dropout and `{weight,bias}_{ih,hh}_l{k}`
parameter naming.

TPU-native: the time loop is one `lax.scan` per (layer, direction) — a
single compiled XLA while-loop with static shapes — instead of the
reference's per-step op dispatch / cuDNN descriptor path. Variable-length
sequences are masked inside the scan (and reversed within their valid
region for the backward direction), matching the reference's semantics of
carrying the last valid state forward.
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as paddle

from ...core.dispatch import apply, no_grad
from ...core.tensor import Tensor
from .. import functional as F
from .. import initializer as I
from ..layer_base import Layer

__all__ = [
    "RNNCellBase", "SimpleRNNCell", "LSTMCell", "GRUCell",
    "RNN", "BiRNN", "SimpleRNN", "LSTM", "GRU",
]


class RNNCellBase(Layer):
    """reference: rnn.py:139."""

    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        batch = batch_ref.shape[batch_dim_idx]
        shape = shape or self.state_shape
        if isinstance(shape, tuple) and shape and isinstance(shape[0], (tuple, list)):
            return tuple(
                paddle.full([batch] + list(s), init_value, dtype or "float32")
                for s in shape
            )
        return paddle.full([batch] + list(shape), init_value, dtype or "float32")


def _init_cell_params(cell, input_size, hidden_size, gates,
                      weight_ih_attr=None, weight_hh_attr=None,
                      bias_ih_attr=None, bias_hh_attr=None):
    std = 1.0 / np.sqrt(hidden_size)
    u = I.Uniform(-std, std)
    cell.weight_ih = cell.create_parameter(
        [gates * hidden_size, input_size], attr=weight_ih_attr,
        default_initializer=u)
    cell.weight_hh = cell.create_parameter(
        [gates * hidden_size, hidden_size], attr=weight_hh_attr,
        default_initializer=u)
    cell.bias_ih = (
        None if bias_ih_attr is False else cell.create_parameter(
            [gates * hidden_size], attr=bias_ih_attr, is_bias=True,
            default_initializer=u)
    )
    cell.bias_hh = (
        None if bias_hh_attr is False else cell.create_parameter(
            [gates * hidden_size], attr=bias_hh_attr, is_bias=True,
            default_initializer=u)
    )


class SimpleRNNCell(RNNCellBase):
    """reference: rnn.py:263 — h' = act(W_ih x + b_ih + W_hh h + b_hh)."""

    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = activation
        _init_cell_params(self, input_size, hidden_size, 1, weight_ih_attr,
                          weight_hh_attr, bias_ih_attr, bias_hh_attr)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        i2h = paddle.matmul(inputs, self.weight_ih, transpose_y=True)
        if self.bias_ih is not None:
            i2h = i2h + self.bias_ih
        h2h = paddle.matmul(states, self.weight_hh, transpose_y=True)
        if self.bias_hh is not None:
            h2h = h2h + self.bias_hh
        act = paddle.tanh if self.activation == "tanh" else F.relu
        h = act(i2h + h2h)
        return h, h

    @property
    def state_shape(self):
        return (self.hidden_size,)


class LSTMCell(RNNCellBase):
    """reference: rnn.py:399 — gate order i, f, c, o."""

    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        _init_cell_params(self, input_size, hidden_size, 4, weight_ih_attr,
                          weight_hh_attr, bias_ih_attr, bias_hh_attr)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs, self.state_shape)
        pre_h, pre_c = states
        gates = paddle.matmul(inputs, self.weight_ih, transpose_y=True)
        if self.bias_ih is not None:
            gates = gates + self.bias_ih
        gates = gates + paddle.matmul(pre_h, self.weight_hh, transpose_y=True)
        if self.bias_hh is not None:
            gates = gates + self.bias_hh
        gi, gf, gc, go = paddle.split(gates, 4, axis=-1)
        i = F.sigmoid(gi)
        f = F.sigmoid(gf)
        o = F.sigmoid(go)
        c = f * pre_c + i * paddle.tanh(gc)
        h = o * paddle.tanh(c)
        return h, (h, c)

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))


class GRUCell(RNNCellBase):
    """reference: rnn.py:556 — r/z/c gates, reset applied after the hidden
    matmul: c = tanh(x_c + r·h_c); h = (h_prev − c)·z + c."""

    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        _init_cell_params(self, input_size, hidden_size, 3, weight_ih_attr,
                          weight_hh_attr, bias_ih_attr, bias_hh_attr)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        pre_h = states
        x_g = paddle.matmul(inputs, self.weight_ih, transpose_y=True)
        if self.bias_ih is not None:
            x_g = x_g + self.bias_ih
        h_g = paddle.matmul(pre_h, self.weight_hh, transpose_y=True)
        if self.bias_hh is not None:
            h_g = h_g + self.bias_hh
        x_r, x_z, x_c = paddle.split(x_g, 3, axis=-1)
        h_r, h_z, h_c = paddle.split(h_g, 3, axis=-1)
        r = F.sigmoid(x_r + h_r)
        z = F.sigmoid(x_z + h_z)
        c = paddle.tanh(x_c + r * h_c)
        h = (pre_h - c) * z + c
        return h, h

    @property
    def state_shape(self):
        return (self.hidden_size,)


# ---------------------------------------------------------------------------
# scan machinery
# ---------------------------------------------------------------------------
def _flatten_states(states):
    return list(states) if isinstance(states, (tuple, list)) else [states]


def _pack_states(flat, is_tuple):
    return tuple(flat) if is_tuple else flat[0]


class RNN(Layer):
    """reference: rnn.py:707 — scan `cell` over the time axis (one lax.scan,
    compiled; not a Python loop of per-step ops)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None,
                **kwargs):
        cell = self.cell
        if initial_states is None:
            ref = inputs if not self.time_major else inputs.transpose([1, 0, 2])
            initial_states = cell.get_initial_states(ref, cell.state_shape)
        states_is_tuple = isinstance(initial_states, (tuple, list))
        init_flat = _flatten_states(initial_states)
        t_objs = [p for _, p in sorted(cell.named_parameters(),
                                       key=lambda kv: kv[0])]
        n_states = len(init_flat)
        time_major = self.time_major
        reverse = self.is_reverse
        has_len = sequence_length is not None

        def scan_fn(*vals):
            from ...jit import _bind_values

            pvals = vals[:len(t_objs)]
            x = vals[len(t_objs)]
            inits = vals[len(t_objs) + 1:len(t_objs) + 1 + n_states]
            seq_len = vals[-1] if has_len else None
            xs = x if time_major else jnp.swapaxes(x, 0, 1)  # [T, B, I]
            T = xs.shape[0]

            def step(carry, t):
                # reverse = scan positions T-1..0; with sequence_length the
                # padded tail is masked, so the walk effectively starts at
                # len-1 (reverse within the valid region, paddle semantics)
                tt = (T - 1 - t) if reverse else t
                xt = xs[tt]
                with _bind_values(t_objs, list(pvals)), no_grad():
                    out, new = cell(
                        Tensor(xt, stop_gradient=True),
                        (
                            tuple(Tensor(c, stop_gradient=True) for c in carry)
                            if states_is_tuple
                            else Tensor(carry[0], stop_gradient=True)
                        ),
                    )
                new_flat = [s._value for s in _flatten_states(new)]
                out_v = out._value
                if seq_len is not None:
                    valid = (tt < seq_len)[:, None]  # [B, 1]
                    new_flat = [
                        jnp.where(valid, nv, cv)
                        for nv, cv in zip(new_flat, carry)
                    ]
                    out_v = jnp.where(valid, out_v, jnp.zeros_like(out_v))
                return tuple(new_flat), out_v

            carry, outs = jax.lax.scan(step, tuple(inits), jnp.arange(T))
            if reverse:
                outs = outs[::-1]
            outs = outs if time_major else jnp.swapaxes(outs, 0, 1)
            return (outs,) + carry

        # args must line up with t_objs (name-sorted), not creation order
        args = list(t_objs) + [inputs] + init_flat
        if has_len:
            args.append(sequence_length)
        res = apply(scan_fn, *args, op_name=f"rnn_{type(cell).__name__}")
        outs = res[0]
        final = _pack_states(res[1:], states_is_tuple)
        return outs, final


class BiRNN(Layer):
    """reference: rnn.py:782 — forward + backward cells, concat outputs."""

    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.cell_fw = cell_fw
        self.cell_bw = cell_bw
        self.rnn_fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.rnn_bw = RNN(cell_bw, is_reverse=True, time_major=time_major)
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None,
                **kwargs):
        if initial_states is None:
            st_fw = st_bw = None
        else:
            st_fw, st_bw = initial_states
        out_fw, fin_fw = self.rnn_fw(inputs, st_fw, sequence_length)
        out_bw, fin_bw = self.rnn_bw(inputs, st_bw, sequence_length)
        outs = paddle.concat([out_fw, out_bw], axis=-1)
        return outs, (fin_fw, fin_bw)


class RNNBase(Layer):
    """reference: rnn.py:861 — stacks layers × directions."""

    def __init__(self, mode, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None):
        super().__init__()
        bidi = direction in ("bidirectional", "bidirect")
        if not bidi and direction != "forward":
            raise ValueError(
                f"direction should be forward or bidirect, got {direction}"
            )
        self.mode = mode
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_directions = 2 if bidi else 1
        self.time_major = time_major
        self.dropout = dropout
        self.state_components = 2 if mode == "LSTM" else 1
        kwargs = dict(weight_ih_attr=weight_ih_attr,
                      weight_hh_attr=weight_hh_attr,
                      bias_ih_attr=bias_ih_attr, bias_hh_attr=bias_hh_attr)
        cell_cls = {"LSTM": LSTMCell, "GRU": GRUCell}.get(mode, SimpleRNNCell)
        if mode not in ("LSTM", "GRU"):
            kwargs["activation"] = getattr(self, "activation", "tanh")

        self._layers_list = []
        for i in range(num_layers):
            in_sz = input_size if i == 0 else hidden_size * self.num_directions
            if bidi:
                wrap = BiRNN(cell_cls(in_sz, hidden_size, **kwargs),
                             cell_cls(in_sz, hidden_size, **kwargs), time_major)
            else:
                wrap = RNN(cell_cls(in_sz, hidden_size, **kwargs),
                           time_major=time_major)
            self.add_sublayer(str(i), wrap)
            self._layers_list.append(wrap)
        # reference parameter aliases: weight_ih_l0, bias_hh_l1_reverse, ...
        for li, wrap in enumerate(self._layers_list):
            cells = (
                [(wrap.cell_fw, ""), (wrap.cell_bw, "_reverse")]
                if bidi else [(wrap.cell, "")]
            )
            for cell, suffix in cells:
                for pname in ("weight_ih", "weight_hh", "bias_ih", "bias_hh"):
                    p = getattr(cell, pname)
                    if p is not None:
                        object.__setattr__(self, f"{pname}_l{li}{suffix}", p)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        D, L, C = self.num_directions, self.num_layers, self.state_components
        batch_idx = 1 if self.time_major else 0
        batch = inputs.shape[batch_idx]
        if initial_states is None:
            init = [
                paddle.zeros([L * D, batch, self.hidden_size])
                for _ in range(C)
            ]
            initial_states = init[0] if C == 1 else tuple(init)
        states = (
            [initial_states] if C == 1 else list(initial_states)
        )  # C × [L*D, B, H]

        h = inputs
        finals = [[] for _ in range(C)]  # per component, L*D entries in order
        for li, wrap in enumerate(self._layers_list):
            if D == 2:
                def st(d):
                    idx = li * D + d
                    comp = [s[idx] for s in states]
                    return tuple(comp) if C > 1 else comp[0]

                h, (fin_fw, fin_bw) = wrap(h, (st(0), st(1)), sequence_length)
                for fin in (fin_fw, fin_bw):
                    for ci, s in enumerate(_flatten_states(fin)):
                        finals[ci].append(s)
            else:
                comp = [s[li] for s in states]
                h, fin = wrap(h, tuple(comp) if C > 1 else comp[0],
                              sequence_length)
                for ci, s in enumerate(_flatten_states(fin)):
                    finals[ci].append(s)
            if self.dropout > 0.0 and li < L - 1 and self.training:
                h = F.dropout(h, self.dropout)
        final_states = [paddle.stack(f, axis=0) for f in finals]
        return h, (final_states[0] if C == 1 else tuple(final_states))


class SimpleRNN(RNNBase):
    """reference: rnn.py:1105."""

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        self.activation = activation
        super().__init__("RNN", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, weight_ih_attr,
                         weight_hh_attr, bias_ih_attr, bias_hh_attr)


class LSTM(RNNBase):
    """reference: rnn.py:1215."""

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__("LSTM", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, weight_ih_attr,
                         weight_hh_attr, bias_ih_attr, bias_hh_attr)


class GRU(RNNBase):
    """reference: rnn.py:1329."""

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__("GRU", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, weight_ih_attr,
                         weight_hh_attr, bias_ih_attr, bias_hh_attr)


class BeamSearchDecoder:
    """Beam-search decoding over an RNN cell (reference: nn/layer/rnn.py
    BeamSearchDecoder / fluid layers beam search). Host-driven eager loop via
    `dynamic_decode` — decode lengths are data-dependent, which is the one
    place the reference also runs a dynamic loop.

    Protocol: `step(time, inputs, states) -> (outputs, states)` where
    outputs are per-step logits [batch*beam, vocab]."""

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    def _logits(self, tok, states):
        inp = paddle.to_tensor(np.asarray(tok, np.int64))
        if self.embedding_fn is not None:
            inp = self.embedding_fn(inp)
        out, new_states = self.cell(inp, states)
        if self.output_fn is not None:
            out = self.output_fn(out)
        return out, new_states


def _gather_states(states, idx):
    if isinstance(states, (tuple, list)):
        return type(states)(_gather_states(s, idx) for s in states)
    return paddle.to_tensor(states.numpy()[idx])


def dynamic_decode(decoder, inits=None, max_step_num=None, output_time_major=False,
                   impute_finished=False, is_test=False, return_length=False,
                   **kwargs):
    """Run a decoder until all beams emit end_token or max_step_num
    (reference: nn/layer/rnn.py dynamic_decode). Returns
    (ids [B, beam, T] scores [B, beam]) (+ lengths)."""
    if max_step_num is None:
        max_step_num = 64
    beam = decoder.beam_size
    # bootstrap: single start token per batch item
    if inits is None:
        raise ValueError("dynamic_decode needs initial states (inits)")
    states = inits
    # infer batch from states leaf
    leaf = states
    while isinstance(leaf, (tuple, list)):
        leaf = leaf[0]
    batch = leaf.shape[0]

    logits, states = decoder._logits(
        np.full((batch,), decoder.start_token), states
    )
    logp = np.asarray(F.log_softmax(logits, axis=-1).numpy())
    vocab = logp.shape[-1]
    top = np.argsort(-logp, axis=-1)[:, :beam]              # [B, beam]
    scores = np.take_along_axis(logp, top, axis=-1)          # [B, beam]
    seqs = top[:, :, None]                                   # [B, beam, 1]
    finished = top == decoder.end_token
    # tile states to beams: [B, ...] -> [B*beam, ...]
    rep = np.repeat(np.arange(batch), beam)
    states = _gather_states(states, rep)
    lengths = np.ones((batch, beam), np.int64)

    for _ in range(1, max_step_num):
        if finished.all():
            break
        flat_tok = seqs[:, :, -1].reshape(-1)
        logits, new_states = decoder._logits(flat_tok, states)
        logp = np.asarray(F.log_softmax(logits, axis=-1).numpy())
        logp = logp.reshape(batch, beam, vocab)
        # finished beams only extend with end_token at no cost
        fin_mask = np.full((vocab,), -1e9, logp.dtype)
        fin_mask[decoder.end_token] = 0.0
        logp = np.where(finished[:, :, None], fin_mask[None, None, :], logp)
        total = scores[:, :, None] + logp                    # [B, beam, V]
        flat = total.reshape(batch, -1)
        pick = np.argsort(-flat, axis=-1)[:, :beam]          # [B, beam]
        scores = np.take_along_axis(flat, pick, axis=-1)
        src_beam = pick // vocab
        tok = pick % vocab
        seqs = np.concatenate(
            [np.take_along_axis(seqs, src_beam[:, :, None], axis=1),
             tok[:, :, None]], axis=2,
        )
        was_fin = np.take_along_axis(finished, src_beam, axis=1)
        lengths = np.take_along_axis(lengths, src_beam, axis=1) + (~was_fin)
        finished = was_fin | (tok == decoder.end_token)
        gather_idx = (np.arange(batch)[:, None] * beam + src_beam).reshape(-1)
        states = _gather_states(new_states, gather_idx)

    ids = paddle.to_tensor(seqs)
    sc = paddle.to_tensor(scores)
    if output_time_major:
        ids = paddle.to_tensor(np.transpose(seqs, (2, 0, 1)))
    if return_length:
        return ids, sc, paddle.to_tensor(lengths)
    return ids, sc
