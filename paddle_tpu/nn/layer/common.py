"""Common layers: Linear, Embedding, Dropout, Flatten, Pad, Upsample, containers.

Reference analogue: python/paddle/nn/layer/common.py + container.py.
"""
from __future__ import annotations

import collections

import numpy as np

from .. import functional as F
from .. import initializer as I
from ..layer_base import Layer, Parameter


class Linear(Layer):
    """y = xW + b with paddle weight layout [in_features, out_features]
    (reference: nn/layer/common.py Linear)."""

    def __init__(self, in_features, out_features, weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.weight = self.create_parameter(
            shape=[in_features, out_features],
            attr=weight_attr,
            default_initializer=I.XavierNormal(),
        )
        self.bias = (
            None
            if bias_attr is False
            else self.create_parameter(
                shape=[out_features], attr=bias_attr, is_bias=True
            )
        )

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return f"in_features={self.in_features}, out_features={self.out_features}"


class Embedding(Layer):
    """reference: nn/layer/common.py Embedding → phi embedding kernel."""

    def __init__(
        self, num_embeddings, embedding_dim, padding_idx=None, sparse=False,
        weight_attr=None, name=None,
    ):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self._padding_idx = (
            None
            if padding_idx is None
            else padding_idx
            if padding_idx >= 0
            else num_embeddings + padding_idx
        )
        self.weight = self.create_parameter(
            shape=[num_embeddings, embedding_dim],
            attr=weight_attr,
            default_initializer=I.XavierNormal(),
        )
        if self._padding_idx is not None:
            with __import__("paddle_tpu").no_grad():
                arr = np.asarray(self.weight.numpy())
                arr[self._padding_idx] = 0
                self.weight.set_value(arr)

    def forward(self, x):
        return F.embedding(x, self.weight, padding_idx=self._padding_idx)

    def extra_repr(self):
        return f"{self._num_embeddings}, {self._embedding_dim}"


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, x):
        return F.dropout(x, self.p, axis=self.axis, training=self.training, mode=self.mode)

    def extra_repr(self):
        return f"p={self.p}"


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout2d(x, self.p, training=self.training, data_format=self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, self.p, training=self.training)


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis = start_axis
        self.stop_axis = stop_axis

    def forward(self, x):
        return x.flatten(self.start_axis, self.stop_axis)


class Identity(Layer):
    def __init__(self, *args, **kwargs):
        super().__init__()

    def forward(self, x):
        return x


class Upsample(Layer):
    def __init__(
        self, size=None, scale_factor=None, mode="nearest", align_corners=False,
        align_mode=0, data_format="NCHW", name=None,
    ):
        super().__init__()
        self.size = size
        self.scale_factor = scale_factor
        self.mode = mode
        self.align_corners = align_corners
        self.align_mode = align_mode
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(
            x, self.size, self.scale_factor, self.mode, self.align_corners,
            self.align_mode, self.data_format,
        )


class Pad1D(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCL", name=None):
        super().__init__()
        self.padding = padding if isinstance(padding, (list, tuple)) else [padding] * 2
        self.mode = mode
        self.value = value
        self.data_format = data_format

    def forward(self, x):
        return F.pad(x, self.padding, self.mode, self.value, self.data_format)


class Pad2D(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW", name=None):
        super().__init__()
        self.padding = padding if isinstance(padding, (list, tuple)) else [padding] * 4
        self.mode = mode
        self.value = value
        self.data_format = data_format

    def forward(self, x):
        return F.pad(x, self.padding, self.mode, self.value, self.data_format)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis = axis
        self.eps = eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, self.axis, self.eps)


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            shape=[out_features, in1_features, in2_features], attr=weight_attr,
        )
        self.bias = (
            None if bias_attr is False
            else self.create_parameter(shape=[1, out_features], is_bias=True, attr=bias_attr)
        )

    def forward(self, x1, x2):
        import paddle_tpu as paddle

        out = paddle.tensor_api.apply_bilinear(x1, x2, self.weight) if hasattr(
            paddle.tensor_api, "apply_bilinear"
        ) else None
        if out is None:
            from ...core.dispatch import apply
            import jax.numpy as jnp

            out = apply(
                lambda a, b, w: jnp.einsum("bi,oij,bj->bo", a, w, b), x1, x2, self.weight
            )
        if self.bias is not None:
            out = out + self.bias
        return out


# ----------------------------- containers -----------------------------------
class Sequential(Layer):
    """reference: nn/layer/container.py Sequential."""

    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0], collections.OrderedDict):
            for name, layer in layers[0].items():
                self.add_sublayer(name, layer)
        else:
            for i, layer in enumerate(layers):
                if isinstance(layer, tuple):
                    self.add_sublayer(layer[0], layer[1])
                else:
                    self.add_sublayer(str(i), layer)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return Sequential(*list(self._sub_layers.values())[idx])
        return list(self._sub_layers.values())[idx]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())

    def forward(self, x):
        for layer in self._sub_layers.values():
            x = layer(x)
        return x


class LayerList(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers is not None:
            for i, l in enumerate(sublayers):
                self.add_sublayer(str(i), l)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return LayerList(list(self._sub_layers.values())[idx])
        return list(self._sub_layers.values())[idx]

    def __setitem__(self, idx, layer):
        self._sub_layers[str(idx)] = layer

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())

    def append(self, layer):
        self.add_sublayer(str(len(self._sub_layers)), layer)
        return self

    def extend(self, layers):
        for l in layers:
            self.append(l)
        return self

    def insert(self, index, layer):
        layers = list(self._sub_layers.values())
        layers.insert(index, layer)
        self._sub_layers.clear()
        for i, l in enumerate(layers):
            self._sub_layers[str(i)] = l


class ParameterList(Layer):
    def __init__(self, parameters=None):
        super().__init__()
        if parameters is not None:
            for i, p in enumerate(parameters):
                self.add_parameter(str(i), p)

    def __getitem__(self, idx):
        return list(self._parameters.values())[idx]

    def __len__(self):
        return len(self._parameters)

    def __iter__(self):
        return iter(self._parameters.values())

    def append(self, parameter):
        self.add_parameter(str(len(self._parameters)), parameter)
        return self


class LayerDict(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers:
            self.update(sublayers)

    def __getitem__(self, key):
        return self._sub_layers[key]

    def __setitem__(self, key, layer):
        self.add_sublayer(key, layer)

    def __delitem__(self, key):
        del self._sub_layers[key]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers)

    def __contains__(self, key):
        return key in self._sub_layers

    def keys(self):
        return self._sub_layers.keys()

    def values(self):
        return self._sub_layers.values()

    def items(self):
        return self._sub_layers.items()

    def update(self, sublayers):
        items = sublayers.items() if isinstance(sublayers, dict) else sublayers
        for key, layer in items:
            self.add_sublayer(key, layer)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout3d(x, self.p, self.training, self.data_format)


class Pad3D(Layer):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCDHW", name=None):
        super().__init__()
        self._pad = padding
        self._mode = mode
        self._value = value
        self._data_format = data_format

    def forward(self, x):
        return F.pad(x, self._pad, self._mode, self._value, self._data_format)


class ZeroPad2D(Layer):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__()
        self._pad = padding
        self._data_format = data_format

    def forward(self, x):
        return F.zeropad2d(x, self._pad, self._data_format)


class PairwiseDistance(Layer):
    """reference: nn/layer/distance.py PairwiseDistance."""

    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p = p
        self.epsilon = epsilon
        self.keepdim = keepdim

    def forward(self, x, y):
        import paddle_tpu as paddle

        d = x - y
        return paddle.norm(
            d + self.epsilon, p=self.p, axis=-1, keepdim=self.keepdim
        )


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.upscale_factor = upscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_shuffle(x, self.upscale_factor, self.data_format)


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.downscale_factor = downscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_unshuffle(x, self.downscale_factor, self.data_format)


class Fold(Layer):
    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1, name=None):
        super().__init__()
        self.output_sizes = output_sizes
        self.kernel_sizes = kernel_sizes
        self.strides = strides
        self.paddings = paddings
        self.dilations = dilations

    def forward(self, x):
        return F.fold(x, self.output_sizes, self.kernel_sizes, self.strides,
                      self.paddings, self.dilations)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1,
                 name=None):
        super().__init__()
        self.kernel_sizes = kernel_sizes
        self.strides = strides
        self.paddings = paddings
        self.dilations = dilations

    def forward(self, x):
        return F.unfold(x, self.kernel_sizes, self.strides, self.paddings,
                        self.dilations)


class UpsamplingBilinear2D(Layer):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self.size = size
        self.scale_factor = scale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, size=self.size, scale_factor=self.scale_factor,
                             mode="bilinear", align_corners=True,
                             data_format=self.data_format)


class UpsamplingNearest2D(Layer):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self.size = size
        self.scale_factor = scale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, size=self.size, scale_factor=self.scale_factor,
                             mode="nearest", data_format=self.data_format)
