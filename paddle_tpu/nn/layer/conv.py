"""Conv layers. Reference analogue: python/paddle/nn/layer/conv.py."""
from __future__ import annotations

import numpy as np

from .. import functional as F
from .. import initializer as I
from ..layer_base import Layer


class _ConvNd(Layer):
    def __init__(
        self, in_channels, out_channels, kernel_size, dims, stride=1, padding=0,
        dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
        bias_attr=None, data_format="NCHW",
    ):
        super().__init__()
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size,) * dims
        self._in_channels = in_channels
        self._out_channels = out_channels
        self._kernel_size = tuple(kernel_size)
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._groups = groups
        self._data_format = data_format
        fan_in = in_channels // groups * int(np.prod(self._kernel_size))
        self.weight = self.create_parameter(
            shape=[out_channels, in_channels // groups, *self._kernel_size],
            attr=weight_attr,
            default_initializer=I.Normal(0.0, (2.0 / fan_in) ** 0.5),
        )
        self.bias = (
            None
            if bias_attr is False
            else self.create_parameter(shape=[out_channels], attr=bias_attr, is_bias=True)
        )

    def extra_repr(self):
        return (
            f"{self._in_channels}, {self._out_channels}, "
            f"kernel_size={list(self._kernel_size)}, stride={self._stride}"
        )


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)

    def forward(self, x):
        return F.conv1d(x, self.weight, self.bias, self._stride, self._padding,
                        self._dilation, self._groups, self._data_format)


class Conv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, self._stride, self._padding,
                        self._dilation, self._groups, self._data_format)


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, self._stride, self._padding,
                        self._dilation, self._groups, self._data_format)


class Conv2DTranspose(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 output_padding=0, dilation=1, groups=1, weight_attr=None,
                 bias_attr=None, data_format="NCHW"):
        super().__init__()
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size, kernel_size)
        self._stride = stride
        self._padding = padding
        self._output_padding = output_padding
        self._dilation = dilation
        self._groups = groups
        self._data_format = data_format
        # paddle conv_transpose weight layout: [in, out/groups, kh, kw]
        self.weight = self.create_parameter(
            shape=[in_channels, out_channels // groups, *kernel_size],
        )
        self.bias = (
            None if bias_attr is False
            else self.create_parameter(shape=[out_channels], attr=bias_attr, is_bias=True)
        )

    def forward(self, x, output_size=None):
        return F.conv2d_transpose(
            x, self.weight, self.bias, self._stride, self._padding,
            self._output_padding, self._groups, self._dilation,
            self._data_format, output_size,
        )


class Conv1DTranspose(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__()
        k = kernel_size if isinstance(kernel_size, int) else kernel_size[0]
        self._stride = stride
        self._padding = padding
        self._output_padding = output_padding
        self._dilation = dilation
        self._groups = groups
        self._data_format = data_format
        self.weight = self.create_parameter(
            shape=[in_channels, out_channels // groups, k], attr=weight_attr,
        )
        self.bias = (
            None if bias_attr is False
            else self.create_parameter(shape=[out_channels], attr=bias_attr,
                                       is_bias=True)
        )

    def forward(self, x, output_size=None):
        return F.conv1d_transpose(
            x, self.weight, self.bias, self._stride, self._padding,
            self._output_padding, self._groups, self._dilation,
            output_size, self._data_format,
        )


class Conv3DTranspose(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__()
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size,) * 3
        self._stride = stride
        self._padding = padding
        self._output_padding = output_padding
        self._dilation = dilation
        self._groups = groups
        self._data_format = data_format
        self.weight = self.create_parameter(
            shape=[in_channels, out_channels // groups, *kernel_size],
            attr=weight_attr,
        )
        self.bias = (
            None if bias_attr is False
            else self.create_parameter(shape=[out_channels], attr=bias_attr,
                                       is_bias=True)
        )

    def forward(self, x, output_size=None):
        return F.conv3d_transpose(
            x, self.weight, self.bias, self._stride, self._padding,
            self._output_padding, self._groups, self._dilation,
            output_size, self._data_format,
        )
