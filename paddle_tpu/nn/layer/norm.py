"""Normalization layers. Reference: python/paddle/nn/layer/norm.py."""
from __future__ import annotations

from ...core.tensor import Tensor
from .. import functional as F
from .. import initializer as I
from ..layer_base import Layer


class _BatchNormBase(Layer):
    def __init__(
        self, num_features, momentum=0.9, epsilon=1e-05, weight_attr=None,
        bias_attr=None, data_format="NCHW", use_global_stats=None, name=None,
    ):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        self.weight = (
            None if weight_attr is False
            else self.create_parameter(
                shape=[num_features], attr=weight_attr,
                default_initializer=I.Constant(1.0),
            )
        )
        self.bias = (
            None if bias_attr is False
            else self.create_parameter(shape=[num_features], attr=bias_attr, is_bias=True)
        )
        self.register_buffer("_mean", Tensor([0.0] * num_features, dtype="float32"))
        self.register_buffer("_variance", Tensor([1.0] * num_features, dtype="float32"))

    def forward(self, x):
        return F.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self._momentum, epsilon=self._epsilon,
            data_format=self._data_format, use_global_stats=self._use_global_stats,
        )

    def extra_repr(self):
        return f"num_features={self._num_features}, momentum={self._momentum}"


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    pass


class BatchNorm(_BatchNormBase):
    """fluid-era BatchNorm (reference: fluid/dygraph/nn.py BatchNorm)."""

    def __init__(self, num_channels, act=None, momentum=0.9, epsilon=1e-05, **kw):
        super().__init__(num_channels, momentum, epsilon)
        self._act = act

    def forward(self, x):
        out = super().forward(x)
        if self._act:
            out = getattr(F, self._act)(out)
        return out


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica BN. On TPU the mean/var allreduce happens automatically
    when the train step is compiled over a data-sharded mesh (XLA inserts the
    collective); eager single-process falls back to local stats.
    Reference: python/paddle/nn/layer/norm.py SyncBatchNorm."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        out = layer
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, cls):
            out = cls(layer._num_features, layer._momentum, layer._epsilon)
            if layer.weight is not None:
                out.weight.set_value(layer.weight)
                out.bias.set_value(layer.bias)
            out._mean.set_value(layer._mean)
            out._variance.set_value(layer._variance)
        for name, sub in list(layer._sub_layers.items()):
            out._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return out


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        self.weight = (
            None if weight_attr is False
            else self.create_parameter(
                shape=self._normalized_shape, attr=weight_attr,
                default_initializer=I.Constant(1.0),
            )
        )
        self.bias = (
            None if bias_attr is False
            else self.create_parameter(
                shape=self._normalized_shape, attr=bias_attr, is_bias=True
            )
        )

    def forward(self, x):
        return F.layer_norm(
            x, self._normalized_shape, self.weight, self.bias, self._epsilon
        )

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}, epsilon={self._epsilon}"


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._num_groups = num_groups
        self._num_channels = num_channels
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = (
            None if weight_attr is False
            else self.create_parameter(
                shape=[num_channels], attr=weight_attr,
                default_initializer=I.Constant(1.0),
            )
        )
        self.bias = (
            None if bias_attr is False
            else self.create_parameter(shape=[num_channels], attr=bias_attr, is_bias=True)
        )

    def forward(self, x):
        return F.group_norm(
            x, self._num_groups, self._epsilon, self.weight, self.bias,
            self._data_format,
        )


class InstanceNorm2D(Layer):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._epsilon = epsilon
        self.scale = (
            None if weight_attr is False
            else self.create_parameter(
                shape=[num_features], attr=weight_attr,
                default_initializer=I.Constant(1.0),
            )
        )
        self.bias = (
            None if bias_attr is False
            else self.create_parameter(shape=[num_features], attr=bias_attr, is_bias=True)
        )

    def forward(self, x):
        return F.instance_norm(
            x, weight=self.scale, bias=self.bias, eps=self._epsilon
        )


InstanceNorm1D = InstanceNorm2D
InstanceNorm3D = InstanceNorm2D


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW", name=None):
        super().__init__()
        self.size = size
        self.alpha = alpha
        self.beta = beta
        self.k = k

    def forward(self, x):
        return F.local_response_norm(x, self.size, self.alpha, self.beta, self.k)


class SpectralNorm(Layer):
    """reference: nn/layer/norm.py SpectralNorm (phi spectral_norm kernel) —
    normalize `weight` by its largest singular value, estimated with
    `power_iters` rounds of power iteration on persistent u/v vectors."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12, name=None):
        super().__init__()
        self.dim = dim
        self.power_iters = power_iters
        self.eps = eps
        self._shape = list(weight_shape)
        h = self._shape[dim]
        w = 1
        for i, s in enumerate(self._shape):
            if i != dim:
                w *= s
        import paddle_tpu as _paddle

        # persistent estimation state, refined every forward (reference keeps
        # u/v as non-trainable persistables updated in place) — seeded from
        # the global generator so paddle.seed governs it
        self.register_buffer("weight_u", _paddle.randn([h]))
        self.register_buffer("weight_v", _paddle.randn([w]))

    def forward(self, weight):
        import jax
        import jax.numpy as jnp

        from ...core.dispatch import apply, no_grad
        from ...core.tensor import Tensor

        def f(wt, u, v, dim, power_iters, eps):
            mat = jnp.moveaxis(wt, dim, 0).reshape(wt.shape[dim], -1)
            for _ in range(power_iters):
                v = mat.T @ u
                v = v / (jnp.linalg.norm(v) + eps)
                u = mat @ v
                u = u / (jnp.linalg.norm(u) + eps)
            sigma = u @ mat @ v
            return wt / sigma, jax.lax.stop_gradient(u), jax.lax.stop_gradient(v)

        out, u, v = apply(
            f, weight, self.weight_u, self.weight_v, dim=self.dim,
            power_iters=self.power_iters, eps=self.eps, op_name="spectral_norm",
        )
        # refine the persistent estimate so sigma converges across forwards
        with no_grad():
            self.weight_u._value = u._value
            self.weight_v._value = v._value
        return out
