"""Pooling layers. Reference: python/paddle/nn/layer/pooling.py."""
from __future__ import annotations

from .. import functional as F
from ..layer_base import Layer


class MaxPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 return_mask=False, data_format="NCHW", name=None):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.ceil_mode = ceil_mode
        self.return_mask = return_mask
        self.data_format = data_format

    def forward(self, x):
        return F.max_pool2d(
            x, self.kernel_size, self.stride, self.padding, self.ceil_mode,
            return_mask=self.return_mask, data_format=self.data_format,
        )


class MaxUnPool2D(Layer):
    """reference: nn/layer/pooling.py MaxUnPool2D over phi unpool kernel."""

    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.data_format = data_format
        self.output_size = output_size

    def forward(self, x, indices):
        return F.max_unpool2d(
            x, indices, self.kernel_size, self.stride, self.padding,
            data_format=self.data_format, output_size=self.output_size,
        )


class AvgPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format="NCHW", name=None):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.ceil_mode = ceil_mode
        self.exclusive = exclusive
        self.divisor_override = divisor_override
        self.data_format = data_format

    def forward(self, x):
        return F.avg_pool2d(
            x, self.kernel_size, self.stride, self.padding, self.ceil_mode,
            self.exclusive, self.divisor_override,
            data_format=self.data_format,
        )


class AdaptiveAvgPool2D(Layer):
    def __init__(self, output_size, data_format="NCHW", name=None):
        super().__init__()
        self.output_size = output_size
        self.data_format = data_format

    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self.output_size, self.data_format)


class MaxPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 return_mask=False, name=None):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.ceil_mode = ceil_mode

    def forward(self, x):
        return F.max_pool1d(x, self.kernel_size, self.stride, self.padding, self.ceil_mode)


class AdaptiveAvgPool1D(Layer):
    def __init__(self, output_size, name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_avg_pool1d(x, self.output_size)


class AvgPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, exclusive=True,
                 ceil_mode=False, name=None):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.exclusive = exclusive
        self.ceil_mode = ceil_mode

    def forward(self, x):
        return F.avg_pool1d(x, self.kernel_size, self.stride, self.padding,
                            self.exclusive, self.ceil_mode)


class AvgPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format="NCDHW",
                 name=None):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.ceil_mode = ceil_mode
        self.exclusive = exclusive
        self.divisor_override = divisor_override
        self.data_format = data_format

    def forward(self, x):
        return F.avg_pool3d(x, self.kernel_size, self.stride, self.padding,
                            self.ceil_mode, self.exclusive,
                            self.divisor_override, self.data_format)


class MaxPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 return_mask=False, data_format="NCDHW", name=None):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.ceil_mode = ceil_mode
        self.return_mask = return_mask
        self.data_format = data_format

    def forward(self, x):
        return F.max_pool3d(x, self.kernel_size, self.stride, self.padding,
                            self.ceil_mode, self.return_mask, self.data_format)


class AdaptiveAvgPool3D(Layer):
    def __init__(self, output_size, data_format="NCDHW", name=None):
        super().__init__()
        self.output_size = output_size
        self.data_format = data_format

    def forward(self, x):
        return F.adaptive_avg_pool3d(x, self.output_size, self.data_format)


class AdaptiveMaxPool1D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size
        self.return_mask = return_mask

    def forward(self, x):
        return F.adaptive_max_pool1d(x, self.output_size, self.return_mask)


class AdaptiveMaxPool2D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size
        self.return_mask = return_mask

    def forward(self, x):
        return F.adaptive_max_pool2d(x, self.output_size, self.return_mask)


class AdaptiveMaxPool3D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size
        self.return_mask = return_mask

    def forward(self, x):
        return F.adaptive_max_pool3d(x, self.output_size, self.return_mask)


class MaxUnPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, data_format="NCL",
                 output_size=None, name=None):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.data_format = data_format
        self.output_size = output_size

    def forward(self, x, indices):
        return F.max_unpool1d(x, indices, self.kernel_size, self.stride,
                              self.padding, self.output_size,
                              self.data_format)


class MaxUnPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.data_format = data_format
        self.output_size = output_size

    def forward(self, x, indices):
        return F.max_unpool3d(x, indices, self.kernel_size, self.stride,
                              self.padding, self.output_size,
                              self.data_format)
