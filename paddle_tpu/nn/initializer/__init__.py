"""paddle.nn.initializer — parameter initializers.

Reference analogue: python/paddle/nn/initializer/ + fluid/initializer.py
(Constant, Uniform, Normal, TruncatedNormal, Xavier, KaimingNormal/MSRA,
Assign, Bilinear). Initializers generate concrete jax arrays host-side using
the global Generator key stream (core/random.py).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ...core import random as _random
from ...core.dtype import to_np_dtype

__all__ = [
    "Initializer",
    "Constant",
    "Uniform",
    "Normal",
    "TruncatedNormal",
    "XavierNormal",
    "XavierUniform",
    "KaimingNormal",
    "KaimingUniform",
    "Assign",
    "Orthogonal",
    "Dirac",
    "calculate_gain",
]


def calculate_gain(nonlinearity, param=None):
    recommended = {
        "sigmoid": 1.0,
        "linear": 1.0,
        "conv1d": 1.0,
        "conv2d": 1.0,
        "conv3d": 1.0,
        "tanh": 5.0 / 3,
        "relu": math.sqrt(2.0),
        "leaky_relu": math.sqrt(2.0 / (1 + (param or 0.01) ** 2)),
        "selu": 3.0 / 4,
    }
    return recommended[nonlinearity]


def _fans(shape):
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        # paddle linear weight is [in, out]
        return shape[0], shape[1]
    receptive = int(np.prod(shape[2:]))
    fan_in = shape[1] * receptive
    fan_out = shape[0] * receptive
    return fan_in, fan_out


class Initializer:
    def _generate(self, shape, dtype):
        raise NotImplementedError

    def __call__(self, param, block=None):
        # fluid-style imperative init on an existing tensor
        param.set_value(self._generate(tuple(param.shape), param._value.dtype))
        return param


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def _generate(self, shape, dtype):
        return jnp.full(shape, self.value, dtype=to_np_dtype(dtype))


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0, name=None):
        self.low, self.high = low, high

    def _generate(self, shape, dtype):
        return jax.random.uniform(
            _random.next_key(), shape, dtype=to_np_dtype(dtype),
            minval=self.low, maxval=self.high,
        )


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0, name=None):
        self.mean, self.std = mean, std

    def _generate(self, shape, dtype):
        return (
            jax.random.normal(_random.next_key(), shape, dtype=to_np_dtype(dtype))
            * self.std
            + self.mean
        )


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, name=None):
        self.mean, self.std = mean, std

    def _generate(self, shape, dtype):
        return (
            jax.random.truncated_normal(
                _random.next_key(), -2.0, 2.0, shape, dtype=to_np_dtype(dtype)
            )
            * self.std
            + self.mean
        )


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self._fan_in, self._fan_out, self._gain = fan_in, fan_out, gain

    def _generate(self, shape, dtype):
        fan_in, fan_out = _fans(shape)
        fan_in = self._fan_in or fan_in
        fan_out = self._fan_out or fan_out
        std = self._gain * math.sqrt(2.0 / (fan_in + fan_out))
        return (
            jax.random.normal(_random.next_key(), shape, dtype=to_np_dtype(dtype)) * std
        )


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self._fan_in, self._fan_out, self._gain = fan_in, fan_out, gain

    def _generate(self, shape, dtype):
        fan_in, fan_out = _fans(shape)
        fan_in = self._fan_in or fan_in
        fan_out = self._fan_out or fan_out
        limit = self._gain * math.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(
            _random.next_key(), shape, dtype=to_np_dtype(dtype),
            minval=-limit, maxval=limit,
        )


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self._fan_in = fan_in
        self._slope = negative_slope
        self._nonlinearity = nonlinearity

    def _generate(self, shape, dtype):
        fan_in, _ = _fans(shape)
        fan_in = self._fan_in or fan_in
        gain = calculate_gain(self._nonlinearity, self._slope)
        std = gain / math.sqrt(fan_in)
        return (
            jax.random.normal(_random.next_key(), shape, dtype=to_np_dtype(dtype)) * std
        )


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self._fan_in = fan_in
        self._slope = negative_slope
        self._nonlinearity = nonlinearity

    def _generate(self, shape, dtype):
        fan_in, _ = _fans(shape)
        fan_in = self._fan_in or fan_in
        gain = calculate_gain(self._nonlinearity, self._slope)
        limit = gain * math.sqrt(3.0 / fan_in)
        return jax.random.uniform(
            _random.next_key(), shape, dtype=to_np_dtype(dtype),
            minval=-limit, maxval=limit,
        )


class Assign(Initializer):
    def __init__(self, value, name=None):
        self.value = np.asarray(value)

    def _generate(self, shape, dtype):
        arr = jnp.asarray(self.value, dtype=to_np_dtype(dtype))
        if tuple(arr.shape) != tuple(shape):
            raise ValueError(f"Assign shape {arr.shape} != param shape {shape}")
        return arr


class Orthogonal(Initializer):
    def __init__(self, gain=1.0, name=None):
        self.gain = gain

    def _generate(self, shape, dtype):
        rows = shape[0]
        cols = int(np.prod(shape[1:])) if len(shape) > 1 else 1
        flat = jax.random.normal(_random.next_key(), (max(rows, cols), min(rows, cols)))
        q, r = jnp.linalg.qr(flat)
        q = q * jnp.sign(jnp.diagonal(r))
        if rows < cols:
            q = q.T
        return (self.gain * q[:rows, :cols]).reshape(shape).astype(to_np_dtype(dtype))


class Dirac(Initializer):
    def __init__(self, groups=1, name=None):
        self.groups = groups

    def _generate(self, shape, dtype):
        out = np.zeros(shape, dtype=to_np_dtype(dtype))
        oc, ic = shape[0], shape[1]
        centers = [s // 2 for s in shape[2:]]
        per = oc // self.groups
        for g in range(self.groups):
            for i in range(min(per, ic)):
                idx = (g * per + i, i) + tuple(centers)
                out[idx] = 1.0
        return jnp.asarray(out)


# fluid-era aliases (reference: fluid/initializer.py)
ConstantInitializer = Constant
UniformInitializer = Uniform
NormalInitializer = Normal
TruncatedNormalInitializer = TruncatedNormal
XavierInitializer = XavierNormal
MSRAInitializer = KaimingNormal
NumpyArrayInitializer = Assign


class Bilinear(Initializer):
    """Bilinear-upsample kernel init for conv_transpose weights (reference:
    fluid/initializer.py BilinearInitializer — the deconv upsampling init)."""

    def _generate(self, shape, dtype):
        import numpy as np

        if len(shape) != 4:
            raise ValueError("Bilinear initializer needs a 4-D weight")
        kh, kw = shape[2], shape[3]
        f_h, f_w = (kh + 1) // 2, (kw + 1) // 2
        c_h = (2 * f_h - 1 - f_h % 2) / (2.0 * f_h)
        c_w = (2 * f_w - 1 - f_w % 2) / (2.0 * f_w)
        og = np.ogrid[:kh, :kw]
        filt = (1 - abs(og[0] / f_h - c_h)) * (1 - abs(og[1] / f_w - c_w))
        w = np.zeros(shape, np.float64)
        for i in range(shape[0]):
            for j in range(shape[1]):
                w[i, j] = filt
        import jax.numpy as jnp

        from ...core.dtype import to_np_dtype

        return jnp.asarray(w, to_np_dtype(dtype))


_global_weight_init = None
_global_bias_init = None


def set_global_initializer(weight_init, bias_init=None):
    """Set process-wide default initializers used when a parameter has no
    explicit attr (reference: fluid/initializer.py set_global_initializer).
    Pass (None, None) to restore framework defaults."""
    global _global_weight_init, _global_bias_init
    _global_weight_init = weight_init
    _global_bias_init = bias_init
