"""paddle.nn — layers, functional, initializers.

Reference analogue: python/paddle/nn/ (25.2k LoC).
"""
from . import functional  # noqa: F401
from . import utils  # noqa: F401
from . import quant  # noqa: F401
from . import initializer  # noqa: F401
from .layer_base import Layer, Parameter  # noqa: F401
from .param_attr import ParamAttr  # noqa: F401
from .clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue  # noqa: F401
from .layer.activation import (  # noqa: F401
    CELU, ELU, GELU, GLU, Hardshrink, Hardsigmoid, Hardswish, Hardtanh,
    LeakyReLU, LogSigmoid, LogSoftmax, Maxout, Mish, PReLU, ReLU, ReLU6, SELU,
    Sigmoid, Silu, Softmax, Softplus, Softshrink, Softsign, Swish, Tanh,
    Tanhshrink, ThresholdedReLU,
)
from .layer.common import (  # noqa: F401
    AlphaDropout, Bilinear, CosineSimilarity, Dropout, Dropout2D, Dropout3D,
    Embedding, Flatten, Fold, Identity, LayerDict, LayerList, Linear, Pad1D,
    Pad2D, Pad3D, PairwiseDistance, ParameterList, PixelShuffle,
    PixelUnshuffle, Sequential, Unfold, Upsample, UpsamplingBilinear2D,
    UpsamplingNearest2D, ZeroPad2D,
)
from .layer.conv import (  # noqa: F401
    Conv1D, Conv1DTranspose, Conv2D, Conv2DTranspose, Conv3D, Conv3DTranspose,
)
from .layer.loss import (  # noqa: F401
    BCELoss, BCEWithLogitsLoss, CrossEntropyLoss, CTCLoss, HingeEmbeddingLoss,
    HSigmoidLoss, KLDivLoss, L1Loss, MarginRankingLoss, MSELoss, NLLLoss,
    SmoothL1Loss,
)
from .layer.norm import (  # noqa: F401
    BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D, GroupNorm,
    InstanceNorm1D, InstanceNorm2D, InstanceNorm3D, LayerNorm,
    LocalResponseNorm, SpectralNorm, SyncBatchNorm,
)
from .layer.pooling import (  # noqa: F401
    AdaptiveAvgPool1D, AdaptiveAvgPool2D, AdaptiveAvgPool3D,
    AdaptiveMaxPool1D, AdaptiveMaxPool2D, AdaptiveMaxPool3D, AvgPool1D,
    AvgPool2D, AvgPool3D, MaxPool1D, MaxPool2D, MaxPool3D, MaxUnPool1D,
    MaxUnPool2D, MaxUnPool3D,
)
from .layer.rnn import (  # noqa: F401
    GRU, LSTM, RNN, BeamSearchDecoder, BiRNN, GRUCell, LSTMCell, RNNCellBase,
    SimpleRNN, SimpleRNNCell, dynamic_decode,
)
from .layer.transformer import (  # noqa: F401
    MultiHeadAttention, Transformer, TransformerDecoder,
    TransformerDecoderLayer, TransformerEncoder, TransformerEncoderLayer,
)
from .utils_fns import clip_grad_norm_, clip_grad_value_  # noqa: F401
