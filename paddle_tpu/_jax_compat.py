"""Version-compat shims for jax APIs that moved between releases.

One import site for the whole tree (library modules AND tests): jax
promoted ``shard_map`` from ``jax.experimental.shard_map`` to the top-level
namespace (and later removed the experimental module), so neither spelling
imports across every version we run against. Import it from here instead:

    from paddle_tpu._jax_compat import shard_map
"""
from __future__ import annotations

__all__ = ["axis_size", "shard_map", "shardmap_autodiff_limitation"]

try:  # jax >= 0.5: top-level export
    from jax import shard_map as _shard_map
    if not callable(_shard_map):  # transitional releases export the module
        _shard_map = _shard_map.shard_map
except ImportError:  # jax 0.4.x
    from jax.experimental.shard_map import shard_map as _shard_map

import inspect as _inspect

_params = frozenset(_inspect.signature(_shard_map).parameters)


def shard_map(f=None, *args, **kwargs):
    """``jax.shard_map`` with version drift normalized: the replication
    check is spelled ``check_vma`` (new) or ``check_rep`` (0.4.x), and the
    manual-axes set is ``axis_names`` (new) or the complementary ``auto``
    (0.4.x) — accept either spelling and pass whichever the installed
    version understands. Positional ``(f, mesh, in_specs, out_specs)``
    calls work as with the real API."""
    if args:
        if len(args) > 3:
            raise TypeError(
                f"shard_map() takes at most 4 positional arguments "
                f"({1 + len(args)} given)"
            )
        for name, val in zip(("mesh", "in_specs", "out_specs"), args):
            if name in kwargs:
                raise TypeError(
                    f"shard_map() got multiple values for argument {name!r}"
                )
            kwargs[name] = val
    check = kwargs.pop("check_vma", kwargs.pop("check_rep", None))
    if check is not None:
        if "check_vma" in _params:
            kwargs["check_vma"] = check
        elif "check_rep" in _params:
            kwargs["check_rep"] = check
    if "axis_names" in kwargs and "axis_names" not in _params:
        # newer jax: axis_names = the MANUAL axes; 0.4.x spells the same
        # contract as `auto` = the complement set of the mesh's axes.
        # Size-1 axes are folded into the manual set instead: replication
        # over a 1-sized axis is a no-op, and 0.4.x cannot differentiate
        # through shard_map when `auto` is non-empty.
        manual = frozenset(kwargs.pop("axis_names"))
        mesh = kwargs.get("mesh")
        if "auto" in _params and mesh is not None:
            kwargs["auto"] = frozenset(
                a for a in mesh.axis_names
                if a not in manual and mesh.shape[a] > 1
            )
        else:  # never silently widen the manual set
            raise TypeError(
                "this jax version supports neither the axis_names kwarg "
                "nor an auto+mesh translation for it; pass mesh= and drop "
                "axis_names, or upgrade jax"
            )
    if f is None:
        import functools

        return functools.partial(shard_map, **kwargs)
    return _shard_map(f, **kwargs)


def shardmap_autodiff_limitation():
    """Reason string when the installed jax cannot differentiate through a
    ``shard_map`` region with non-empty ``auto`` axes, else ``None``.

    jax 0.4.x (including 0.4.37) hits a partial-eval bug when a shard_map
    with auto (replicated) axes is transposed: scalar residuals produced
    inside the manual region come out as per-shard values the transpose
    rule cannot re-broadcast, and the trace dies deep inside
    ``jax.interpreters.partial_eval`` with an opaque shape error. The two
    consumers of this contract:

    - ``analysis.sharding.pipelined_step_context`` falls back to a
      forward-only loss program on affected versions (its per-shard
      memory/donation report says so), and
    - the whole-step capture controller (``core.lazy``) refuses to capture
      a step on a pipelined (pp) mesh with a structured
      ``_CaptureIneligible(shardmap_autodiff_limitation())`` instead of
      surfacing the opaque trace error — the pp schedule is a shard_map
      region, so capturing forward+backward there would differentiate
      through it.

    jax >= 0.5 rewrote shard_map partial-eval and does not have the bug.
    """
    import jax

    try:
        ver = tuple(int(x) for x in jax.__version__.split(".")[:2])
    except ValueError:
        return None  # unparseable dev version: assume fixed
    return "shardmap_autodiff" if ver < (0, 5) else None


try:  # jax >= 0.5
    from jax.lax import axis_size
except ImportError:
    def axis_size(axis_name):
        """Size of a named mesh axis inside a shard_map/pmap region. psum of
        a Python literal folds to a concrete int on every jax version."""
        import jax

        return jax.lax.psum(1, axis_name)
