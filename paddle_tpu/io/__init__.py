"""paddle.io — Dataset / DataLoader / samplers.

Reference analogue: python/paddle/fluid/dataloader/ (2.8k LoC —
dataloader_iter.py single/multi-process iterators, worker.py shared-memory
queues) and python/paddle/fluid/reader.py:146 DataLoader.

TPU-native design: the loader produces numpy batches on the host; device
transfer happens at Tensor creation (or is overlapped by the jit path's
async dispatch). Multi-process workers use the standard multiprocessing
module; the reference's shared-memory LoDTensor queues are unnecessary since
numpy arrays pickle through pipes and the hot path is device-side anyway.
"""
from .dataset import (  # noqa: F401
    ChainDataset,
    ComposeDataset,
    ConcatDataset,
    Dataset,
    IterableDataset,
    Subset,
    TensorDataset,
    random_split,
)
from .sampler import (  # noqa: F401
    BatchSampler,
    DistributedBatchSampler,
    GlobalStepSampler,
    RandomSampler,
    Sampler,
    SequenceSampler,
    SubsetRandomSampler,
    WeightedRandomSampler,
)
from .dataloader import DataLoader, default_collate_fn, get_worker_info  # noqa: F401
from .bucketing import BucketSpec  # noqa: F401
