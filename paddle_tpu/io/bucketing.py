"""Shape-bucketing policy for variable-length data.

SURVEY §7 hard part 3. The reference absorbs ragged input via LoDTensor
(`paddle/fluid/framework/lod_tensor.h`) — kernels walk the level-of-detail
offsets, so every batch shape is fine. Under XLA, every distinct shape is
a separate compilation: the TPU-native answer is a PADDING POLICY — pad
ragged dims up to a small set of bucket boundaries so the number of
compiled programs is bounded, and surface a warning when a workload blows
past its recompile budget instead of silently compiling forever.

`BucketSpec` is both a standalone padding helper and a DataLoader policy
(`DataLoader(..., bucket_spec=spec)` pads each batch during collate):

    spec = BucketSpec(boundaries=[32, 64, 128], axis=-1, pad_value=0)
    loader = DataLoader(ragged_ds, batch_size=8, bucket_spec=spec)
    # every emitted ids array has seq len in {32, 64, 128}:
    # at most 3 compilations of the train step instead of one per length
"""
from __future__ import annotations

import warnings
from typing import Optional, Sequence

import numpy as np

__all__ = ["BucketSpec"]


class BucketSpec:
    """Pad-to-bucket policy.

    Args:
        boundaries: ascending bucket sizes for the ragged axis. A length
            above the largest boundary rounds up to the next multiple of
            it (shapes stay bounded: largest, 2*largest, ...).
        axis: the ragged axis of each array (default -1). The batch axis
            is handled by `pad_batch_to`, not `axis`.
        pad_value: fill for padded positions (e.g. a tokenizer's pad id).
        pad_batch_to: when set, short batches (the last-batch problem)
            are padded along axis 0 up to this size by REPEATING the
            final sample — keeping the compiled batch shape constant.
            `real_batch_size(batch)` recovers the unpadded count.
        max_shapes: recompile budget — distinct emitted shapes beyond
            this warn once per new shape (each one is an XLA compile).
        fields: indices of the sample tuple the policy applies to (None:
            every array field with ndim >= 1).
    """

    def __init__(self, boundaries: Sequence[int], axis: int = -1,
                 pad_value=0, pad_batch_to: Optional[int] = None,
                 max_shapes: int = 8, fields: Optional[Sequence[int]] = None):
        bs = [int(b) for b in boundaries]
        if not bs or sorted(bs) != bs or any(b <= 0 for b in bs):
            raise ValueError("boundaries must be ascending positive ints")
        self.boundaries = bs
        self.axis = int(axis)
        self.pad_value = pad_value
        self.pad_batch_to = pad_batch_to
        self.max_shapes = int(max_shapes)
        self.fields = None if fields is None else set(int(f) for f in fields)
        self._seen_shapes = set()
        # id(batch) -> unpadded row count, FIFO-capped: entries outlive
        # their batch only briefly, so a recycled id cannot alias a live
        # query beyond the last few batches
        from collections import OrderedDict

        self._real_sizes = OrderedDict()
        self._real_sizes_cap = 16

    # -- bucket arithmetic ---------------------------------------------------
    def bucket_for(self, length: int) -> int:
        """Smallest boundary >= length; beyond the table, the next
        multiple of the largest boundary."""
        for b in self.boundaries:
            if length <= b:
                return b
        top = self.boundaries[-1]
        return ((length + top - 1) // top) * top

    @property
    def seen_shapes(self):
        """Distinct padded shapes emitted so far (the compile count a
        consumer of this loader pays)."""
        return frozenset(self._seen_shapes)

    def _observe(self, shape):
        if shape in self._seen_shapes:
            return
        self._seen_shapes.add(shape)
        if len(self._seen_shapes) > self.max_shapes:
            warnings.warn(
                f"BucketSpec: {len(self._seen_shapes)} distinct padded "
                f"shapes exceed the recompile budget max_shapes="
                f"{self.max_shapes} (each is one XLA compilation). "
                f"Coarsen `boundaries` or raise the budget. "
                f"Newest shape: {shape}",
                stacklevel=3,
            )

    # -- array padding -------------------------------------------------------
    def pad(self, arr, target: Optional[int] = None):
        """Pad `arr` along `self.axis` to `target` (default: the bucket
        of its current length)."""
        a = np.asarray(arr)
        ax = self.axis if self.axis >= 0 else a.ndim + self.axis
        cur = a.shape[ax]
        tgt = self.bucket_for(cur) if target is None else int(target)
        if cur > tgt:
            raise ValueError(f"length {cur} exceeds pad target {tgt}")
        if cur == tgt:
            return a
        widths = [(0, 0)] * a.ndim
        widths[ax] = (0, tgt - cur)
        return np.pad(a, widths, constant_values=self.pad_value)

    def apply(self, batch):
        """Pad an already-collated batch (array, or tuple/list/dict of
        arrays) to bucket boundaries and record the emitted shapes."""
        if isinstance(batch, (tuple, list)):
            out = [
                self.apply(b) if self._applies(i, b) else b
                for i, b in enumerate(batch)
            ]
            return type(batch)(out)
        if isinstance(batch, dict):
            return {
                k: self.apply(v) if self._applies(None, v) else v
                for k, v in batch.items()
            }
        padded = self.pad(batch)
        self._observe(tuple(padded.shape))
        return padded

    def _applies(self, idx, value) -> bool:
        if (self.fields is not None and idx is not None
                and idx not in self.fields):
            return False
        if isinstance(value, list):
            return True
        # scalars (0-d arrays, python numbers — e.g. label fields) have no
        # ragged axis to pad
        return np.ndim(value) >= 1 and hasattr(value, "shape")

    # -- collate-time policy (ragged samples) --------------------------------
    def collate(self, samples, base_collate):
        """Pad each RAGGED sample field to the bucket of the batch max
        length, then run the normal collate (which can now stack).
        Handles tuple/list samples and bare-array samples."""
        if not samples:
            return base_collate(samples)
        first = samples[0]
        if isinstance(first, (tuple, list)):
            n_fields = len(first)
            cols = list(zip(*samples))
            padded_cols = []
            for i in range(n_fields):
                col = cols[i]
                if self._applies(i, np.asarray(col[0])):
                    arrs = [np.asarray(c) for c in col]
                    ax = self.axis if self.axis >= 0 else \
                        arrs[0].ndim + self.axis
                    tgt = self.bucket_for(max(a.shape[ax] for a in arrs))
                    padded_cols.append(
                        tuple(self.pad(a, tgt) for a in arrs)
                    )
                else:
                    padded_cols.append(col)
            samples = [
                type(first)(field[j] for field in padded_cols)
                for j in range(len(samples))
            ]
        else:
            arrs = [np.asarray(s) for s in samples]
            ax = self.axis if self.axis >= 0 else arrs[0].ndim + self.axis
            tgt = self.bucket_for(max(a.shape[ax] for a in arrs))
            samples = [self.pad(a, tgt) for a in arrs]
        batch = base_collate(samples)
        batch = self._pad_batch_dim(batch)
        self._record_shapes(batch)
        return batch

    def _pad_batch_dim(self, batch):
        if self.pad_batch_to is None:
            return batch
        tgt = int(self.pad_batch_to)

        def padb(a):
            arr = a if isinstance(a, np.ndarray) else None
            if arr is None:
                v = getattr(a, "_value", None)  # Tensor passthrough
                if v is None:
                    return a
                arr = np.asarray(v)
            n = arr.shape[0]
            if n >= tgt:
                return a
            reps = np.repeat(arr[-1:], tgt - n, axis=0)
            out = np.concatenate([arr, reps], axis=0)
            self._remember_real(out, n)
            if not isinstance(a, np.ndarray):
                from ..core.tensor import Tensor

                t = Tensor(out)
                self._remember_real(t, n)
                return t
            return out

        if isinstance(batch, (tuple, list)):
            return type(batch)(padb(b) for b in batch)
        return padb(batch)

    def _remember_real(self, obj, n):
        self._real_sizes[id(obj)] = int(n)
        while len(self._real_sizes) > self._real_sizes_cap:
            self._real_sizes.popitem(last=False)

    def real_batch_size(self, padded) -> Optional[int]:
        """Unpadded row count of a batch grown by `pad_batch_to`
        (None: the batch was not padded)."""
        return self._real_sizes.get(id(padded))

    def _record_shapes(self, batch):
        if isinstance(batch, (tuple, list)):
            for b in batch:
                self._record_shapes(b)
            return
        shp = getattr(batch, "shape", None)
        if shp is not None:
            self._observe(tuple(shp))
