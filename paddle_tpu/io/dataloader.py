"""DataLoader.

Reference analogue: python/paddle/fluid/reader.py:146 (DataLoader) and
dataloader_iter.py:146/:338 (single-process and multi-process iterators with
shared-memory worker queues, worker.py).

num_workers > 0 spawns real worker PROCESSES (fork) with task/result
queues — the reference's _DataLoaderIterMultiProcess: CPU-heavy
transforms run outside the trainer's GIL, large arrays ride
multiprocessing.shared_memory blocks instead of pickled pipe bytes
(use_shared_memory, the reference's LoDTensor shared-mem path), batches
reassemble in sampler order (or completion order with in_order=False),
worker crashes/exceptions propagate with their tracebacks, and
persistent_workers keeps the pool across epochs. A thread pool remains
available via use_thread_workers=True for GIL-releasing datasets.
"""
from __future__ import annotations

import itertools
import multiprocessing as mp
import os
import queue
import threading
import traceback
from typing import Callable, Optional

import numpy as np

from ..core.tensor import Tensor, to_tensor
from .dataset import Dataset, IterableDataset
from .sampler import BatchSampler

_worker_info = threading.local()

# arrays at least this large ride shared memory instead of the pickle pipe
_SHM_MIN_BYTES = 1 << 16


class WorkerInfo:
    def __init__(self, id, num_workers, dataset, seed):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset
        self.seed = seed


def get_worker_info():
    return getattr(_worker_info, "info", None)


def default_collate_fn(batch):
    """reference: dataloader/collate.py default_collate_fn."""
    sample = batch[0]
    if isinstance(sample, Tensor):
        import paddle_tpu as paddle

        return paddle.stack(batch, axis=0)
    if isinstance(sample, np.ndarray):
        return to_tensor(np.stack(batch, axis=0))
    if isinstance(sample, (int, np.integer)):
        return to_tensor(np.asarray(batch, dtype=np.int64))
    if isinstance(sample, (float, np.floating)):
        return to_tensor(np.asarray(batch, dtype=np.float32))
    if isinstance(sample, (str, bytes)):
        return list(batch)
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch]) for k in sample}
    if isinstance(sample, (tuple, list)):
        return [default_collate_fn(list(items)) for items in zip(*batch)]
    raise TypeError(f"cannot collate {type(sample)}")


def default_convert_fn(batch):
    return batch


# ---------------------------------------------------------------------------
# multiprocess transport: Tensor-free trees over queues, big arrays via shm
# ---------------------------------------------------------------------------
def _tree_to_ipc(obj, shm_blocks, use_shm):
    """Tensors/arrays → IPC-safe descriptors; big arrays → shared memory."""
    if isinstance(obj, Tensor):
        obj = np.asarray(obj.numpy())
    if isinstance(obj, np.ndarray):
        if use_shm and obj.nbytes >= _SHM_MIN_BYTES:
            from multiprocessing import resource_tracker, shared_memory

            shm = shared_memory.SharedMemory(create=True, size=obj.nbytes)
            # ownership transfers to the parent (which unlinks after copy);
            # deregister from THIS process's tracker or it double-unlinks
            # at worker exit and warns about the missing segment
            try:
                resource_tracker.unregister(shm._name, "shared_memory")
            except Exception:
                pass
            dst = np.ndarray(obj.shape, obj.dtype, buffer=shm.buf)
            dst[...] = obj
            shm_blocks.append(shm)
            return ("shm", shm.name, obj.shape, str(obj.dtype))
        return ("arr", obj)
    if isinstance(obj, dict):
        return ("dict", {k: _tree_to_ipc(v, shm_blocks, use_shm) for k, v in obj.items()})
    if isinstance(obj, (tuple, list)):
        return ("seq", type(obj) is tuple,
                [_tree_to_ipc(v, shm_blocks, use_shm) for v in obj])
    return ("raw", obj)


def _discard_payload(desc):
    """Unlink shared-memory blocks of a payload that will never be
    consumed (abandoned iterator / shutdown drain) — without this the
    /dev/shm segments outlive the process."""
    kind = desc[0]
    if kind == "shm":
        from multiprocessing import shared_memory

        try:
            shm = shared_memory.SharedMemory(name=desc[1])
            shm.close()
            shm.unlink()
        except FileNotFoundError:
            pass
    elif kind == "dict":
        for v in desc[1].values():
            _discard_payload(v)
    elif kind == "seq":
        for v in desc[2]:
            _discard_payload(v)


def _tree_from_ipc(desc, as_tensor=True):
    kind = desc[0]
    if kind == "shm":
        from multiprocessing import shared_memory

        _, name, shape, dtype = desc
        shm = shared_memory.SharedMemory(name=name)
        try:
            arr = np.array(np.ndarray(shape, dtype, buffer=shm.buf))  # copy out
        finally:
            shm.close()
            try:
                shm.unlink()
            except FileNotFoundError:
                pass
        return to_tensor(arr) if as_tensor else arr
    if kind == "arr":
        return to_tensor(desc[1]) if as_tensor else desc[1]
    if kind == "dict":
        return {k: _tree_from_ipc(v, as_tensor) for k, v in desc[1].items()}
    if kind == "seq":
        vals = [_tree_from_ipc(v, as_tensor) for v in desc[2]]
        return tuple(vals) if desc[1] else vals
    return desc[1]


def _np_collate(batch):
    """default_collate_fn's numpy twin: forked workers must never touch
    jax (the parent's XLA runtime does not survive fork), so worker-side
    collation stacks numpy and the parent wraps Tensors."""
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return np.stack(batch, axis=0)
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, dtype=np.int64)
    if isinstance(sample, (float, np.floating)):
        return np.asarray(batch, dtype=np.float32)
    if isinstance(sample, (str, bytes)):
        return list(batch)
    if isinstance(sample, dict):
        return {k: _np_collate([d[k] for d in batch]) for k in sample}
    if isinstance(sample, (tuple, list)):
        return [_np_collate(list(items)) for items in zip(*batch)]
    raise TypeError(f"cannot collate {type(sample)}")


def _mp_worker_main(wid, num_workers, dataset, collate_np, worker_init_fn,
                    task_q, result_q, use_shm, base_seed):
    """Worker process body (reference: fluid/dataloader/worker.py
    _worker_loop): pull index batches, fetch (+collate when the default
    collate is in use), ship results. collate_np=None ships raw sample
    trees and the parent runs the user's custom collate_fn."""
    seed = base_seed + wid  # fork copies the parent RNG state — reseed per
    np.random.seed(seed % (2**32))  # worker or augmentations duplicate
    _worker_info.info = WorkerInfo(wid, num_workers, dataset, seed)
    if worker_init_fn is not None:
        worker_init_fn(wid)
    while True:
        task = task_q.get()
        if task is None:
            return
        seq, indices = task
        shm_blocks = []
        try:
            samples = [dataset[i] for i in indices]
            if collate_np is not None:
                payload = _tree_to_ipc(collate_np(samples), shm_blocks, use_shm)
                result_q.put((seq, "ok", payload))
            else:
                payload = _tree_to_ipc(list(samples), shm_blocks, use_shm)
                result_q.put((seq, "samples", payload))
        except Exception as e:
            result_q.put((seq, "err",
                          f"{type(e).__name__}: {e}\n{traceback.format_exc()}"))
        finally:
            for shm in shm_blocks:
                shm.close()  # parent copies then unlinks


def _mp_worker_iterable(wid, num_workers, dataset, collate_np, worker_init_fn,
                        batch_size, drop_last, result_q, use_shm, base_seed):
    """IterableDataset worker: iterates ITS shard (the dataset uses
    get_worker_info to split) and ships whole batches, completion-ordered."""
    seed = base_seed + wid
    np.random.seed(seed % (2**32))
    _worker_info.info = WorkerInfo(wid, num_workers, dataset, seed)
    if worker_init_fn is not None:
        worker_init_fn(wid)

    def ship(batch):
        shm_blocks = []
        try:
            if collate_np is not None:
                result_q.put(
                    (-1, "ok", _tree_to_ipc(collate_np(batch), shm_blocks, use_shm))
                )
            else:
                result_q.put(
                    (-1, "samples", _tree_to_ipc(list(batch), shm_blocks, use_shm))
                )
        finally:
            for shm in shm_blocks:
                shm.close()

    try:
        batch = []
        for sample in dataset:
            batch.append(sample)
            if len(batch) == batch_size:
                ship(batch)
                batch = []
        if batch and not drop_last:
            ship(batch)
        result_q.put((-1, "done", wid))
    except Exception as e:
        result_q.put((-1, "err",
                      f"{type(e).__name__}: {e}\n{traceback.format_exc()}"))


class DataLoader:
    """reference: fluid/reader.py DataLoader (from_dataset/from_generator
    legacy constructors are served by paddle_tpu.static facade)."""

    def __init__(
        self,
        dataset: Dataset,
        feed_list=None,
        places=None,
        return_list=True,
        batch_sampler=None,
        batch_size=1,
        shuffle=False,
        drop_last=False,
        collate_fn: Optional[Callable] = None,
        num_workers=0,
        use_buffer_reader=True,
        prefetch_factor=2,
        use_shared_memory=True,
        timeout=0,
        worker_init_fn=None,
        persistent_workers=False,
        use_thread_workers=False,
        in_order=True,
        worker_collate_fn=None,
        return_numpy=False,
        bucket_spec=None,
    ):
        self.dataset = dataset
        self.return_list = return_list
        self.collate_fn = collate_fn or default_collate_fn
        # shape-bucketing policy (io.bucketing.BucketSpec): ragged sample
        # fields are padded to bucket boundaries during collate so the
        # number of distinct batch shapes a compiled consumer sees stays
        # bounded (each distinct shape = one XLA compilation)
        self.bucket_spec = bucket_spec
        if bucket_spec is not None:
            if (getattr(bucket_spec, "pad_batch_to", None) is not None
                    and int(num_workers) > 0 and not use_thread_workers):
                # process workers pad on a forked COPY of the spec: the
                # parent's real_batch_size() would silently report None
                # and padded repeat-rows would count as real samples
                raise ValueError(
                    "BucketSpec.pad_batch_to requires num_workers=0 or "
                    "use_thread_workers=True (the real-batch-size map "
                    "cannot cross a process fork)"
                )
            base = self.collate_fn

            def bucketed_collate(samples, _base=base, _spec=bucket_spec):
                return _spec.collate(samples, _base)

            self.collate_fn = bucketed_collate
            self._bucket_base_collate = base
        else:
            self._bucket_base_collate = None
        self.num_workers = int(num_workers)
        self.prefetch_factor = prefetch_factor
        self.worker_init_fn = worker_init_fn
        self.use_shared_memory = bool(use_shared_memory)
        self.timeout = float(timeout) if timeout else 0.0
        self.persistent_workers = bool(persistent_workers)
        # thread pool opt-in (GIL-releasing datasets); processes otherwise
        self.use_thread_workers = bool(use_thread_workers)
        # in_order=False yields batches in completion order (lower latency
        # under skewed per-batch cost; batch order becomes nondeterministic)
        self.in_order = bool(in_order)
        # worker_collate_fn: numpy-only collate executed INSIDE worker
        # processes (must not touch jax — forked children share no XLA
        # runtime); the default collate's numpy twin runs there when unset.
        # return_numpy=True skips the parent-side Tensor wrap (callers that
        # feed a compiled step can upload arrays themselves).
        self.worker_collate_fn = worker_collate_fn
        self.return_numpy = bool(return_numpy)
        self._pool = None  # persistent multiprocess pool state
        # live-iteration consumption tracking (see state_dict): sampler
        # state at iteration start + batches the caller has consumed since
        self._live_start = None
        self._live_consumed = 0
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size, drop_last=drop_last
            )

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset DataLoader has no len()")
        return len(self.batch_sampler)

    # -- resumable-iterator state (paddle.distributed.checkpoint) ----------
    def state_dict(self):
        """Sampler epoch/cursor + the framework RNG — what
        ``training_state(..., data=loader)`` packs next to params so a
        resumed run continues the data stream mid-epoch instead of
        re-reading it from the top (each sample consumed exactly once).

        The cursor reflects batches the CALLER has consumed, not how far
        the prefetchers have advanced the sampler — with num_workers>0 the
        sampler runs up to num_workers*prefetch_factor batches ahead, and
        checkpointing that inflated cursor would skip never-trained
        samples on resume."""
        from ..core import random as _random

        doc = {"rng": tuple(_random.default_generator.get_state())}
        sampler = getattr(self, "batch_sampler", None)
        if sampler is not None and hasattr(sampler, "state_dict"):
            if self._live_start is not None:
                s = dict(self._live_start)
                s["cursor"] = int(s.get("cursor", 0)) + self._live_consumed
            else:
                s = sampler.state_dict()
            doc["sampler"] = s
        return doc

    def load_state_dict(self, state):
        from ..core import random as _random

        if "rng" in state:
            _random.default_generator.set_state(tuple(state["rng"]))
        sampler = getattr(self, "batch_sampler", None)
        if (sampler is not None and "sampler" in state
                and hasattr(sampler, "load_state_dict")):
            sampler.load_state_dict(state["sampler"])
        self._live_start = None
        self._live_consumed = 0

    def _tracked(self, gen):
        """Count batches handed to the caller so state_dict can report a
        consumption cursor even while prefetchers run the sampler ahead.
        The snapshot is taken before the first pull (nothing has advanced
        yet); normal exhaustion hands authority back to the sampler (whose
        epoch-end state — cursor reset — is then correct)."""
        sampler = self.batch_sampler
        if self._live_start is not None and hasattr(sampler,
                                                    "load_state_dict"):
            # the previous iteration was ABANDONED mid-epoch: rewind the
            # sampler's prefetch overshoot to the consumption point, else
            # the never-delivered prefetched batches are skipped forever.
            # Rewind only a pure overshoot — if anything else moved
            # (set_epoch, an explicit cursor seek), the caller's state wins
            want = dict(self._live_start)
            want["cursor"] = int(want.get("cursor", 0)) + self._live_consumed
            cur = sampler.state_dict()
            cur_c = int(cur.get("cursor", 0))
            # an epoch-scoped sampler (has an "epoch" field) resets its
            # cursor to 0 when the PREFETCHER drains the whole epoch —
            # with the epoch unchanged that 0 is overshoot too, not a
            # caller reset (GlobalStepSampler's global cursor never
            # wraps, so 0 there means an explicit seek and wins). A
            # caller who consumed EVERY batch before breaking gets the
            # reset state as-is — rewinding to the full count would make
            # the next epoch iterate empty
            try:
                total = len(sampler)
            except TypeError:
                total = None
            wrapped = ("epoch" in cur and cur_c == 0
                       and 0 < int(want["cursor"])
                       and (total is None or int(want["cursor"]) < total))
            if ({k: v for k, v in cur.items() if k != "cursor"}
                    == {k: v for k, v in want.items() if k != "cursor"}
                    and (cur_c > int(want["cursor"]) or wrapped)):
                sampler.load_state_dict(want)
        self._live_start = sampler.state_dict()
        self._live_consumed = 0
        for batch in gen:
            # count BEFORE the yield: the generator only resumes at the
            # next pull, and a batch handed to the caller is consumed
            self._live_consumed += 1
            yield batch
        self._live_start = None
        self._live_consumed = 0

    def __iter__(self):
        if self._iterable_mode:
            if self.num_workers > 0 and not self.use_thread_workers:
                return self._iter_iterable_multiprocess()
            return self._iter_iterable()
        if self.num_workers == 0:
            it = self._iter_single()
        elif self.use_thread_workers:
            it = self._iter_threaded()
        else:
            it = self._iter_multiprocess()
        if hasattr(self.batch_sampler, "state_dict"):
            it = self._tracked(it)
        return it

    def _fetch(self, indices):
        samples = [self.dataset[i] for i in indices]
        return self.collate_fn(samples)

    def _iter_single(self):
        for indices in self.batch_sampler:
            yield self._fetch(indices)

    def _iter_iterable(self):
        batch = []
        for sample in self.dataset:
            batch.append(sample)
            if len(batch) == self.batch_size:
                yield self.collate_fn(batch)
                batch = []
        if batch and not self.drop_last:
            yield self.collate_fn(batch)

    # -- multiprocess path (reference: _DataLoaderIterMultiProcess) ---------
    def _worker_collate(self):
        """Worker-side collate: explicit worker_collate_fn, else the numpy
        twin of the default, else None for custom collate_fn (which runs in
        the parent on worker-fetched samples)."""
        if self.worker_collate_fn is not None:
            return self.worker_collate_fn
        if self.bucket_spec is not None:
            if self._bucket_base_collate is default_collate_fn:
                # numpy-pure bucket collate runs in the worker; the
                # parent re-observes shapes when wrapping Tensors
                spec = self.bucket_spec

                def worker_bucketed(samples, _spec=spec):
                    return _spec.collate(samples, _np_collate)

                return worker_bucketed
            return None
        return _np_collate if self.collate_fn is default_collate_fn else None

    def _start_pool(self):
        if self._pool is not None:
            return self._pool
        ctx = mp.get_context("fork")
        task_q = ctx.Queue()
        result_q = ctx.Queue()
        seed = int(np.random.randint(0, 2**31 - 1))
        procs = [
            ctx.Process(
                target=_mp_worker_main,
                args=(wid, self.num_workers, self.dataset,
                      self._worker_collate(), self.worker_init_fn,
                      task_q, result_q, self.use_shared_memory, seed),
                daemon=True,
            )
            for wid in range(self.num_workers)
        ]
        for p in procs:
            p.start()
        self._pool = (procs, task_q, result_q, itertools.count())
        return self._pool

    def _stop_pool(self):
        if self._pool is None:
            return
        procs, task_q, result_q, _ = self._pool
        for _ in procs:
            task_q.put(None)
        for p in procs:
            p.join(timeout=5.0)
            if p.is_alive():
                p.terminate()
        # unlink shm of any results nobody consumed
        while True:
            try:
                _, status, payload = result_q.get_nowait()
            except (queue.Empty, OSError):
                break
            if status in ("ok", "samples"):
                _discard_payload(payload)
        self._pool = None

    def _drain_outstanding(self, order, result_q, procs):
        """Consume (and discard) results for every still-outstanding seq so
        an abandoned iterator neither leaks /dev/shm segments nor poisons
        the shared queues for the next epoch (persistent_workers)."""
        deadline = 10.0
        import time as _time

        t0 = _time.monotonic()
        while order and _time.monotonic() - t0 < deadline:
            try:
                seq, status, payload = result_q.get(timeout=1.0)
            except queue.Empty:
                if all(not p.is_alive() for p in procs):
                    break
                continue
            if status in ("ok", "samples"):
                _discard_payload(payload)
            try:
                order.remove(seq)
            except ValueError:
                pass

    def _get_result(self, result_q, procs, done_ok=False):
        """Next worker result. done_ok: workers may legitimately have
        exited (iterable shards finishing early) — only a NONZERO exit
        code counts as a crash."""
        timeout = self.timeout or 5.0
        while True:
            try:
                return result_q.get(timeout=timeout)
            except queue.Empty:
                crashed = [
                    p for p in procs
                    if not p.is_alive() and p.exitcode not in (0, None)
                ]
                if crashed:
                    raise RuntimeError(
                        f"DataLoader worker (pid {crashed[0].pid}) exited "
                        f"unexpectedly with code {crashed[0].exitcode}"
                    ) from None
                if not done_ok and all(not p.is_alive() for p in procs):
                    raise RuntimeError(
                        "all DataLoader workers exited while batches were "
                        "still expected"
                    ) from None
                if self.timeout:
                    raise RuntimeError(
                        f"DataLoader timed out after {self.timeout}s waiting "
                        "for a worker batch"
                    ) from None

    def _finish_batch(self, status, payload):
        if status == "err":
            raise RuntimeError(f"DataLoader worker raised:\n{payload}")
        if status == "samples":
            batch = self.collate_fn(_tree_from_ipc(payload, as_tensor=False))
        else:
            batch = _tree_from_ipc(payload, as_tensor=not self.return_numpy)
            if self.bucket_spec is not None:
                # worker-side padding ran on a forked COPY of the spec —
                # re-observe emitted shapes here so seen_shapes/the
                # recompile-budget warning track the parent's reality
                self.bucket_spec._record_shapes(batch)
        return batch

    def _iter_multiprocess(self):
        from collections import deque

        procs, task_q, result_q, seq_counter = self._start_pool()
        n_prefetch = max(1, self.num_workers * self.prefetch_factor)
        sampler_iter = iter(self.batch_sampler)
        pending = {}  # seq -> (status, payload) awaiting in-order yield
        order = deque()  # submitted seqs in sampler order
        try:
            exhausted = False
            while True:
                while not exhausted and len(order) < n_prefetch:
                    try:
                        indices = next(sampler_iter)
                    except StopIteration:
                        exhausted = True
                        break
                    seq = next(seq_counter)
                    order.append(seq)
                    task_q.put((seq, list(indices)))
                if exhausted and not order:
                    return
                if self.in_order:
                    target = order[0]
                    while target not in pending:
                        seq, status, payload = self._get_result(result_q, procs)
                        pending[seq] = (status, payload)
                    status, payload = pending.pop(target)
                    order.popleft()
                else:
                    seq, status, payload = self._get_result(result_q, procs)
                    order.remove(seq)
                yield self._finish_batch(status, payload)
        finally:
            # account for every submitted batch: an abandoned iterator must
            # not leak shm segments or poison queues for the next epoch
            for status, payload in pending.values():
                if status in ("ok", "samples"):
                    _discard_payload(payload)
            for seq in list(pending):
                pending.pop(seq)
                try:
                    order.remove(seq)
                except ValueError:
                    pass
            self._drain_outstanding(order, result_q, procs)
            if not self.persistent_workers:
                self._stop_pool()

    def _iter_iterable_multiprocess(self):
        ctx = mp.get_context("fork")
        result_q = ctx.Queue()
        seed = int(np.random.randint(0, 2**31 - 1))
        procs = [
            ctx.Process(
                target=_mp_worker_iterable,
                args=(wid, self.num_workers, self.dataset,
                      self._worker_collate(), self.worker_init_fn,
                      self.batch_size, self.drop_last, result_q,
                      self.use_shared_memory, seed),
                daemon=True,
            )
            for wid in range(self.num_workers)
        ]
        for p in procs:
            p.start()
        done = 0
        try:
            while done < len(procs):
                _, status, payload = self._get_result(
                    result_q, procs, done_ok=True
                )
                if status == "done":
                    done += 1
                    continue
                yield self._finish_batch(status, payload)
        finally:
            # drain anything unconsumed (early break) before joining
            while True:
                try:
                    _, status, payload = result_q.get_nowait()
                except (queue.Empty, OSError):
                    break
                if status in ("ok", "samples"):
                    _discard_payload(payload)
            for p in procs:
                p.join(timeout=5.0)
                if p.is_alive():
                    p.terminate()

    def __del__(self):
        try:
            self._stop_pool()
        except Exception:
            pass

    def _iter_threaded(self):
        """Prefetching pipeline: worker threads fetch+collate index batches,
        results are yielded in order (numpy/dataset work releases the GIL
        enough in practice; the reference uses processes because its samples
        are C++ LoDTensors)."""
        sampler_iter = iter(self.batch_sampler)
        n_prefetch = max(1, self.num_workers * self.prefetch_factor)
        results = {}
        lock = threading.Lock()
        cond = threading.Condition(lock)
        task_q: "queue.Queue" = queue.Queue()
        stop = threading.Event()

        for wid in range(self.num_workers):
            if self.worker_init_fn:
                self.worker_init_fn(wid)

        def worker():
            while not stop.is_set():
                try:
                    seq, indices = task_q.get(timeout=0.1)
                except queue.Empty:
                    continue
                try:
                    out = self._fetch(indices)
                except Exception as e:  # propagate to consumer
                    out = e
                with cond:
                    results[seq] = out
                    cond.notify_all()

        threads = [
            threading.Thread(target=worker, daemon=True)
            for _ in range(self.num_workers)
        ]
        for t in threads:
            t.start()

        try:
            seq_submit = 0
            seq_yield = 0
            exhausted = False
            while True:
                while not exhausted and seq_submit - seq_yield < n_prefetch:
                    try:
                        indices = next(sampler_iter)
                    except StopIteration:
                        exhausted = True
                        break
                    task_q.put((seq_submit, indices))
                    seq_submit += 1
                if exhausted and seq_yield == seq_submit:
                    return
                with cond:
                    while seq_yield not in results:
                        cond.wait(timeout=1.0)
                    out = results.pop(seq_yield)
                seq_yield += 1
                if isinstance(out, Exception):
                    raise out
                yield out
        finally:
            stop.set()
