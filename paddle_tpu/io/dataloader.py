"""DataLoader.

Reference analogue: python/paddle/fluid/reader.py:146 (DataLoader) and
dataloader_iter.py:146/:338 (single-process and multi-process iterators with
shared-memory worker queues, worker.py).

The multi-process path uses a multiprocessing.Pool of index-batch workers
feeding an ordered prefetch queue — same prefetch discipline as the
reference's _DataLoaderIterMultiProcess but without LoDTensor shared-memory
blobs (numpy through pipes; device upload happens downstream, overlapped by
the jit path's async dispatch).
"""
from __future__ import annotations

import itertools
import queue
import threading
from typing import Callable, Optional

import numpy as np

from ..core.tensor import Tensor, to_tensor
from .dataset import Dataset, IterableDataset
from .sampler import BatchSampler

_worker_info = threading.local()


class WorkerInfo:
    def __init__(self, id, num_workers, dataset, seed):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset
        self.seed = seed


def get_worker_info():
    return getattr(_worker_info, "info", None)


def default_collate_fn(batch):
    """reference: dataloader/collate.py default_collate_fn."""
    sample = batch[0]
    if isinstance(sample, Tensor):
        import paddle_tpu as paddle

        return paddle.stack(batch, axis=0)
    if isinstance(sample, np.ndarray):
        return to_tensor(np.stack(batch, axis=0))
    if isinstance(sample, (int, np.integer)):
        return to_tensor(np.asarray(batch, dtype=np.int64))
    if isinstance(sample, (float, np.floating)):
        return to_tensor(np.asarray(batch, dtype=np.float32))
    if isinstance(sample, (str, bytes)):
        return list(batch)
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch]) for k in sample}
    if isinstance(sample, (tuple, list)):
        return [default_collate_fn(list(items)) for items in zip(*batch)]
    raise TypeError(f"cannot collate {type(sample)}")


def default_convert_fn(batch):
    return batch


class DataLoader:
    """reference: fluid/reader.py DataLoader (from_dataset/from_generator
    legacy constructors are served by paddle_tpu.static facade)."""

    def __init__(
        self,
        dataset: Dataset,
        feed_list=None,
        places=None,
        return_list=True,
        batch_sampler=None,
        batch_size=1,
        shuffle=False,
        drop_last=False,
        collate_fn: Optional[Callable] = None,
        num_workers=0,
        use_buffer_reader=True,
        prefetch_factor=2,
        use_shared_memory=True,
        timeout=0,
        worker_init_fn=None,
        persistent_workers=False,
    ):
        self.dataset = dataset
        self.return_list = return_list
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = int(num_workers)
        self.prefetch_factor = prefetch_factor
        self.worker_init_fn = worker_init_fn
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size, drop_last=drop_last
            )

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset DataLoader has no len()")
        return len(self.batch_sampler)

    def __iter__(self):
        if self._iterable_mode:
            return self._iter_iterable()
        if self.num_workers == 0:
            return self._iter_single()
        return self._iter_threaded()

    def _fetch(self, indices):
        samples = [self.dataset[i] for i in indices]
        return self.collate_fn(samples)

    def _iter_single(self):
        for indices in self.batch_sampler:
            yield self._fetch(indices)

    def _iter_iterable(self):
        batch = []
        for sample in self.dataset:
            batch.append(sample)
            if len(batch) == self.batch_size:
                yield self.collate_fn(batch)
                batch = []
        if batch and not self.drop_last:
            yield self.collate_fn(batch)

    def _iter_threaded(self):
        """Prefetching pipeline: worker threads fetch+collate index batches,
        results are yielded in order (numpy/dataset work releases the GIL
        enough in practice; the reference uses processes because its samples
        are C++ LoDTensors)."""
        sampler_iter = iter(self.batch_sampler)
        n_prefetch = max(1, self.num_workers * self.prefetch_factor)
        results = {}
        lock = threading.Lock()
        cond = threading.Condition(lock)
        task_q: "queue.Queue" = queue.Queue()
        stop = threading.Event()

        for wid in range(self.num_workers):
            if self.worker_init_fn:
                self.worker_init_fn(wid)

        def worker():
            while not stop.is_set():
                try:
                    seq, indices = task_q.get(timeout=0.1)
                except queue.Empty:
                    continue
                try:
                    out = self._fetch(indices)
                except Exception as e:  # propagate to consumer
                    out = e
                with cond:
                    results[seq] = out
                    cond.notify_all()

        threads = [
            threading.Thread(target=worker, daemon=True)
            for _ in range(self.num_workers)
        ]
        for t in threads:
            t.start()

        try:
            seq_submit = 0
            seq_yield = 0
            exhausted = False
            while True:
                while not exhausted and seq_submit - seq_yield < n_prefetch:
                    try:
                        indices = next(sampler_iter)
                    except StopIteration:
                        exhausted = True
                        break
                    task_q.put((seq_submit, indices))
                    seq_submit += 1
                if exhausted and seq_yield == seq_submit:
                    return
                with cond:
                    while seq_yield not in results:
                        cond.wait(timeout=1.0)
                    out = results.pop(seq_yield)
                seq_yield += 1
                if isinstance(out, Exception):
                    raise out
                yield out
        finally:
            stop.set()
