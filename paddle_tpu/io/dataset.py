"""Datasets. Reference: python/paddle/fluid/dataloader/dataset.py."""
from __future__ import annotations

import bisect
from typing import Iterable, List, Sequence


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset does not support indexing")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        lengths = {t.shape[0] for t in tensors}
        if len(lengths) > 1:
            raise ValueError("all tensors must share dim 0")
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        lengths = {len(d) for d in self.datasets}
        if len(lengths) > 1:
            raise ValueError("datasets must share length")

    def __len__(self):
        return len(self.datasets[0])

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            sample = d[idx]
            if isinstance(sample, (tuple, list)):
                out.extend(sample)
            else:
                out.append(sample)
        return tuple(out)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            for sample in d:
                yield sample


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cumulative_sizes = []
        s = 0
        for d in self.datasets:
            s += len(d)
            self.cumulative_sizes.append(s)

    def __len__(self):
        return self.cumulative_sizes[-1] if self.cumulative_sizes else 0

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        ds_idx = bisect.bisect_right(self.cumulative_sizes, idx)
        prev = self.cumulative_sizes[ds_idx - 1] if ds_idx > 0 else 0
        return self.datasets[ds_idx][idx - prev]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    import numpy as np

    from ..core import random as _random

    n = len(dataset)
    if sum(lengths) != n:
        # fractional lengths support (paddle >= 2.5 style)
        if all(0 < l < 1 for l in lengths):
            counts = [int(np.floor(n * l)) for l in lengths]
            rem = n - sum(counts)
            for i in range(rem):
                counts[i % len(counts)] += 1
            lengths = counts
        else:
            raise ValueError("sum of lengths != dataset size")
    rng = np.random.default_rng(
        generator.initial_seed() if generator is not None
        else _random.default_generator.initial_seed()
    )
    perm = rng.permutation(n)
    out, off = [], 0
    for l in lengths:
        out.append(Subset(dataset, perm[off : off + l].tolist()))
        off += l
    return out
