"""Samplers. Reference: python/paddle/fluid/dataloader/sampler.py,
batch_sampler.py, and fleet's DistributedBatchSampler
(python/paddle/io/__init__.py exports)."""
from __future__ import annotations

import math

import numpy as np

from ..core import random as _random


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None, generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples
        self.generator = generator

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        rng = np.random.default_rng(
            _random.default_generator.get_state()[0] * 1000003
            + _random.default_generator.get_state()[1]
        )
        if self.replacement:
            yield from rng.integers(0, n, self.num_samples).tolist()
        else:
            yield from rng.permutation(n)[: self.num_samples].tolist()

    def __len__(self):
        return self.num_samples


class SubsetRandomSampler(Sampler):
    def __init__(self, indices):
        self.indices = list(indices)

    def __iter__(self):
        rng = np.random.default_rng(_random.default_generator.get_state()[1])
        return iter(rng.permutation(self.indices).tolist())

    def __len__(self):
        return len(self.indices)


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, dtype=np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        rng = np.random.default_rng(_random.default_generator.get_state()[1])
        p = self.weights / self.weights.sum()
        idx = rng.choice(
            len(self.weights), self.num_samples, replace=self.replacement, p=p
        )
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    """reference: dataloader/batch_sampler.py BatchSampler."""

    def __init__(self, dataset=None, sampler=None, shuffle=False, batch_size=1,
                 drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Shards the dataset across data-parallel ranks.

    Reference: python/paddle/fluid/dataloader/batch_sampler.py
    DistributedBatchSampler (rank/num_replicas from ParallelEnv).

    ``total_size = ceil(len/nranks) * nranks`` pads the epoch with WRAPPED
    samples (``epoch_pad_ids``) so every rank sees the same batch count —
    fine for a fixed world, but a pad sample is a duplicate: under elastic
    rescale the global-step-indexed stream (:class:`GlobalStepSampler`)
    excludes padding entirely so shrink/grow never trains twice on a pad
    sample in one epoch. ``set_world`` re-shards in place after a rescale;
    ``state_dict``/``load_state_dict`` carry (epoch, batch cursor) so a
    resumed run continues mid-epoch instead of re-reading from the top."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        if num_replicas is None or rank is None:
            from ..distributed import get_rank, get_world_size

            num_replicas = num_replicas or get_world_size()
            rank = rank if rank is not None else get_rank()
        self.epoch = 0
        self._cursor = 0  # batches already consumed in the current epoch
        self.set_world(rank, num_replicas)

    def set_world(self, rank, num_replicas):
        """Elastic-rescale fix-up: re-shard the SAME dataset across a new
        world. The pad set is recomputed for the new ``total_size`` and the
        epoch survives; the mid-epoch BATCH cursor resets on a world
        change — rank r's batch k indexes a different interleaving in
        every world, so carrying it would skip and duplicate samples.
        Exactly-once mid-epoch resharding is GlobalStepSampler's contract
        (its global-step cursor IS world-invariant)."""
        num_replicas = int(num_replicas)
        rank = int(rank)
        if not (0 <= rank < num_replicas):
            raise ValueError(
                f"rank {rank} out of range for num_replicas={num_replicas}")
        if getattr(self, "nranks", None) is not None and (
                num_replicas != self.nranks or rank != self.local_rank):
            self._cursor = 0
        self.nranks = num_replicas
        self.local_rank = rank
        self.num_samples = int(math.ceil(len(self.dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def _epoch_indices(self):
        n = len(self.dataset)
        indices = np.arange(n)
        if self.shuffle:
            rng = np.random.default_rng(self.epoch)
            indices = rng.permutation(n)
        return indices

    def epoch_pad_ids(self):
        """The wrapped sample ids this epoch pads with (duplicates of real
        samples) — what the global-step-indexed stream must exclude."""
        pad = self.total_size - len(self.dataset)
        return self._epoch_indices()[:max(0, pad)].tolist()

    def __iter__(self):
        n = len(self.dataset)
        indices = self._epoch_indices()
        # pad to make evenly divisible, then shard
        pad = self.total_size - n
        if pad > 0:
            indices = np.concatenate([indices, indices[:pad]])
        local = indices[self.local_rank :: self.nranks]
        batch = []
        emitted = 0
        skip = self._cursor  # restored mid-epoch: fast-forward, no fetch
        for idx in local.tolist():
            batch.append(idx)
            if len(batch) == self.batch_size:
                emitted += 1
                if emitted > skip:
                    self._cursor = emitted
                    yield batch
                batch = []
        if batch and not self.drop_last:
            emitted += 1
            if emitted > skip:
                self._cursor = emitted
                yield batch
        self._cursor = 0  # epoch fully consumed

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch
        self._cursor = 0

    # -- resumable-iterator state (paddle.distributed.checkpoint) ---------
    def state_dict(self):
        return {"epoch": int(self.epoch), "cursor": int(self._cursor)}

    def load_state_dict(self, state):
        self.epoch = int(state.get("epoch", 0))
        self._cursor = int(state.get("cursor", 0))


class GlobalStepSampler(Sampler):
    """Deterministic, reshardable, global-step-indexed sampling (the
    elastic-rescale data plane — RESILIENCE.md "Elastic rescale").

    The sample ids consumed at global step ``s`` are a PURE FUNCTION of
    ``(seed, epoch, s)`` — epoch ``e = s // steps_per_epoch`` draws one
    seeded permutation, step ``s`` takes its ``global_batch_size`` slice —
    and are split across whatever world exists at ``s``: the step's
    ``global_batch_size // microbatch_size`` microbatches are dealt to
    ranks as contiguous aligned blocks, so rank ``r`` of world ``W`` runs
    ``accumulation_factor = num_microbatches // W`` accumulation
    microsteps. After a shrink/grow, ``set_world`` re-deals the SAME
    stream — survivors raise their accumulation factor to hold the global
    batch constant, and (with ``deterministic_tree_sum`` gradient
    reduction) the post-rescale trajectory is bitwise-identical to a
    fault-free run at matched global batch.

    Padding is excluded by construction: only the first
    ``steps_per_epoch * global_batch_size`` entries of each epoch's
    permutation are ever consumed — the tail remainder is dropped, never
    wrapped, so no sample can appear twice in one epoch's stream (the
    DistributedBatchSampler pad-duplication hazard cannot occur).

    ``world`` and ``num_microbatches`` must be powers of two (aligned
    blocks are then exact subtrees of the fixed reduction tree)."""

    def __init__(self, dataset, global_batch_size, seed=0, rank=0, world=1,
                 microbatch_size=None, shuffle=True):
        self._n = int(dataset) if isinstance(dataset, int) else len(dataset)
        self.global_batch_size = int(global_batch_size)
        if not (0 < self.global_batch_size <= self._n):
            raise ValueError(
                f"global_batch_size={global_batch_size} must be in "
                f"[1, {self._n}] (dataset length)")
        self.microbatch_size = int(microbatch_size or self.global_batch_size)
        if self.global_batch_size % self.microbatch_size:
            raise ValueError(
                f"global_batch_size={self.global_batch_size} must be a "
                f"multiple of microbatch_size={self.microbatch_size}")
        m = self.global_batch_size // self.microbatch_size
        if m & (m - 1):
            raise ValueError(
                f"num_microbatches={m} must be a power of two (aligned "
                "rank blocks must be exact subtrees of the reduction tree)")
        self.seed = int(seed)
        self.shuffle = bool(shuffle)
        self.cursor = 0  # next global step to consume
        self._perm_cache = (None, None)  # (epoch, permutation)
        self.set_world(rank, world)
        # triage sample-id recovery (paddle.profiler.attribution): ids at
        # step s are a pure function of (seed, epoch, s), so a postmortem
        # can name the offending batch's samples from the step number
        # alone. Registration is weak — diagnostics never extend the data
        # pipeline's lifetime — and the latest sampler wins.
        try:
            from ..profiler import attribution as _attribution

            _attribution.register_sampler(self)
        except Exception:
            pass

    # -- geometry --------------------------------------------------------
    @property
    def steps_per_epoch(self) -> int:
        return self._n // self.global_batch_size

    @property
    def num_microbatches(self) -> int:
        return self.global_batch_size // self.microbatch_size

    @property
    def accumulation_factor(self) -> int:
        """Microbatches this rank accumulates per global step (the PR 6
        k-step factor) — rises when the world shrinks, holding the global
        batch constant."""
        return self.num_microbatches // self.world

    @property
    def epoch(self) -> int:
        return self.cursor // self.steps_per_epoch

    def set_world(self, rank, world):
        """Elastic-rescale fix-up: re-deal the stream across a new world.
        Pure — the global stream is untouched; only which block of each
        step's microbatches this rank consumes changes."""
        rank, world = int(rank), int(world)
        if world <= 0 or world & (world - 1):
            raise ValueError(f"world={world} must be a positive power of "
                             "two (tree-reduction alignment)")
        if not (0 <= rank < world):
            raise ValueError(f"rank {rank} out of range for world={world}")
        if self.num_microbatches % world:
            raise ValueError(
                f"world={world} must divide num_microbatches="
                f"{self.num_microbatches} (every rank owns a whole block)")
        self.rank = rank
        self.world = world

    # -- the pure (seed, epoch, step) -> ids function ---------------------
    def _perm(self, epoch):
        cached_epoch, cached = self._perm_cache
        if cached_epoch == epoch:
            return cached
        if self.shuffle:
            perm = np.random.default_rng(
                (self.seed, int(epoch))).permutation(self._n)
        else:
            perm = np.arange(self._n)
        self._perm_cache = (epoch, perm)
        return perm

    def global_ids(self, step) -> np.ndarray:
        """All ``global_batch_size`` sample ids of global step ``step`` —
        identical on every rank, for any world, forever."""
        step = int(step)
        spe = self.steps_per_epoch
        epoch, pos = step // spe, step % spe
        g = self.global_batch_size
        ids = self._perm(epoch)[pos * g:(pos + 1) * g]
        assert len(ids) == g  # pad-free by construction: tail dropped
        return ids

    def microbatches(self, step):
        """This rank's contiguous aligned block of the step's microbatches
        (``accumulation_factor`` arrays of ``microbatch_size`` ids)."""
        ids = self.global_ids(step)
        k = self.accumulation_factor
        m = self.microbatch_size
        lo = self.rank * k
        return [ids[(lo + j) * m:(lo + j + 1) * m] for j in range(k)]

    def local_ids(self, step) -> list:
        """This rank's flat id list for global step ``step``."""
        return np.concatenate(self.microbatches(step)).tolist()

    # -- batch-sampler protocol ------------------------------------------
    def __iter__(self):
        """Yields this rank's per-global-step batches from the cursor to
        the end of the CURRENT epoch, advancing the cursor — a restored
        sampler resumes mid-epoch, consuming each sample exactly once."""
        epoch = self.epoch
        while self.cursor // self.steps_per_epoch == epoch:
            step = self.cursor
            self.cursor += 1
            yield self.local_ids(step)

    def __len__(self):
        return self.steps_per_epoch

    # -- resumable-iterator state (paddle.distributed.checkpoint) ---------
    def state_dict(self):
        return {
            "seed": self.seed,
            "cursor": int(self.cursor),
            "global_batch_size": self.global_batch_size,
            "microbatch_size": self.microbatch_size,
            "shuffle": self.shuffle,
        }

    def load_state_dict(self, state):
        for key in ("global_batch_size", "microbatch_size"):
            if key in state and int(state[key]) != getattr(self, key):
                raise ValueError(
                    f"restored {key}={state[key]} != configured "
                    f"{getattr(self, key)} — the global-step stream would "
                    "not be the one the checkpoint was cut from")
        self.seed = int(state.get("seed", self.seed))
        self.shuffle = bool(state.get("shuffle", self.shuffle))
        self.cursor = int(state.get("cursor", 0))
        self._perm_cache = (None, None)
