"""paddle.sparse — COO/CSR sparse tensors.

Reference analogue: python/paddle/sparse/ (sparse_coo_tensor /
sparse_csr_tensor creation over phi SparseCooTensor/SparseCsrTensor,
paddle/phi/core/sparse_coo_tensor.h, sparse kernels in
paddle/phi/kernels/sparse/) plus sparse ReLU/Conv3D layers.

TPU-native: the MXU has no gather/scatter sparsity — XLA wants dense,
static-shape work. SparseCooTensor therefore stores (indices, values,
shape) as dense jax arrays with a STATIC nnz (the compile-friendly
formulation: segment-sum scatter for matmul, elementwise ops on `values`
only), and converts to dense at ops where sparsity stops paying. CSR keeps
(crows, cols, values) and lowers through COO.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as paddle

from ..core.dispatch import apply
from ..core.tensor import Tensor, to_tensor

__all__ = [
    "sparse_coo_tensor", "sparse_csr_tensor", "SparseCooTensor",
    "SparseCsrTensor", "is_sparse", "add", "multiply", "matmul", "masked_matmul",
    "relu", "ReLU",
]


class SparseCooTensor:
    """COO: indices [ndim, nnz] + values [nnz, ...]."""

    def __init__(self, indices: Tensor, values: Tensor, shape: Sequence[int],
                 coalesced: bool = False):
        self.indices = indices if isinstance(indices, Tensor) else to_tensor(indices)
        self.values = values if isinstance(values, Tensor) else to_tensor(values)
        self.shape = list(int(s) for s in shape)
        self._coalesced = coalesced

    # paddle Tensor-surface parity
    def is_sparse_coo(self):
        return True

    def is_sparse_csr(self):
        return False

    @property
    def dtype(self):
        return self.values.dtype

    @property
    def nnz(self):
        return self.values.shape[0]

    def to_dense(self) -> Tensor:
        def f(idx, vals, shape):
            # hybrid COO: idx covers the leading sparse dims; trailing dims
            # (e.g. the channel axis of a voxel grid) live in the values
            out = jnp.zeros(shape, vals.dtype)
            nsparse = idx.shape[0]
            return out.at[tuple(idx[i] for i in range(nsparse))].add(vals)

        return apply(f, self.indices, self.values, shape=tuple(self.shape),
                     op_name="coo_to_dense")

    def to_sparse_csr(self) -> "SparseCsrTensor":
        if len(self.shape) != 2:
            raise ValueError("to_sparse_csr: only 2-D supported")
        idx = np.asarray(self.indices.numpy())
        vals = self.values
        order = np.lexsort((idx[1], idx[0]))
        rows, cols = idx[0][order], idx[1][order]
        crows = np.zeros(self.shape[0] + 1, np.int64)
        np.add.at(crows[1:], rows, 1)
        crows = np.cumsum(crows)
        return SparseCsrTensor(
            to_tensor(crows), to_tensor(cols),
            paddle.gather(vals, to_tensor(order.astype(np.int64))), self.shape,
        )

    def values_tensor(self):
        return self.values

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={self.dtype.name})")


class SparseCsrTensor:
    """CSR: crows [rows+1], cols [nnz], values [nnz]."""

    def __init__(self, crows, cols, values, shape):
        self.crows = crows if isinstance(crows, Tensor) else to_tensor(crows)
        self.cols = cols if isinstance(cols, Tensor) else to_tensor(cols)
        self.values = values if isinstance(values, Tensor) else to_tensor(values)
        self.shape = list(int(s) for s in shape)

    def is_sparse_coo(self):
        return False

    def is_sparse_csr(self):
        return True

    @property
    def dtype(self):
        return self.values.dtype

    @property
    def nnz(self):
        return self.values.shape[0]

    def to_sparse_coo(self, sparse_dim=2) -> SparseCooTensor:
        crows = np.asarray(self.crows.numpy())
        counts = np.diff(crows)
        rows = np.repeat(np.arange(len(counts)), counts)
        idx = paddle.stack(
            [to_tensor(rows.astype(np.int64)), self.cols.astype("int64")]
        )
        return SparseCooTensor(idx, self.values, self.shape)

    def to_dense(self) -> Tensor:
        return self.to_sparse_coo().to_dense()

    def __repr__(self):
        return (f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={self.dtype.name})")


def is_sparse(x):
    return isinstance(x, (SparseCooTensor, SparseCsrTensor))


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True) -> SparseCooTensor:
    """reference: sparse/creation.py sparse_coo_tensor."""
    idx = indices if isinstance(indices, Tensor) else to_tensor(np.asarray(indices))
    if isinstance(values, Tensor):
        vals = values  # caller's tensor keeps its own trainability
    else:
        vals = to_tensor(np.asarray(values), dtype=dtype)
        vals.stop_gradient = stop_gradient
    if shape is None:
        mx = np.asarray(idx.numpy()).max(axis=1) + 1
        shape = [int(m) for m in mx]
    return SparseCooTensor(idx.astype("int64"), vals, shape)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True) -> SparseCsrTensor:
    """reference: sparse/creation.py sparse_csr_tensor."""
    if isinstance(values, Tensor):
        vals = values  # caller's tensor keeps its own trainability
    else:
        vals = to_tensor(np.asarray(values), dtype=dtype)
        vals.stop_gradient = stop_gradient
    return SparseCsrTensor(
        to_tensor(np.asarray(crows)).astype("int64"),
        to_tensor(np.asarray(cols)).astype("int64"), vals, shape,
    )


def _coo(x):
    if isinstance(x, SparseCsrTensor):
        return x.to_sparse_coo()
    return x


def add(x, y):
    """sparse + sparse → sparse (concatenated, uncoalesced) or
    sparse + dense → dense."""
    x = _coo(x)
    if isinstance(y, Tensor):
        return x.to_dense() + y
    y = _coo(y)
    idx = paddle.concat([x.indices, y.indices], axis=1)
    vals = paddle.concat([x.values, y.values], axis=0)
    return SparseCooTensor(idx, vals, x.shape)


def multiply(x, y):
    """elementwise multiply: sparse × dense gathers the dense entries."""
    x = _coo(x)
    if isinstance(y, (int, float)):
        return SparseCooTensor(x.indices, x.values * y, x.shape)

    def f(idx, vals, dense):
        return vals * dense[tuple(idx[i] for i in range(dense.ndim))]

    vals = apply(f, x.indices, x.values,
                 y if isinstance(y, Tensor) else _coo(y).to_dense(),
                 op_name="coo_mul")
    return SparseCooTensor(x.indices, vals, x.shape)


def matmul(x, y):
    """sparse [M, K] @ dense [K, N] → dense, via gather + segment-sum (the
    XLA-friendly SpMM: static nnz, one scatter-add)."""
    x = _coo(x)
    if not isinstance(y, Tensor):
        y = _coo(y).to_dense()

    def f(idx, vals, dense, m):
        rows, cols = idx[0], idx[1]
        gathered = dense[cols] * vals[:, None]        # [nnz, N]
        return jax.ops.segment_sum(gathered, rows, num_segments=m)

    return apply(f, x.indices, x.values, y, m=x.shape[0], op_name="spmm")


def masked_matmul(x: Tensor, y: Tensor, mask: SparseCooTensor):
    """(x @ y) sampled at mask's sparsity (SDDMM)."""

    def f(idx, xv, yv):
        rows, cols = idx[0], idx[1]
        return (xv[rows] * yv[:, cols].T).sum(-1)

    vals = apply(f, mask.indices, x, y, op_name="sddmm")
    return SparseCooTensor(mask.indices, vals, mask.shape)


def relu(x):
    x = _coo(x)
    return SparseCooTensor(x.indices, paddle.nn.functional.relu(x.values), x.shape)


class ReLU(paddle.nn.Layer):
    """reference: sparse/layer/activation.py ReLU."""

    def forward(self, x):
        return relu(x)


# functional namespace parity (paddle.sparse.functional.relu)
class _Functional:
    relu = staticmethod(relu)


functional = _Functional()


def _dense3d(x):
    """SparseCooTensor [N, D, H, W, C] -> dense Tensor (autograd intact:
    to_dense is a dispatched scatter, so grads flow back to x.values)."""
    if isinstance(x, SparseCooTensor):
        return x.to_dense()
    return x


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NDHWC"):
    """Sparse 3-D convolution (reference: sparse/functional/conv.py:68
    conv3d). TPU-native lowering: sparse voxels are densified and the conv
    runs on the MXU — XLA's strength is dense contraction; scatter/gather
    sparse kernels (the reference's GPU rulebook) do not map to the
    systolic array. Weight layout follows the reference: [kD, kH, kW,
    C_in/g, C_out]."""
    import jax.numpy as jnp

    from ..core.dispatch import apply
    from ..nn import functional as F

    if data_format != "NDHWC":
        raise ValueError(
            f"sparse conv3d supports NDHWC only (the reference sparse "
            f"layout), got {data_format}"
        )
    dense = _dense3d(x)
    from ..core.tensor import Tensor as _T

    xt = dense if isinstance(dense, _T) else _T(dense, stop_gradient=True)
    w = weight if hasattr(weight, "_value") else _T(weight)
    # reference weight [kd, kh, kw, cin/g, cout] -> lax OIDHW
    wt = w.transpose([4, 3, 0, 1, 2])
    # bias joins AFTER sparsification: the reference adds it only at active
    # output sites; a dense bias-add would turn every empty voxel nonzero
    out = F.conv3d(
        xt, wt, None, stride=stride, padding=padding, dilation=dilation,
        groups=groups, data_format="NDHWC",
    )
    sp = _to_sparse_coo(out)
    if bias is not None:
        sp = SparseCooTensor(sp.indices, sp.values + bias, sp.shape)
    return sp


def subm_conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NDHWC"):
    """Submanifold sparse conv (reference: sparse/functional/conv.py:182):
    output sites restricted to the input's active sites."""
    import numpy as _np

    if not isinstance(x, SparseCooTensor):
        raise TypeError("subm_conv3d input must be a SparseCooTensor")
    out = conv3d(x, weight, bias, stride=stride, padding=padding,
                 dilation=dilation, groups=groups, data_format=data_format)
    # the submanifold constraint (output sites == input sites) only makes
    # sense when the conv preserves the voxel grid — the reference requires
    # stride 1 + shape-preserving padding for subm convs
    if out.shape[:-1] != x.shape[:-1]:
        raise ValueError(
            f"subm_conv3d needs a shape-preserving conv (stride 1, padding "
            f"kernel//2): input sites grid {x.shape[:-1]} vs conv output "
            f"grid {out.shape[:-1]}"
        )
    # gather the dense conv result at the INPUT's active sites — this IS
    # the submanifold output, and the gather keeps autograd connected
    dense = out.to_dense()
    vals = _gather_sites(dense, x.indices)
    return SparseCooTensor(x.indices, vals, out.shape)


def _gather_sites(dense_t, indices):
    """Differentiable gather of dense values at COO sites [nsparse, nnz]."""

    def f(d, idx):
        return d[tuple(idx[i] for i in range(idx.shape[0]))]

    return apply(f, dense_t, indices, op_name="coo_gather_sites")


def _to_sparse_coo(dense_t):
    """Sparsify a dense Tensor. Active sites are found on the host from a
    DETACHED copy (data-dependent nnz can't trace); the values themselves
    are gathered differentiably so grads flow to the producing op."""
    import numpy as _np

    arr = _np.asarray(dense_t.numpy())
    site = _np.abs(arr).sum(-1) > 0 if arr.ndim >= 2 else _np.abs(arr) > 0
    idx = _np.stack(_np.nonzero(site))
    from ..core.tensor import to_tensor as _tt

    idx_t = _tt(idx.astype(_np.int64))
    vals = _gather_sites(dense_t, idx_t)
    return SparseCooTensor(idx_t, vals, list(arr.shape))


class _Conv3DBase(paddle.nn.Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, subm=False,
                 padding_mode="zeros", weight_attr=None, bias_attr=None,
                 data_format="NDHWC"):
        super().__init__()
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size,) * 3
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._groups = groups
        self._subm = subm
        # reference sparse conv weight layout [kd, kh, kw, cin/g, cout]
        self.weight = self.create_parameter(
            shape=[*kernel_size, in_channels // groups, out_channels],
            attr=weight_attr,
        )
        self.bias = (
            None if bias_attr is False
            else self.create_parameter(shape=[out_channels], attr=bias_attr,
                                       is_bias=True)
        )

    def forward(self, x):
        fn = subm_conv3d if self._subm else conv3d
        return fn(x, self.weight, self.bias, self._stride, self._padding,
                  self._dilation, self._groups)


class Conv3D(_Conv3DBase):
    """reference: sparse/layer/conv.py Conv3D."""

    def __init__(self, *args, **kwargs):
        kwargs.pop("subm", None)
        super().__init__(*args, subm=False, **kwargs)


class SubmConv3D(_Conv3DBase):
    """reference: sparse/layer/conv.py:250 SubmConv3D."""

    def __init__(self, *args, **kwargs):
        kwargs.pop("subm", None)
        super().__init__(*args, subm=True, **kwargs)


_Functional.conv3d = staticmethod(conv3d)
_Functional.subm_conv3d = staticmethod(subm_conv3d)

from . import creation  # noqa: E402,F401
