"""paddle.static.nn — static-graph layer functions.

Reference analogue: python/paddle/static/nn/__init__.py (fc, conv2d,
batch_norm, control flow, sequence ops...). On this stack a "static layer
fn" is an eager/traceable call that creates its parameters in the current
default Program's scope on first use — the same build-once semantics
without a ProgramDesc. Sequence ops operate on padded [B, T, ...] batches
(the LoDTensor replacement per SURVEY §7 "dynamic shapes" policy); ragged
semantics take an optional `length` tensor where the reference reads LoD.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

import paddle_tpu as paddle

__all__ = [
    "fc", "embedding", "conv2d", "conv3d", "conv2d_transpose",
    "conv3d_transpose", "batch_norm", "layer_norm", "instance_norm",
    "group_norm", "data_norm", "spectral_norm", "prelu", "deform_conv2d",
    "bilinear_tensor_product", "row_conv", "nce", "crf_decoding",
    "multi_box_head", "py_func", "case", "cond", "switch_case", "while_loop",
    "sequence_concat", "sequence_conv", "sequence_enumerate",
    "sequence_expand", "sequence_expand_as", "sequence_first_step",
    "sequence_last_step", "sequence_pad", "sequence_pool",
    "sequence_reshape", "sequence_reverse", "sequence_scatter",
    "sequence_slice", "sequence_softmax", "sequence_unpad",
]

_param_registry = {}


def _layer_cache(key, builder, named=True):
    """Build-once parameter holder keyed by the call-site key.

    Unnamed calls additionally key on their call-sequence index within the
    current builder invocation (reset by Program's builder wrapper), so two
    same-shape unnamed layers get independent parameters — matching the
    reference, where every fc() call creates fresh parameters unless a
    shared param name is given."""
    from . import default_main_program

    prog = default_main_program()
    cache = getattr(prog, "_static_layers", None)
    if cache is None:
        cache = prog._static_layers = {}
    if not named:
        seq = getattr(prog, "_call_seq", None)
        if seq is None:
            seq = prog._call_seq = {}
        idx = seq.get(key, 0)
        seq[key] = idx + 1
        key = key + ("#call", idx)
    if key not in cache:
        cache[key] = builder()
    return cache[key]


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    """reference: static/nn/common.py fc."""
    xs = x if isinstance(x, (list, tuple)) else [x]
    flat = []
    for t in xs:
        lead = 1
        for d in t.shape[:num_flatten_dims]:
            lead *= d
        flat.append(t.reshape([lead, -1]))
    key = (name or "fc", size, tuple(t.shape[-1] for t in flat))

    def build():
        return [
            paddle.nn.Linear(int(t.shape[-1]), size, weight_attr=weight_attr,
                             bias_attr=bias_attr if i == 0 else False)
            for i, t in enumerate(flat)
        ]

    layers = _layer_cache(key, build, named=name is not None)
    out = layers[0](flat[0])
    for layer, t in zip(layers[1:], flat[1:]):
        out = out + layer(t)
    if activation:
        out = getattr(paddle.nn.functional, activation)(out)
    lead_shape = list(xs[0].shape[:num_flatten_dims])
    return out.reshape(lead_shape + [size])


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32"):
    key = ("embedding", tuple(size))
    layer = _layer_cache(
        key, lambda: paddle.nn.Embedding(size[0], size[1],
                                         padding_idx=padding_idx,
                                         weight_attr=param_attr),
    named=False)
    return layer(input)


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None, data_format="NCHW"):
    cin = input.shape[1] if data_format == "NCHW" else input.shape[-1]
    key = (name or "conv2d", cin, num_filters, tuple(np.atleast_1d(filter_size)))
    layer = _layer_cache(
        key, lambda: paddle.nn.Conv2D(
            int(cin), num_filters, filter_size, stride=stride, padding=padding,
            dilation=dilation, groups=groups, weight_attr=param_attr,
            bias_attr=bias_attr, data_format=data_format),
    named=name is not None)
    out = layer(input)
    return getattr(paddle.nn.functional, act)(out) if act else out


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None, data_format="NCDHW"):
    cin = input.shape[1] if data_format == "NCDHW" else input.shape[-1]
    key = (name or "conv3d", cin, num_filters, tuple(np.atleast_1d(filter_size)))
    layer = _layer_cache(
        key, lambda: paddle.nn.Conv3D(
            int(cin), num_filters, filter_size, stride=stride, padding=padding,
            dilation=dilation, groups=groups, weight_attr=param_attr,
            bias_attr=bias_attr, data_format=data_format),
    named=name is not None)
    out = layer(input)
    return getattr(paddle.nn.functional, act)(out) if act else out


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None, data_format="NCHW"):
    cin = input.shape[1] if data_format == "NCHW" else input.shape[-1]
    key = (name or "conv2dT", cin, num_filters, tuple(np.atleast_1d(filter_size)))
    layer = _layer_cache(
        key, lambda: paddle.nn.Conv2DTranspose(
            int(cin), num_filters, filter_size, stride=stride, padding=padding,
            dilation=dilation, groups=groups, weight_attr=param_attr,
            bias_attr=bias_attr, data_format=data_format),
    named=name is not None)
    out = layer(input, output_size=output_size)
    return getattr(paddle.nn.functional, act)(out) if act else out


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None, data_format="NCDHW"):
    cin = input.shape[1] if data_format == "NCDHW" else input.shape[-1]
    key = (name or "conv3dT", cin, num_filters, tuple(np.atleast_1d(filter_size)))
    layer = _layer_cache(
        key, lambda: paddle.nn.Conv3DTranspose(
            int(cin), num_filters, filter_size, stride=stride, padding=padding,
            dilation=dilation, groups=groups, weight_attr=param_attr,
            bias_attr=bias_attr, data_format=data_format),
    named=name is not None)
    out = layer(input, output_size=output_size)
    return getattr(paddle.nn.functional, act)(out) if act else out


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               in_place=False, name=None, moving_mean_name=None,
               moving_variance_name=None, do_model_average_for_mean_and_var=True,
               use_global_stats=False):
    from . import default_main_program

    c = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    key = (name or "batch_norm", int(c))
    layer = _layer_cache(
        key, lambda: paddle.nn.BatchNorm(
            int(c), momentum=momentum, epsilon=epsilon,
            param_attr=param_attr, bias_attr=bias_attr,
            data_layout=data_layout),
    named=name is not None)
    # a Program cloned with for_test=True marks itself eval-mode; the op's
    # is_test then defaults on, like the reference clone's is_test rewrite
    is_test = is_test or getattr(default_main_program(), "_for_test", False)
    layer.training = not is_test and not use_global_stats
    out = layer(input)
    return getattr(paddle.nn.functional, act)(out) if act else out


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    shape = [int(s) for s in input.shape[begin_norm_axis:]]
    key = (name or "layer_norm", tuple(shape))
    layer = _layer_cache(
        key, lambda: paddle.nn.LayerNorm(shape, epsilon=epsilon,
                                         weight_attr=param_attr if scale else False,
                                         bias_attr=bias_attr if shift else False),
    named=name is not None)
    out = layer(input)
    return getattr(paddle.nn.functional, act)(out) if act else out


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None,
                  name=None):
    c = int(input.shape[1])
    layer = _layer_cache(
        (name or "instance_norm", c),
        lambda: paddle.nn.InstanceNorm2D(c, epsilon=epsilon,
                                         weight_attr=param_attr,
                                         bias_attr=bias_attr),
    named=False)
    return layer(input)


def group_norm(input, groups, epsilon=1e-5, param_attr=None, bias_attr=None,
               act=None, data_layout="NCHW", name=None):
    c = int(input.shape[1] if data_layout == "NCHW" else input.shape[-1])
    layer = _layer_cache(
        (name or "group_norm", groups, c),
        lambda: paddle.nn.GroupNorm(groups, c, epsilon=epsilon,
                                    weight_attr=param_attr,
                                    bias_attr=bias_attr),
    named=name is not None)
    return layer(input)


def data_norm(input, act=None, epsilon=1e-5, param_attr=None,
              enable_scale_and_shift=False, name=None, **kwargs):
    """reference: static/nn/common.py data_norm — normalization by global
    accumulated statistics (PS CTR models). Single-process form: running
    batch statistics without scale/shift coupling."""
    c = int(input.shape[-1])

    def build():
        import paddle_tpu as p

        state = {
            "size": p.to_tensor(np.full(c, epsilon, np.float32)),
            "sum": p.to_tensor(np.zeros(c, np.float32)),
            "square_sum": p.to_tensor(np.full(c, epsilon, np.float32)),
        }
        return state

    state = _layer_cache((name or "data_norm", c), build, named=name is not None)
    bsz = input.shape[0]
    import jax.core as _jcore

    tracing = isinstance(getattr(input, "_value", None), _jcore.Tracer)
    if not tracing:
        # running-stat accumulation is a host-side mutation; under a jit
        # trace (Executor's compiled path) the stats freeze at their
        # warm-run values — the traced program must stay pure
        with paddle.no_grad():
            state["size"].set_value(state["size"] + float(bsz))
            state["sum"].set_value(state["sum"] + input.sum(axis=0).detach())
            state["square_sum"].set_value(
                state["square_sum"] + (input * input).sum(axis=0).detach()
            )
    mean = state["sum"] / state["size"]
    var = state["square_sum"] / state["size"] - mean * mean
    out = (input - mean) / paddle.sqrt(var.clip(min=epsilon))
    return getattr(paddle.nn.functional, act)(out) if act else out


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    layer = _layer_cache(
        (name or "spectral_norm", tuple(weight.shape), dim),
        lambda: paddle.nn.SpectralNorm(weight.shape, dim=dim,
                                       power_iters=power_iters, eps=eps),
    named=False)
    return layer(weight)


def prelu(x, mode="all", param_attr=None, data_format="NCHW", name=None):
    if mode == "all":
        num = 1
    elif mode == "channel":
        num = int(x.shape[1] if data_format == "NCHW" else x.shape[-1])
    else:  # element
        num = int(np.prod(x.shape[1:]))
    layer = _layer_cache(
        (name or "prelu", mode, num),
        lambda: paddle.nn.PReLU(num_parameters=num, weight_attr=param_attr),
    named=False)
    return layer(x)


def deform_conv2d(x, offset, mask, num_filters, filter_size, stride=1,
                  padding=0, dilation=1, groups=1, deformable_groups=1,
                  im2col_step=1, param_attr=None, bias_attr=None, name=None):
    from ..vision.ops import DeformConv2D

    cin = int(x.shape[1])
    layer = _layer_cache(
        (name or "deform_conv2d", cin, num_filters,
         tuple(np.atleast_1d(filter_size))),
        lambda: DeformConv2D(cin, num_filters, filter_size, stride=stride,
                             padding=padding, dilation=dilation,
                             deformable_groups=deformable_groups,
                             groups=groups, weight_attr=param_attr,
                             bias_attr=bias_attr),
    named=name is not None)
    return layer(x, offset, mask)


def bilinear_tensor_product(x, y, size, act=None, name=None,
                            param_attr=None, bias_attr=None):
    layer = _layer_cache(
        (name or "bilinear", int(x.shape[-1]), int(y.shape[-1]), size),
        lambda: paddle.nn.Bilinear(int(x.shape[-1]), int(y.shape[-1]), size,
                                   weight_attr=param_attr,
                                   bias_attr=bias_attr),
    named=False)
    out = layer(x, y)
    return getattr(paddle.nn.functional, act)(out) if act else out


def row_conv(input, future_context_size, param_attr=None, act=None):
    """Lookahead row convolution (reference: static/nn/common.py row_conv —
    the DeepSpeech2 op): out[t] = sum_{i=0..k} in[t+i] * W[i]."""
    k = future_context_size + 1
    d = int(input.shape[-1])
    layer = _layer_cache(
        ("row_conv", k, d),
        lambda: paddle.create_parameter([k, d], "float32"),
    named=False)
    import jax.numpy as jnp

    from ..core.dispatch import apply

    def _rc(v, w):
        # v [B, T, D]; pad future frames with zeros
        pads = jnp.zeros(v.shape[:1] + (k - 1,) + v.shape[2:], v.dtype)
        vp = jnp.concatenate([v, pads], axis=1)
        out = jnp.zeros_like(v)
        for i in range(k):
            out = out + vp[:, i : i + v.shape[1]] * w[i]
        return out

    out = apply(_rc, input, layer, op_name="row_conv")
    return getattr(paddle.nn.functional, act)(out) if act else out


def nce(input, label, num_total_classes, sample_weight=None, param_attr=None,
        bias_attr=None, num_neg_samples=None, name=None, sampler="uniform",
        custom_dist=None, seed=0, is_sparse=False):
    """Noise-contrastive estimation loss (reference: static/nn/common.py
    nce op): positive class + sampled negatives through sigmoid CE."""
    d = int(input.shape[-1])
    num_neg = num_neg_samples or 10

    def build():
        w = paddle.create_parameter([num_total_classes, d], "float32")
        b = paddle.create_parameter([num_total_classes], "float32",
                                    is_bias=True)
        return (w, b)

    w, b = _layer_cache(("nce", num_total_classes, d), build, named=False)
    bsz = input.shape[0]
    import jax as _jax

    from ..core import random as _random

    neg = _jax.random.randint(
        _random.next_key(), (bsz, num_neg), 0, num_total_classes
    )
    from ..core.tensor import Tensor

    neg_t = Tensor(neg, stop_gradient=True)
    lab = label.reshape([-1, 1])
    idx = paddle.concat([lab, neg_t], axis=1)            # [B, 1+num_neg]
    wsel = paddle.gather(w, idx.reshape([-1])).reshape(
        [bsz, 1 + num_neg, d]
    )
    bsel = paddle.gather(b, idx.reshape([-1])).reshape([bsz, 1 + num_neg])
    logits = (wsel * input.unsqueeze(1)).sum(axis=-1) + bsel
    targets = paddle.concat(
        [paddle.ones([bsz, 1]), paddle.zeros([bsz, num_neg])], axis=1
    )
    loss = paddle.nn.functional.binary_cross_entropy_with_logits(
        logits, targets, reduction="none"
    )
    return loss.sum(axis=1, keepdim=True)


def crf_decoding(input, param_attr=None, label=None, length=None):
    """Viterbi decode with a learned transition matrix (reference:
    static/nn/common.py crf_decoding over the linear_chain_crf params)."""
    from ..text import viterbi_decode

    n_tags = int(input.shape[-1])
    trans = _layer_cache(
        ("crf_decoding", n_tags),
        lambda: paddle.create_parameter([n_tags + 2, n_tags], "float32"),
    named=False)
    # reference layout: rows 0/1 are start/stop, rest tag-to-tag
    if length is None:
        length = paddle.to_tensor(
            np.full(input.shape[0], input.shape[1], np.int64)
        )
    scores, path = viterbi_decode(
        input, trans[2:], length, include_bos_eos_tag=False
    )
    return path


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, step_w=None, step_h=None,
                   offset=0.5, variance=(0.1, 0.1, 0.2, 0.2), flip=True,
                   clip=False, kernel_size=1, pad=0, stride=1, name=None,
                   min_max_aspect_ratios_order=False):
    """SSD detection heads (reference: static/nn/common.py multi_box_head):
    per-feature-map loc/conf convs + prior boxes."""
    locs, confs, priors, pvars = [], [], [], []
    img_h, img_w = int(image.shape[2]), int(image.shape[3])
    n_layers = len(inputs)
    if min_sizes is None:
        # reference ratio interpolation (first layer pinned at 10%/20%)
        min_ratio, max_ratio = int(min_ratio), int(max_ratio)
        min_sizes, max_sizes = [], []
        if n_layers > 2:
            step = int((max_ratio - min_ratio) / (n_layers - 2))
            for r in range(min_ratio, max_ratio + 1, step):
                min_sizes.append(base_size * r / 100.0)
                max_sizes.append(base_size * (r + step) / 100.0)
        else:
            min_sizes.append(base_size * min_ratio / 100.0)
            max_sizes.append(base_size * max_ratio / 100.0)
        min_sizes = [base_size * 0.1] + min_sizes
        max_sizes = [base_size * 0.2] + max_sizes
    for i, feat in enumerate(inputs):
        ar = aspect_ratios[i]
        ar_full = [1.0]
        for a in ar:
            ar_full.append(a)
            if flip:
                ar_full.append(1.0 / a)
        n_priors = len(ar_full) + (1 if max_sizes else 0)
        loc = conv2d(feat, n_priors * 4, kernel_size, padding=pad,
                     stride=stride, name=f"{name or 'mbox'}_loc{i}")
        conf = conv2d(feat, n_priors * num_classes, kernel_size, padding=pad,
                      stride=stride, name=f"{name or 'mbox'}_conf{i}")
        fh, fw = int(feat.shape[2]), int(feat.shape[3])
        # prior boxes for this map
        sw = steps[i] if steps else img_w / fw
        sh = steps[i] if steps else img_h / fh
        boxes = []
        for y in range(fh):
            for x_ in range(fw):
                cx = (x_ + offset) * sw
                cy = (y + offset) * sh
                sizes = []
                ms = min_sizes[i]
                for a in ar_full:
                    sizes.append((ms * np.sqrt(a), ms / np.sqrt(a)))
                if max_sizes:
                    bigger = np.sqrt(ms * max_sizes[i])
                    sizes.append((bigger, bigger))
                for bw, bh in sizes:
                    box = [
                        (cx - bw / 2) / img_w, (cy - bh / 2) / img_h,
                        (cx + bw / 2) / img_w, (cy + bh / 2) / img_h,
                    ]
                    if clip:
                        box = [min(max(v, 0.0), 1.0) for v in box]
                    boxes.append(box)
        priors.append(np.asarray(boxes, np.float32))
        pvars.append(np.tile(np.asarray(variance, np.float32),
                             (len(boxes), 1)))
        b = int(feat.shape[0])
        locs.append(loc.transpose([0, 2, 3, 1]).reshape([b, -1, 4]))
        confs.append(
            conf.transpose([0, 2, 3, 1]).reshape([b, -1, num_classes])
        )
    mbox_locs = paddle.concat(locs, axis=1)
    mbox_confs = paddle.concat(confs, axis=1)
    box = paddle.to_tensor(np.concatenate(priors, 0))
    var = paddle.to_tensor(np.concatenate(pvars, 0))
    return mbox_locs, mbox_confs, box, var


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    from . import py_func as _pf

    return _pf(func, x, out, backward_func, skip_vars_in_backward_input)


# --- control flow (reference: static/nn/control_flow.py; lowered to python
# callables — the traced program inlines the taken structure, and
# paddle.jit uses lax control flow where tensors decide) -------------------
def cond(pred, true_fn=None, false_fn=None, name=None):
    import jax

    pv = pred
    if hasattr(pv, "_value"):
        pv = pv._value
    try:
        taken = bool(pv)
    except jax.errors.TracerBoolConversionError:
        raise NotImplementedError(
            "static.nn.cond with a traced predicate: write the branch with "
            "paddle.where / lax.cond inside a to_static function instead"
        )
    if taken:
        return true_fn() if true_fn else None
    return false_fn() if false_fn else None


def case(pred_fn_pairs, default=None, name=None):
    for pred, fn in pred_fn_pairs:
        pv = pred._value if hasattr(pred, "_value") else pred
        if bool(pv):
            return fn()
    if default is not None:
        return default()
    return pred_fn_pairs[-1][1]()


def switch_case(branch_index, branch_fns, default=None, name=None):
    idx = int(branch_index) if not isinstance(branch_index, int) else branch_index
    fns = dict(branch_fns) if not isinstance(branch_fns, dict) else branch_fns
    if idx in fns:
        return fns[idx]()
    if default is not None:
        return default()
    return fns[max(fns)]()


def while_loop(cond, body, loop_vars, is_test=False, name=None):
    """reference: static/nn/control_flow.py while_loop. Runs the python
    loop eagerly; under jit use paddle.jit with lax.while_loop."""
    vars_ = list(loop_vars)
    while bool(cond(*vars_)):
        out = body(*vars_)
        vars_ = list(out) if isinstance(out, (list, tuple)) else [out]
    return vars_


# --- sequence ops over padded [B, T, ...] batches -------------------------
def _lens_or_full(x, length):
    if length is None:
        return np.full(int(x.shape[0]), int(x.shape[1]), np.int64)
    return np.asarray(length.numpy() if hasattr(length, "numpy") else length)


def sequence_pool(input, pool_type, is_test=False, pad_value=0.0,
                  length=None):
    """max/avg/sum/sqrt/first/last pooling over the time axis."""
    import jax.numpy as jnp

    from ..core.dispatch import apply
    from ..core.tensor import to_tensor

    lens = to_tensor(_lens_or_full(input, length))
    pt = pool_type.lower()

    def _pool(v, ln):
        t = v.shape[1]
        mask = jnp.arange(t)[None, :] < ln[:, None]
        for _ in range(v.ndim - 2):
            mask = mask[..., None]
        if pt == "max":
            return jnp.where(mask, v, -jnp.inf).max(axis=1)
        if pt == "first":
            return v[:, 0]
        if pt == "last":
            return jnp.take_along_axis(
                v, (ln - 1).reshape(-1, *([1] * (v.ndim - 1))), axis=1
            )[:, 0]
        s = jnp.where(mask, v, 0.0).sum(axis=1)
        if pt == "sum":
            return s
        denom = jnp.maximum(ln, 1).astype(v.dtype)
        denom = denom.reshape(-1, *([1] * (s.ndim - 1)))
        if pt == "average":
            return s / denom
        if pt == "sqrt":
            return s / jnp.sqrt(denom)
        raise ValueError(pool_type)

    return apply(_pool, input, lens, op_name=f"sequence_pool_{pt}")


def sequence_softmax(input, use_cudnn=False, name=None, length=None):
    import jax.numpy as jnp

    from ..core.dispatch import apply
    from ..core.tensor import to_tensor

    lens = to_tensor(_lens_or_full(input, length))

    def _sm(v, ln):
        mask = jnp.arange(v.shape[1])[None, :] < ln[:, None]
        mask = mask.reshape(mask.shape + (1,) * (v.ndim - 2))
        masked = jnp.where(mask, v, -jnp.inf)
        out = jax.nn.softmax(masked, axis=1)
        return jnp.where(mask, out, 0.0)

    import jax

    return apply(_sm, input, lens, op_name="sequence_softmax")


def sequence_reverse(x, name=None, length=None):
    import jax.numpy as jnp

    from ..core.dispatch import apply
    from ..core.tensor import to_tensor

    lens = to_tensor(_lens_or_full(x, length))

    def _rev(v, ln):
        t = v.shape[1]
        idx = jnp.arange(t)[None, :]
        src = jnp.where(idx < ln[:, None], ln[:, None] - 1 - idx, idx)
        return jnp.take_along_axis(
            v, src.reshape(src.shape + (1,) * (v.ndim - 2)), axis=1
        )

    return apply(_rev, x, lens, op_name="sequence_reverse")


def sequence_first_step(input, length=None):
    return sequence_pool(input, "first", length=length)


def sequence_last_step(input, length=None):
    return sequence_pool(input, "last", length=length)


def sequence_concat(input, name=None):
    return paddle.concat(list(input), axis=1)


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=True, padding_start=None, bias_attr=None,
                  param_attr=None, act=None, name=None):
    """Context-window conv over time (reference: sequence_conv op) =
    conv1d over the padded batch."""
    d = int(input.shape[-1])
    layer = _layer_cache(
        (name or "sequence_conv", d, num_filters, filter_size),
        lambda: paddle.nn.Conv1D(d, num_filters, filter_size,
                                 padding=(filter_size - 1) // 2 if padding else 0,
                                 weight_attr=param_attr, bias_attr=bias_attr),
    named=False)
    out = layer(input.transpose([0, 2, 1])).transpose([0, 2, 1])
    return getattr(paddle.nn.functional, act)(out) if act else out


def sequence_enumerate(input, win_size, pad_value=0, name=None):
    """Sliding windows of ids (reference: sequence_enumerate op)."""
    import jax.numpy as jnp

    from ..core.dispatch import apply

    def _enum(v):
        t = v.shape[1]
        outs = []
        for off in range(win_size):
            idx = jnp.arange(t) + off
            col = jnp.where(idx < t, v[:, jnp.minimum(idx, t - 1)], pad_value)
            outs.append(col)
        return jnp.stack(outs, axis=-1)

    return apply(_enum, input, differentiable=False,
                 op_name="sequence_enumerate")


def sequence_expand(x, y, ref_level=-1, name=None):
    """Repeat each row of x per the batch of y (padded-batch semantics:
    tile x's batch to y's)."""
    reps = int(y.shape[0]) // max(int(x.shape[0]), 1)
    return paddle.concat([x] * reps, axis=0) if reps > 1 else x


def sequence_expand_as(x, y, name=None):
    return sequence_expand(x, y)


def sequence_pad(x, pad_value, maxlen=None, name=None):
    """x already padded in this design; returns (x, lengths)."""
    lens = paddle.to_tensor(_lens_or_full(x, None))
    return x, lens


def sequence_unpad(x, length, name=None):
    """Mask out the padding tail (stays padded-rectangular: XLA needs
    static shapes; consumers read `length`)."""
    import jax.numpy as jnp

    from ..core.dispatch import apply
    from ..core.tensor import to_tensor

    lens = to_tensor(_lens_or_full(x, length))

    def _mask(v, ln):
        m = jnp.arange(v.shape[1])[None, :] < ln[:, None]
        return v * m.reshape(m.shape + (1,) * (v.ndim - 2)).astype(v.dtype)

    return apply(_mask, x, lens, op_name="sequence_unpad")


def sequence_reshape(input, new_dim):
    b = int(input.shape[0])
    return input.reshape([b, -1, new_dim])


def sequence_slice(input, offset, length, name=None):
    """Per-sequence slice (same offset/length per row under padded
    batches)."""
    off = int(np.asarray(offset.numpy() if hasattr(offset, "numpy") else offset).reshape(-1)[0])
    ln = int(np.asarray(length.numpy() if hasattr(length, "numpy") else length).reshape(-1)[0])
    return input[:, off : off + ln]


def sequence_scatter(input, index, updates, name=None):
    """Scatter updates into per-row time positions (reference:
    sequence_scatter op)."""
    import jax.numpy as jnp

    from ..core.dispatch import apply

    def _scatter(v, idx, upd):
        rows = jnp.arange(v.shape[0])[:, None]
        return v.at[rows, idx].add(upd)

    return apply(_scatter, input, index, updates, op_name="sequence_scatter")
