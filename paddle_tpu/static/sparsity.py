"""paddle.static.sparsity — 2:4 structured-sparsity (ASP) static API.

Reference analogue: python/paddle/fluid/contrib/sparsity/asp.py exposed as
paddle.static.sparsity. Delegates to the working ASP implementation in
paddle_tpu.incubate.asp.
"""
from __future__ import annotations

import numpy as np

from ..incubate import asp as _asp

__all__ = [
    "calculate_density",
    "decorate",
    "prune_model",
    "reset_excluded_layers",
    "set_excluded_layers",
]

_excluded = set()


def calculate_density(x):
    """Fraction of nonzero entries (reference: sparsity/utils.py
    calculate_density)."""
    arr = np.asarray(x.numpy() if hasattr(x, "numpy") else x)
    return float((arr != 0).sum() / max(arr.size, 1))


def decorate(optimizer):
    """Wrap an optimizer so steps preserve pruned masks (reference:
    sparsity/asp.py decorate)."""
    return _asp.decorate(optimizer)


def prune_model(model, n=2, m=4, mask_algo="mask_1d", with_mask=True):
    """Prune a model's weights to n:m sparsity (reference: asp.prune_model).
    Layers named via set_excluded_layers are skipped."""
    return _asp.prune_model(model, n=n, m=m, mask_algo=mask_algo,
                            with_mask=with_mask, excluded=_excluded)


def set_excluded_layers(main_program=None, param_names=None):
    global _excluded
    _excluded |= set(param_names or [])


def reset_excluded_layers(main_program=None):
    global _excluded
    _excluded = set()
