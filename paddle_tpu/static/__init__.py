"""paddle.static — static-graph compatibility facade.

Reference analogue: python/paddle/static/ + fluid/framework.py (Program/
Block/Variable classes), fluid/executor.py:1103 (Executor.run with
feed/fetch), fluid/compiler.py (CompiledProgram).

TPU-native design: the reference's proto Program + InterpreterCore pipeline
is replaced by traced-and-compiled Python callables — a `Program` here is a
recorded Python function plus its compiled XLA executables (cached by feed
shapes). `Executor.run(prog, feed=..., fetch_list=...)` keeps the exact user
contract; under the hood it is one donated-buffer jit call, which IS the
standalone-executor role (scheduling/streams/GC all belong to XLA).

Round-1 scope: program capture via `build_program(fn)` / `program_guard` on
callables, Executor feed/fetch, save/load_inference_model via StableHLO.
The full op-by-op ProgramDesc emulation (append_op etc.) is intentionally
not replicated — dy2static covers the same user intent on TPU.
"""
from __future__ import annotations

import contextlib
import pickle
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core import _static_mode
from ..nn.param_attr import ParamAttr as _ParamAttr
from ..core.dispatch import no_grad
from ..core.tensor import Tensor
from ..jit import InputSpec  # noqa: F401

__all__ = [
    "enable_static",
    "disable_static",
    "in_static_mode",
    "Program",
    "program_guard",
    "default_main_program",
    "default_startup_program",
    "data",
    "Executor",
    "CompiledProgram",
    "InputSpec",
    "save_inference_model",
    "load_inference_model",
    "gradients",
    "append_backward",
    "name_scope",
]


def enable_static():
    _static_mode.enable()


def disable_static():
    _static_mode.disable()


def in_static_mode():
    return _static_mode.enabled()


class Variable:
    """Symbolic placeholder created by static.data (feed target)."""

    def __init__(self, name, shape, dtype):
        self.name = name
        self.shape = list(shape)
        self.dtype = dtype
        self.persistable = False

    def __repr__(self):
        return f"var {self.name} : {self.dtype}{self.shape}"


class Program:
    """A build-once/run-many training or inference graph.

    The reference Program is a proto of blocks+ops (framework.proto:236);
    here it carries: the feed variables declared while this program was
    default, a builder callable registered via `set_builder` (or captured
    through dy2static), and fetch targets."""

    def __init__(self):
        self.feed_vars: Dict[str, Variable] = {}
        self.builder: Optional[Callable] = None
        self.random_seed = 0
        self._compiled_cache: Dict = {}

    def set_builder(self, fn: Callable):
        """Register the callable(feed_dict)->fetches that defines this program.

        Each invocation resets the unnamed-layer call sequence so static.nn
        layer fns resolve to the SAME parameters every run (build-once)."""

        def wrapped(feed):
            self._call_seq = {}
            return fn(feed)

        wrapped.__wrapped__ = fn
        self.builder = wrapped
        return self

    def global_block(self):
        return self

    def all_parameters(self):
        """Parameters created by static.nn layer fns under this program
        (reference: Program.all_parameters over persistable vars)."""
        def slug(key):
            # full call-site key -> stable, collision-free checkpoint name
            return "_".join(
                str(k).replace(" ", "") for k in key
            ).replace("#call_", "c")

        out = []
        for key, obj in getattr(self, "_static_layers", {}).items():
            layers = obj if isinstance(obj, (list, tuple)) else [obj]
            base = slug(key)
            for li, layer in enumerate(layers):
                if hasattr(layer, "named_parameters"):
                    for pname, p in layer.named_parameters():
                        # derived from the FULL key (auto param_N names vary
                        # per process; key[0] alone can collide)
                        p.name = f"{base}_{li}.{pname}"
                        out.append(p)
                elif hasattr(layer, "_value"):  # bare Parameter
                    layer.name = f"{base}_{li}"
                    out.append(layer)
                elif isinstance(layer, dict):  # state dicts (data_norm)
                    for k, v in layer.items():
                        if hasattr(v, "_value"):
                            v.name = f"{base}.{k}"
                            out.append(v)
        return out

    def _iter_layers(self):
        """Every layer/parameter object held by static.nn layer caches."""
        for obj in getattr(self, "_static_layers", {}).values():
            items = obj if isinstance(obj, (list, tuple)) else [obj]
            for it in items:
                if isinstance(it, dict):
                    for v in it.values():
                        yield v
                else:
                    yield it

    def clone(self, for_test=False):
        """reference: framework.py Program.clone — the clone shares the
        source's variables (persistables live in one scope), so here it
        shares `_static_layers`/warm state: `all_parameters()` on the clone
        returns the SOURCE's parameters, and training the source updates
        the clone's weights. `for_test=True` marks the clone eval-mode:
        its builder runs with every cached layer switched to eval
        (dropout off, batch-norm running stats) and restored after."""
        p = Program()
        p.feed_vars = dict(self.feed_vars)
        p.random_seed = self.random_seed
        # materialize the layer cache NOW even if the source never ran:
        # a clone taken before the first run must still share the dict the
        # source will fill later, or their parameters silently diverge
        if getattr(self, "_static_layers", None) is None:
            self._static_layers = {}
        p._static_layers = self._static_layers
        p._warmed = getattr(self, "_warmed", False)
        p._for_test = bool(for_test)
        src = self.builder
        if src is None:
            return p
        inner = getattr(src, "__wrapped__", src)

        def cloned(feed):
            # reset the CLONE's unnamed-layer call sequence (the source's
            # builder wrapper resets only the source program's)
            p._call_seq = {}
            if not p._for_test:
                return inner(feed)
            layers = [
                l for l in p._iter_layers()
                if hasattr(l, "eval") and hasattr(l, "training")
            ]
            prev = [l.training for l in layers]
            for l in layers:
                l.eval()
            try:
                return inner(feed)
            finally:
                for l, was_training in zip(layers, prev):
                    if was_training:
                        l.train()
                    else:
                        l.eval()

        cloned.__wrapped__ = inner
        p.builder = cloned
        return p

    def __repr__(self):
        return f"Program(feeds={list(self.feed_vars)}, builder={self.builder})"

    # -- op-level introspection (reference: Program.global_block().ops) ------
    @property
    def ops(self):
        """OpDesc-like views of the traced program's operations.

        The reference exposes mutable proto OpDescs; here the program IS
        the traced jaxpr, so this surface is read-only introspection (op
        type, input/output shapes+dtypes) — rewriting belongs to XLA and
        the layer-level pass frameworks (distributed/passes, quantization/
        passes). Requires feed shapes: every static.data var declared on
        this program. Traced once and cached per feed signature."""
        return [_OpDesc(eqn) for eqn in _flat_eqns(self._traced_jaxpr())]

    def _traced_jaxpr(self):
        from ..core.dispatch import no_grad
        from ..core.dtype import to_np_dtype
        from ..core.tensor import Tensor

        if self.builder is None:
            raise RuntimeError(
                "program has no builder; run layers under this program "
                "(or set_builder) before inspecting ops"
            )
        items = sorted(self.feed_vars.items())
        sig = tuple((n, tuple(v.shape), str(v.dtype)) for n, v in items)
        cached = self._compiled_cache.get(("jaxpr", sig))
        if cached is not None:
            return cached
        names = [n for n, _ in items]
        shapes = [
            tuple(max(int(d), 1) if d not in (None, -1) else 1
                  for d in v.shape)
            for _, v in items
        ]
        dtypes = [to_np_dtype(v.dtype) for _, v in items]

        # warm EAGERLY first, like Executor.run: static.nn parameters must
        # materialize outside any trace (params born under make_jaxpr would
        # be cached leaked tracers crashing later executions), and layer
        # caches must resolve against THIS program, not the current default
        if not getattr(self, "_warmed", False):
            self._warmed = True
            with program_guard(self), no_grad():
                self.builder({
                    n: Tensor(jnp.zeros(s, d), stop_gradient=True)
                    for n, s, d in zip(names, shapes, dtypes)
                })

        def fn(*vals):
            feed = {
                n: Tensor(v, stop_gradient=True)
                for n, v in zip(names, vals)
            }
            with program_guard(self), no_grad():
                out = self.builder(feed)
            outs = out if isinstance(out, (list, tuple)) else [out]
            return [o._value if hasattr(o, "_value") else o for o in outs]

        specs = [
            jax.ShapeDtypeStruct(s, d) for s, d in zip(shapes, dtypes)
        ]
        jaxpr = jax.make_jaxpr(fn)(*specs).jaxpr
        self._compiled_cache[("jaxpr", sig)] = jaxpr
        return jaxpr


def _flat_eqns(jaxpr):
    """Flatten call-like eqns (the per-op jit cache wraps every framework
    op in pjit) AND control-flow primitives (`scan`/`while`/`cond` branch
    jaxprs) so `ops` — and the paddle_tpu.analysis passes — list the REAL
    primitives, like the reference's flat op list, instead of an opaque
    control-flow node. The primitive -> sub-jaxpr dispatch is shared with
    the analysis inliner so the two can never disagree on the op list."""
    from ..analysis import _as_open, _sub_jaxprs

    out = []
    for eqn in jaxpr.eqns:
        _, subs = _sub_jaxprs(eqn)
        if subs:
            for sub in subs:
                out.extend(_flat_eqns(_as_open(sub)[0]))
        else:
            out.append(eqn)
    return out


class _OpDesc:
    """Read-only view of one traced operation (reference: proto OpDesc)."""

    def __init__(self, eqn):
        self._eqn = eqn

    @property
    def type(self) -> str:
        return self._eqn.primitive.name

    def input_shapes(self):
        return [tuple(getattr(v.aval, "shape", ())) for v in self._eqn.invars]

    def output_shapes(self):
        return [tuple(getattr(v.aval, "shape", ()))
                for v in self._eqn.outvars]

    def __repr__(self):
        return (f"op {self.type}: {self.input_shapes()} -> "
                f"{self.output_shapes()}")


_default_main = [Program()]
_default_startup = [Program()]


def default_main_program() -> Program:
    return _default_main[-1]


def default_startup_program() -> Program:
    return _default_startup[-1]


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    _default_main.append(main_program)
    if startup_program is not None:
        _default_startup.append(startup_program)
    try:
        yield
    finally:
        _default_main.pop()
        if startup_program is not None:
            _default_startup.pop()


@contextlib.contextmanager
def name_scope(prefix=None):
    yield


def data(name, shape, dtype="float32", lod_level=0) -> Variable:
    """reference: python/paddle/static/input.py data — declares a feed slot
    on the current default program."""
    v = Variable(name, shape, dtype)
    default_main_program().feed_vars[name] = v
    return v


class Executor:
    """reference: fluid/executor.py:1103 Executor.run — feed/fetch execution.

    run() compiles the program's builder once per feed-shape signature and
    executes the cached XLA program (the StandaloneExecutor path is the
    default and only path here)."""

    def __init__(self, place=None):
        self.place = place

    def run(
        self,
        program: Optional[Program] = None,
        feed: Optional[Dict[str, Any]] = None,
        fetch_list: Optional[List] = None,
        return_numpy: bool = True,
        **kwargs,
    ):
        program = program or default_main_program()
        feed = feed or {}
        if program.builder is None:
            raise RuntimeError(
                "Program has no builder. On paddle_tpu, build static programs "
                "with program.set_builder(fn) or use paddle.jit.to_static — "
                "op-by-op ProgramDesc construction is not replicated (see "
                "paddle_tpu.static docstring)."
            )
        names = sorted(feed.keys())
        vals = [jnp.asarray(np.asarray(feed[k])) for k in names]
        sig = tuple((k, v.shape, str(v.dtype)) for k, v in zip(names, vals))
        fn = program._compiled_cache.get(sig)
        if fn is None and not getattr(program, "_warmed", False):
            # FIRST run executes eagerly: static.nn layer parameters are
            # materialized outside any trace (params created inside jit
            # would be leaked tracers), and builder side effects (Print,
            # py_func, PS table updates) fire exactly once per run
            program._warmed = True
            with program_guard(program), no_grad():
                out = program.builder({
                    k: Tensor(v, stop_gradient=True)
                    for k, v in zip(names, vals)
                })
            outs = list(out) if isinstance(out, (list, tuple)) else [out]
            outs = [o._value if isinstance(o, Tensor) else o for o in outs]
            if return_numpy:
                outs = [np.asarray(jax.device_get(o)) for o in outs]
            return outs
        if fn is None:
            builder = program.builder

            # FLAGS_check_programs: verify the program once per feed
            # signature, before it is compiled (reference: the IR pass
            # verifiers that run ahead of executor program build)
            from ..core import flags as _flags

            if int(_flags.flag("check_programs")):
                from .. import analysis

                analysis.enforce(
                    analysis.check(
                        program,
                        feed_specs={
                            k: (v.shape, str(v.dtype))
                            for k, v in zip(names, vals)
                        },
                    ),
                    where="Executor.run",
                )

            def pure(*feed_vals):
                d = {k: Tensor(v, stop_gradient=True) for k, v in zip(names, feed_vals)}
                # guard THIS program as default while tracing: static.nn
                # layer caches must resolve against it, not whatever
                # program happens to be default at trace time
                with program_guard(program), no_grad():
                    out = builder(d)
                if isinstance(out, (list, tuple)):
                    return tuple(
                        o._value if isinstance(o, Tensor) else o for o in out
                    )
                return out._value if isinstance(out, Tensor) else out

            fn = jax.jit(pure)
            program._compiled_cache[sig] = fn
        # jit tracing (first call per feed signature) replays the builder
        # with tracers; a layer buffer the builder mutates (BN running
        # stats) would otherwise keep a leaked tracer that crashes any
        # later eager read — e.g. running a clone(for_test=True) program.
        # Compiled execution is pure (host-side buffer updates only happen
        # on the eager warm run), so restoring the snapshot is exact.
        buf_state = []
        for layer in program._iter_layers():
            if hasattr(layer, "named_buffers"):
                buf_state.extend((b, b._value) for _, b in layer.named_buffers())
        out = fn(*vals)
        for t, v in buf_state:
            t._value = v
        outs = list(out) if isinstance(out, tuple) else [out]
        if return_numpy:
            outs = [np.asarray(jax.device_get(o)) for o in outs]
        return outs

    def close(self):
        pass


class CompiledProgram:
    """reference: fluid/compiler.py CompiledProgram — everything is compiled
    here, so this is a pass-through wrapper kept for API parity."""

    def __init__(self, program, build_strategy=None):
        self._program = program

    def __getattr__(self, item):
        return getattr(self._program, item)


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor, program=None, **kwargs):
    """Export a builder Program as a StableHLO inference artifact.

    reference: python/paddle/static/io.py save_inference_model (prunes the
    program to feed→fetch and serializes __model__ + params). Here the
    builder is traced to one XLA program with the feed vars as (batch-
    symbolic where the declared dim is None/-1) inputs; weights the builder
    closes over are baked into the artifact as constants — the reference's
    params-in-__model__ combined form.
    """
    from ..framework.artifact import export_artifact

    program = program or default_main_program()
    if program.builder is None:
        raise RuntimeError("save_inference_model requires a Program with a builder")
    feed_vars = [feed_vars] if isinstance(feed_vars, Variable) else list(feed_vars)
    fetch_vars = (
        [fetch_vars] if not isinstance(fetch_vars, (list, tuple)) else list(fetch_vars)
    )
    names = [v.name for v in feed_vars]
    builder = program.builder

    def pure(*feed_vals):
        d = {k: Tensor(v, stop_gradient=True) for k, v in zip(names, feed_vals)}
        with no_grad():
            out = builder(d)
        if isinstance(out, (list, tuple)):
            out = tuple(o._value if isinstance(o, Tensor) else o for o in out)
        else:
            out = (out._value if isinstance(out, Tensor) else out,)
        if len(out) != len(fetch_vars):
            raise ValueError(
                f"builder produced {len(out)} outputs but fetch_vars names "
                f"{len(fetch_vars)}; the builder must return exactly the "
                "fetch targets (prune inside the builder)"
            )
        return out

    output_names = [
        getattr(v, "name", None) or f"output_{i}" for i, v in enumerate(fetch_vars)
    ]
    export_artifact(
        pure,
        path_prefix,
        input_names=names,
        input_shapes=[list(v.shape) for v in feed_vars],
        input_dtypes=[v.dtype for v in feed_vars],
        state=[],
        output_names=output_names,
    )


def load_inference_model(path_prefix, executor, **kwargs):
    """Load a StableHLO inference artifact into a runnable Program.

    Returns [program, feed_target_names, fetch_target_names] exactly like the
    reference (static/io.py load_inference_model); run it with
    Executor.run(program, feed={...}, fetch_list=fetch_targets).
    """
    from ..framework.artifact import load_artifact

    exp, state, meta = load_artifact(path_prefix)
    in_names = list(meta["input_names"])
    out_names = list(meta["output_names"])
    call = jax.jit(exp.call)

    def builder(feed: Dict[str, Tensor]):
        vals = [feed[k]._value for k in in_names]
        out = call(*state, *vals)
        outs = list(out) if isinstance(out, (tuple, list)) else [out]
        return [Tensor(o, stop_gradient=True) for o in outs]

    program = Program()
    program.set_builder(builder)
    for n, sh, dt in zip(in_names, meta.get("input_shapes", []), meta.get("input_dtypes", [])):
        program.feed_vars[n] = Variable(n, sh or [], dt)
    return [program, in_names, out_names]


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    from ..autograd import grad as _grad

    return _grad(targets, inputs, target_gradients, retain_graph=True, allow_unused=True)


def append_backward(loss, parameter_list=None, no_grad_set=None, callbacks=None):
    """reference: fluid/backward.py:1420 — in eager-first paddle_tpu this is
    loss.backward(); kept for script parity."""
    loss.backward(retain_graph=True)
    return []


# nn sub-namespace for static layers parity (maps to dygraph layers)
import types as _types  # noqa: E402

from .. import nn as _nn  # noqa: E402


_sparse_layers = {}


def _sparse_embedding(input, size, param_attr=None, is_test=False,
                      padding_idx=None, name=None, **kwargs):
    """reference: paddle.static.nn.sparse_embedding — the PS-backed lookup
    (distributed_lookup_table). The host C++ MemorySparseTable owns the rows
    (distributed/ps).

    Table identity: the reference keys the persistent table by the op's
    parameter name; here `name` (or param_attr's name) is REQUIRED so
    repeated calls hit the SAME table — an anonymous call would silently
    train a fresh throwaway table per step. The lookup runs eagerly (host
    table); compile only the dense tail (see distributed/ps docstring).
    """
    from ..distributed.ps import SparseEmbedding

    key = name or getattr(param_attr, "name", None)
    if not key:
        raise ValueError(
            "sparse_embedding needs a stable identity: pass name=... (or a "
            "param_attr with a name) so every call reuses one persistent "
            "table — otherwise each call would train a fresh table"
        )
    layer = _sparse_layers.get(key)
    if layer is None:
        layer = SparseEmbedding(size, padding_idx=padding_idx, **kwargs)
        _sparse_layers[key] = layer
    if is_test:
        layer.eval()
    else:
        layer.train()
    return layer(input)


# static.nn is a real submodule (fc/conv2d/sequence_* function forms); it
# additionally carries the paddle.nn layer classes (reference static.nn
# re-exports those too) and the PS sparse_embedding entry point.
from . import nn  # noqa: E402

nn.sparse_embedding = _sparse_embedding
for _k in dir(_nn):
    if not _k.startswith("_") and not hasattr(nn, _k):
        setattr(nn, _k, getattr(_nn, _k))
del _k


# ---------------------------------------------------------------------------
# surface completion (reference: python/paddle/static/__init__.py __all__)
# ---------------------------------------------------------------------------

class BuildStrategy:
    """reference: framework/details/build_strategy.h BuildStrategy — graph
    executor knobs. XLA owns fusion/scheduling here, so the fields are
    recorded config (several map onto real jit choices in CompiledProgram)."""

    def __init__(self):
        self.debug_graphviz_path = ""
        self.enable_sequential_execution = False
        self.fuse_broadcast_ops = False
        self.fuse_elewise_add_act_ops = False
        self.fuse_bn_act_ops = False
        self.fuse_relu_depthwise_conv = False
        self.memory_optimize = None
        self.reduce_strategy = "AllReduce"
        self.remove_unnecessary_lock = True
        self.sync_batch_norm = False
        self.enable_inplace = True
        self.build_cinn_pass = False


class ExecutionStrategy:
    """reference: details/execution_strategy.h knobs."""

    def __init__(self):
        self.num_threads = 1
        self.num_iteration_per_drop_scope = 100
        self.num_iteration_per_run = 1
        self.use_thread_barrier = False


class ParallelExecutor:
    """reference: framework/parallel_executor.h:51 — multi-device graph
    executor. Compiled XLA programs are already multi-device via GSPMD, so
    this wraps Executor for API parity."""

    def __init__(self, use_cuda=False, loss_name=None, main_program=None,
                 share_vars_from=None, exec_strategy=None, build_strategy=None,
                 num_trainers=1, trainer_id=0, scope=None):
        self._program = main_program or default_main_program()
        self._exe = Executor()

    def run(self, fetch_list=None, feed=None, feed_dict=None,
            return_numpy=True):
        return self._exe.run(self._program, feed=feed or feed_dict,
                             fetch_list=fetch_list, return_numpy=return_numpy)


class IpuStrategy:
    """Vendor shim (reference: IPU graph compiler options)."""

    def __init__(self):
        self._options = {}

    def set_graph_config(self, **kwargs):
        self._options.update(kwargs)

    def set_pipelining_config(self, **kwargs):
        self._options.update(kwargs)

    def set_precision_config(self, **kwargs):
        self._options.update(kwargs)


class IpuCompiledProgram:
    """Vendor shim — on this stack every program is XLA-compiled, so this
    returns the program unchanged (reference compiles for IPU here)."""

    def __init__(self, program=None, scope=None, ipu_strategy=None):
        self._program = program or default_main_program()

    def compile(self, feed_list=None, fetch_list=None):
        return self._program


@contextlib.contextmanager
def device_guard(device=None):
    """reference: static/device_guard — pins ops to a device; XLA places
    the whole program, so this is a recorded no-op context."""
    yield


@contextlib.contextmanager
def ipu_shard_guard(index=-1, stage=-1):
    yield


class _ScopeVar:
    """Mutable slot returned by Scope.var (reference: framework/variable.h) —
    get_tensor()/set() so ported scope-poking code works."""

    def __init__(self, name):
        self.name = name
        self._value = None

    def get_tensor(self):
        return self._value

    def set(self, value, place=None):
        from ..core.tensor import Tensor, to_tensor

        self._value = value if isinstance(value, Tensor) else to_tensor(value)
        return self._value

    def set_value(self, value):
        if self._value is not None and hasattr(self._value, "set_value"):
            self._value.set_value(value)
        else:
            self.set(value)


class Scope:
    """Name -> variable holder (reference: framework/scope.h:78)."""

    def __init__(self):
        self._vars: Dict[str, Any] = {}

    def var(self, name):
        """Find-or-create (reference Scope::Var creates an empty Variable)."""
        if name not in self._vars or self._vars[name] is None:
            self._vars[name] = _ScopeVar(name)
        return self._vars[name]

    def find_var(self, name):
        return self._vars.get(name)

    def set_var(self, name, value):
        self._vars[name] = value

    def local_scope(self):
        return Scope()


_global_scope = [Scope()]


def global_scope() -> Scope:
    return _global_scope[-1]


@contextlib.contextmanager
def scope_guard(scope):
    _global_scope.append(scope)
    try:
        yield
    finally:
        _global_scope.pop()


def create_global_var(shape, value, dtype, persistable=False, force_cpu=False,
                      name=None):
    """Persistable scope variable (reference: layers/tensor.py
    create_global_var)."""
    from ..core.tensor import to_tensor

    t = to_tensor(np.full(tuple(shape), value, _np_dtype(dtype)))
    t.persistable = persistable
    nm = name or f"global_var_{len(global_scope()._vars)}"
    t.name = nm
    global_scope().set_var(nm, t)
    return t


def _np_dtype(dtype):
    from ..core.dtype import to_np_dtype

    return to_np_dtype(dtype)


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    import paddle_tpu as paddle

    p = paddle.create_parameter(shape, dtype, name=name, attr=attr,
                                is_bias=is_bias,
                                default_initializer=default_initializer)
    if name:
        global_scope().set_var(name, p)
    return p


class WeightNormParamAttr(_ParamAttr):
    """reference: fluid/param_attr.py WeightNormParamAttr — ParamAttr with a
    weight-norm dim."""

    def __init__(self, dim=None, name=None, initializer=None,
                 learning_rate=1.0, regularizer=None, trainable=True,
                 do_model_average=False, need_clip=True):
        super().__init__(name=name, initializer=initializer,
                         learning_rate=learning_rate, regularizer=regularizer,
                         trainable=trainable)
        self.dim = dim


def Print(input, first_n=-1, message=None, summarize=20, print_tensor_name=True,
          print_tensor_type=True, print_tensor_shape=True,
          print_tensor_layout=True, print_tensor_lod=True,
          print_phase="both"):
    """Debug print that passes the value through (reference:
    layers/control_flow.py Print op)."""
    vals = input.numpy() if hasattr(input, "numpy") else np.asarray(input)
    header = message or ""
    name = getattr(input, "name", "var")
    parts = [header]
    if print_tensor_name:
        parts.append(f"Tensor[{name}]")
    if print_tensor_shape:
        parts.append(f"shape: {tuple(vals.shape)}")
    if print_tensor_type:
        parts.append(f"dtype: {vals.dtype}")
    flat = vals.reshape(-1)
    if summarize is not None and summarize >= 0:
        flat = flat[:summarize]
    parts.append(f"data: {flat}")
    print("  ".join(str(p) for p in parts))
    return input


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Run a user python function as an op (reference:
    layers/nn.py py_func over the py_func op). Eager call here — the jit
    path would need jax.pure_callback, which custom_op.register_op provides."""
    xs = x if isinstance(x, (list, tuple)) else [x]
    out_v = func(*xs)
    return out_v


def accuracy(input, label, k=1, correct=None, total=None):
    """Top-k accuracy op (reference: layers/metric_op.py accuracy)."""
    from ..core.dispatch import apply

    def _acc(logits, lab, *, k):
        import jax.numpy as jnp

        topk = jnp.argsort(-logits, axis=-1)[..., :k]
        lab2 = lab.reshape(-1, 1)
        hit = (topk == lab2).any(-1)
        return hit.mean(dtype=logits.dtype)

    return apply(_acc, input, label, k=int(k), differentiable=False,
                 op_name="accuracy")


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1):
    """Streaming-free AUC op over the batch (reference:
    layers/metric_op.py auc; the stateful streaming form lives in
    paddle.metric.Auc)."""
    from ..core.dispatch import apply

    def _auc(probs, lab, *, bins):
        import jax.numpy as jnp

        pos_p = probs[:, 1] if probs.ndim == 2 else probs.reshape(-1)
        lab = lab.reshape(-1)
        ths = jnp.linspace(0.0, 1.0, bins)
        pred_pos = pos_p[None, :] >= ths[:, None]
        tp = jnp.sum(pred_pos & (lab == 1)[None, :], -1).astype(jnp.float64)
        fp = jnp.sum(pred_pos & (lab == 0)[None, :], -1).astype(jnp.float64)
        P = jnp.maximum(jnp.sum(lab == 1), 1)
        N = jnp.maximum(jnp.sum(lab == 0), 1)
        tpr = tp / P
        fpr = fp / N
        # trapezoid over decreasing threshold
        return -jnp.trapezoid(tpr, fpr).astype(jnp.float32)

    return apply(_auc, input, label, bins=int(num_thresholds),
                 differentiable=False, op_name="auc")


def cpu_places(device_count=None):
    from ..core.place import CPUPlace

    n = device_count or 1
    return [CPUPlace(i) for i in range(n)]


def cuda_places(device_ids=None):
    """Accelerator places (reference returns CUDAPlaces; here the session
    accelerator)."""
    import jax

    from ..core.place import CUDAPlace

    ids = device_ids if device_ids is not None else range(len(jax.devices()))
    return [CUDAPlace(i) for i in ids]


def xpu_places(device_ids=None):
    return cuda_places(device_ids)


def npu_places(device_ids=None):
    return cuda_places(device_ids)


def mlu_places(device_ids=None):
    return cuda_places(device_ids)


class ExponentialMovingAverage:
    """EMA of trainable parameters (reference: fluid/optimizer.py
    ExponentialMovingAverage: update() after each step; apply()/restore()
    swap the shadow weights in and out)."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = decay
        self._thres_steps = thres_steps
        self._params = None
        self._shadow = {}
        self._backup = {}
        self._step = 0

    def _collect(self):
        if self._params is None:
            raise RuntimeError(
                "call ema.register(parameters) once before update() "
                "(program-rewrite registration has no meaning without a "
                "proto graph)"
            )
        return self._params

    def register(self, parameters):
        self._params = list(parameters)
        for i, p in enumerate(self._params):
            self._shadow[i] = np.asarray(p.numpy())
        return self

    def update(self):
        self._step += 1
        if self._thres_steps is not None:
            # reference ramp applies only when thres_steps is given
            d = min(self._decay, (1 + self._step) / (10 + self._step))
        else:
            d = self._decay
        for i, p in enumerate(self._collect()):
            self._shadow[i] = d * self._shadow[i] + (1 - d) * np.asarray(p.numpy())

    @contextlib.contextmanager
    def apply(self, executor=None, need_restore=True):
        params = self._collect()
        for i, p in enumerate(params):
            self._backup[i] = np.asarray(p.numpy())
            p.set_value(self._shadow[i])
        try:
            yield
        finally:
            if need_restore:
                self.restore()

    def restore(self, executor=None):
        for i, p in enumerate(self._collect()):
            if i in self._backup:
                p.set_value(self._backup[i])
        self._backup = {}


# --- program/persistable (de)serialization -------------------------------
def _scope_state(scope=None):
    scope = scope or global_scope()
    out = {}
    for name, v in scope._vars.items():
        if hasattr(v, "numpy"):
            out[name] = np.asarray(v.numpy())
    return out


def save(program, model_path, protocol=4, **configs):
    """Save program persistables (reference: static/io.py save →
    .pdparams/.pdopt/.pdmodel triple; here one .pdparams payload of the
    scope/program state)."""
    state = _scope_state()
    for i, p in enumerate(program.all_parameters()):
        state[getattr(p, "name", None) or f"param_{i}"] = np.asarray(p.numpy())
    with open(model_path + ".pdparams", "wb") as f:
        pickle.dump(state, f, protocol=protocol)
    with open(model_path + ".pdmodel", "wb") as f:
        f.write(serialize_program(program.feed_vars.values(), []))


def load(program, model_path, executor=None, var_list=None):
    """reference: static/io.py load — restore persistables into the scope
    AND into the program's static.nn layer parameters."""
    with open(model_path + ".pdparams", "rb") as f:
        state = pickle.load(f)
    set_program_state(program, state)
    return state


def load_program_state(model_path, var_list=None):
    with open(model_path + ".pdparams", "rb") as f:
        return pickle.load(f)


def set_program_state(program, state_dict):
    scope = global_scope()
    prog_params = {
        getattr(p, "name", None): p for p in program.all_parameters()
    }
    for name, arr in state_dict.items():
        if name in prog_params:
            prog_params[name].set_value(arr)
            continue
        cur = scope.find_var(name)
        if cur is not None and hasattr(cur, "set_value"):
            cur.set_value(arr)
        else:
            from ..core.tensor import to_tensor

            scope.set_var(name, to_tensor(arr))


def serialize_program(feed_vars, fetch_vars, **kwargs):
    """Program metadata -> bytes (reference: static/io.py
    serialize_program → proto bytes; here a pickled spec)."""
    spec = {
        "feeds": [
            {"name": v.name, "shape": list(v.shape), "dtype": str(v.dtype)}
            for v in feed_vars
        ],
        "fetches": [getattr(v, "name", str(i)) for i, v in enumerate(fetch_vars)],
        "format": "paddle_tpu_program_v1",
    }
    return pickle.dumps(spec)


def deserialize_program(data):
    spec = pickle.loads(data)
    p = Program()
    for f in spec.get("feeds", []):
        p.feed_vars[f["name"]] = Variable(f["name"], f["shape"], f["dtype"])
    return p


def serialize_persistables(feed_vars, fetch_vars, executor=None, **kwargs):
    return pickle.dumps(_scope_state())


def deserialize_persistables(program, data, executor=None):
    set_program_state(program, pickle.loads(data))


def save_to_file(path, content):
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path):
    with open(path, "rb") as f:
        return f.read()


def normalize_program(program, feeds, fetches, **kwargs):
    """Prune to an inference program (reference: static/io.py
    normalize_program) — clone with the given feed set."""
    p = program.clone(for_test=True)
    for v in feeds:
        if isinstance(v, Variable):
            p.feed_vars[v.name] = v
    return p


from . import sparsity  # noqa: E402,F401

# paddle.static.analysis — graph verifier & lint passes over traced
# programs (reference: the fluid/framework/ir pass suite). The package
# lives at paddle_tpu.analysis; this alias is its public address, and the
# sys.modules entry makes `import paddle_tpu.static.analysis` (and the
# API.spec generator) resolve it like a real submodule.
import sys as _sys  # noqa: E402

from .. import analysis  # noqa: E402,F401

_sys.modules[__name__ + ".analysis"] = analysis
# the memory planner submodule needs its own alias: without it an import
# of paddle_tpu.static.analysis.memory would RE-EXECUTE memory.py under
# the static package name (and its relative imports would break)
_sys.modules[__name__ + ".analysis.memory"] = analysis.memory
_sys.modules[__name__ + ".analysis.sharding"] = analysis.sharding

__all__ += ["analysis"]

__all__ += [
    "BuildStrategy", "ExecutionStrategy", "ExponentialMovingAverage",
    "IpuCompiledProgram", "IpuStrategy", "ParallelExecutor", "Print",
    "WeightNormParamAttr", "accuracy", "auc", "cpu_places",
    "create_global_var", "create_parameter", "cuda_places",
    "deserialize_persistables", "deserialize_program", "device_guard",
    "global_scope", "ipu_shard_guard", "load", "load_from_file",
    "load_program_state", "mlu_places", "normalize_program", "npu_places",
    "py_func", "save", "save_to_file", "scope_guard",
    "serialize_persistables", "serialize_program", "set_program_state",
    "xpu_places", "nn", "sparsity",
]
