"""paddle.static — static-graph compatibility facade.

Reference analogue: python/paddle/static/ + fluid/framework.py (Program/
Block/Variable classes), fluid/executor.py:1103 (Executor.run with
feed/fetch), fluid/compiler.py (CompiledProgram).

TPU-native design: the reference's proto Program + InterpreterCore pipeline
is replaced by traced-and-compiled Python callables — a `Program` here is a
recorded Python function plus its compiled XLA executables (cached by feed
shapes). `Executor.run(prog, feed=..., fetch_list=...)` keeps the exact user
contract; under the hood it is one donated-buffer jit call, which IS the
standalone-executor role (scheduling/streams/GC all belong to XLA).

Round-1 scope: program capture via `build_program(fn)` / `program_guard` on
callables, Executor feed/fetch, save/load_inference_model via StableHLO.
The full op-by-op ProgramDesc emulation (append_op etc.) is intentionally
not replicated — dy2static covers the same user intent on TPU.
"""
from __future__ import annotations

import contextlib
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core import _static_mode
from ..core.dispatch import no_grad
from ..core.tensor import Tensor
from ..jit import InputSpec  # noqa: F401

__all__ = [
    "enable_static",
    "disable_static",
    "in_static_mode",
    "Program",
    "program_guard",
    "default_main_program",
    "default_startup_program",
    "data",
    "Executor",
    "CompiledProgram",
    "InputSpec",
    "save_inference_model",
    "load_inference_model",
    "gradients",
    "append_backward",
    "name_scope",
]


def enable_static():
    _static_mode.enable()


def disable_static():
    _static_mode.disable()


def in_static_mode():
    return _static_mode.enabled()


class Variable:
    """Symbolic placeholder created by static.data (feed target)."""

    def __init__(self, name, shape, dtype):
        self.name = name
        self.shape = list(shape)
        self.dtype = dtype
        self.persistable = False

    def __repr__(self):
        return f"var {self.name} : {self.dtype}{self.shape}"


class Program:
    """A build-once/run-many training or inference graph.

    The reference Program is a proto of blocks+ops (framework.proto:236);
    here it carries: the feed variables declared while this program was
    default, a builder callable registered via `set_builder` (or captured
    through dy2static), and fetch targets."""

    def __init__(self):
        self.feed_vars: Dict[str, Variable] = {}
        self.builder: Optional[Callable] = None
        self.random_seed = 0
        self._compiled_cache: Dict = {}

    def set_builder(self, fn: Callable):
        """Register the callable(feed_dict)->fetches that defines this program."""
        self.builder = fn
        return self

    def global_block(self):
        return self

    def all_parameters(self):
        return []

    def clone(self, for_test=False):
        p = Program()
        p.feed_vars = dict(self.feed_vars)
        p.builder = self.builder
        return p

    def __repr__(self):
        return f"Program(feeds={list(self.feed_vars)}, builder={self.builder})"


_default_main = [Program()]
_default_startup = [Program()]


def default_main_program() -> Program:
    return _default_main[-1]


def default_startup_program() -> Program:
    return _default_startup[-1]


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    _default_main.append(main_program)
    if startup_program is not None:
        _default_startup.append(startup_program)
    try:
        yield
    finally:
        _default_main.pop()
        if startup_program is not None:
            _default_startup.pop()


@contextlib.contextmanager
def name_scope(prefix=None):
    yield


def data(name, shape, dtype="float32", lod_level=0) -> Variable:
    """reference: python/paddle/static/input.py data — declares a feed slot
    on the current default program."""
    v = Variable(name, shape, dtype)
    default_main_program().feed_vars[name] = v
    return v


class Executor:
    """reference: fluid/executor.py:1103 Executor.run — feed/fetch execution.

    run() compiles the program's builder once per feed-shape signature and
    executes the cached XLA program (the StandaloneExecutor path is the
    default and only path here)."""

    def __init__(self, place=None):
        self.place = place

    def run(
        self,
        program: Optional[Program] = None,
        feed: Optional[Dict[str, Any]] = None,
        fetch_list: Optional[List] = None,
        return_numpy: bool = True,
        **kwargs,
    ):
        program = program or default_main_program()
        feed = feed or {}
        if program.builder is None:
            raise RuntimeError(
                "Program has no builder. On paddle_tpu, build static programs "
                "with program.set_builder(fn) or use paddle.jit.to_static — "
                "op-by-op ProgramDesc construction is not replicated (see "
                "paddle_tpu.static docstring)."
            )
        names = sorted(feed.keys())
        vals = [jnp.asarray(np.asarray(feed[k])) for k in names]
        sig = tuple((k, v.shape, str(v.dtype)) for k, v in zip(names, vals))
        fn = program._compiled_cache.get(sig)
        if fn is None:
            builder = program.builder

            def pure(*feed_vals):
                d = {k: Tensor(v, stop_gradient=True) for k, v in zip(names, feed_vals)}
                with no_grad():
                    out = builder(d)
                if isinstance(out, (list, tuple)):
                    return tuple(
                        o._value if isinstance(o, Tensor) else o for o in out
                    )
                return out._value if isinstance(out, Tensor) else out

            fn = jax.jit(pure)
            program._compiled_cache[sig] = fn
        out = fn(*vals)
        outs = list(out) if isinstance(out, tuple) else [out]
        if return_numpy:
            outs = [np.asarray(jax.device_get(o)) for o in outs]
        return outs

    def close(self):
        pass


class CompiledProgram:
    """reference: fluid/compiler.py CompiledProgram — everything is compiled
    here, so this is a pass-through wrapper kept for API parity."""

    def __init__(self, program, build_strategy=None):
        self._program = program

    def __getattr__(self, item):
        return getattr(self._program, item)


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor, program=None, **kwargs):
    """Export a builder Program as a StableHLO inference artifact.

    reference: python/paddle/static/io.py save_inference_model (prunes the
    program to feed→fetch and serializes __model__ + params). Here the
    builder is traced to one XLA program with the feed vars as (batch-
    symbolic where the declared dim is None/-1) inputs; weights the builder
    closes over are baked into the artifact as constants — the reference's
    params-in-__model__ combined form.
    """
    from ..framework.artifact import export_artifact

    program = program or default_main_program()
    if program.builder is None:
        raise RuntimeError("save_inference_model requires a Program with a builder")
    feed_vars = [feed_vars] if isinstance(feed_vars, Variable) else list(feed_vars)
    fetch_vars = (
        [fetch_vars] if not isinstance(fetch_vars, (list, tuple)) else list(fetch_vars)
    )
    names = [v.name for v in feed_vars]
    builder = program.builder

    def pure(*feed_vals):
        d = {k: Tensor(v, stop_gradient=True) for k, v in zip(names, feed_vals)}
        with no_grad():
            out = builder(d)
        if isinstance(out, (list, tuple)):
            out = tuple(o._value if isinstance(o, Tensor) else o for o in out)
        else:
            out = (out._value if isinstance(out, Tensor) else out,)
        if len(out) != len(fetch_vars):
            raise ValueError(
                f"builder produced {len(out)} outputs but fetch_vars names "
                f"{len(fetch_vars)}; the builder must return exactly the "
                "fetch targets (prune inside the builder)"
            )
        return out

    output_names = [
        getattr(v, "name", None) or f"output_{i}" for i, v in enumerate(fetch_vars)
    ]
    export_artifact(
        pure,
        path_prefix,
        input_names=names,
        input_shapes=[list(v.shape) for v in feed_vars],
        input_dtypes=[v.dtype for v in feed_vars],
        state=[],
        output_names=output_names,
    )


def load_inference_model(path_prefix, executor, **kwargs):
    """Load a StableHLO inference artifact into a runnable Program.

    Returns [program, feed_target_names, fetch_target_names] exactly like the
    reference (static/io.py load_inference_model); run it with
    Executor.run(program, feed={...}, fetch_list=fetch_targets).
    """
    from ..framework.artifact import load_artifact

    exp, state, meta = load_artifact(path_prefix)
    in_names = list(meta["input_names"])
    out_names = list(meta["output_names"])
    call = jax.jit(exp.call)

    def builder(feed: Dict[str, Tensor]):
        vals = [feed[k]._value for k in in_names]
        out = call(*state, *vals)
        outs = list(out) if isinstance(out, (tuple, list)) else [out]
        return [Tensor(o, stop_gradient=True) for o in outs]

    program = Program()
    program.set_builder(builder)
    for n, sh, dt in zip(in_names, meta.get("input_shapes", []), meta.get("input_dtypes", [])):
        program.feed_vars[n] = Variable(n, sh or [], dt)
    return [program, in_names, out_names]


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    from ..autograd import grad as _grad

    return _grad(targets, inputs, target_gradients, retain_graph=True, allow_unused=True)


def append_backward(loss, parameter_list=None, no_grad_set=None, callbacks=None):
    """reference: fluid/backward.py:1420 — in eager-first paddle_tpu this is
    loss.backward(); kept for script parity."""
    loss.backward(retain_graph=True)
    return []


# nn sub-namespace for static layers parity (maps to dygraph layers)
import types as _types  # noqa: E402

from .. import nn as _nn  # noqa: E402


_sparse_layers = {}


def _sparse_embedding(input, size, param_attr=None, is_test=False,
                      padding_idx=None, name=None, **kwargs):
    """reference: paddle.static.nn.sparse_embedding — the PS-backed lookup
    (distributed_lookup_table). The host C++ MemorySparseTable owns the rows
    (distributed/ps).

    Table identity: the reference keys the persistent table by the op's
    parameter name; here `name` (or param_attr's name) is REQUIRED so
    repeated calls hit the SAME table — an anonymous call would silently
    train a fresh throwaway table per step. The lookup runs eagerly (host
    table); compile only the dense tail (see distributed/ps docstring).
    """
    from ..distributed.ps import SparseEmbedding

    key = name or getattr(param_attr, "name", None)
    if not key:
        raise ValueError(
            "sparse_embedding needs a stable identity: pass name=... (or a "
            "param_attr with a name) so every call reuses one persistent "
            "table — otherwise each call would train a fresh table"
        )
    layer = _sparse_layers.get(key)
    if layer is None:
        layer = SparseEmbedding(size, padding_idx=padding_idx, **kwargs)
        _sparse_layers[key] = layer
    if is_test:
        layer.eval()
    else:
        layer.train()
    return layer(input)


nn = _types.SimpleNamespace(
    **{k: getattr(_nn, k) for k in dir(_nn) if not k.startswith("_")},
    sparse_embedding=_sparse_embedding,
)
