"""reference: python/paddle/dataset/wmt14.py — WMT14 en→fr translation
readers. train/test/gen(dict_size) yield (src_ids, trg_ids, trg_ids_next)
where src is wrapped in <s>…<e>, trg_ids is <s>-prefixed and trg_ids_next
is <e>-suffixed, and pairs longer than 80 tokens are dropped.
Synthetic-backed (zero-egress): deterministic sentence pairs with the
reference's exact tuple structure and special-token conventions.
"""
from __future__ import annotations

import numpy as np

__all__ = ["train", "test", "gen", "get_dict"]

START = "<s>"
END = "<e>"
UNK = "<unk>"
UNK_IDX = 2

# the reference's dicts reserve ids 0/1/2 for <s>/<e>/<unk>
_RESERVED = {START: 0, END: 1, UNK: 2}

_SRC_WORDS = [
    "the", "house", "is", "on", "a", "hill", "river", "runs", "through",
    "town", "market", "opens", "at", "dawn", "children", "play", "in",
    "park", "old", "bridge",
]
_TRG_WORDS = [
    "la", "maison", "est", "sur", "une", "colline", "riviere", "traverse",
    "ville", "le", "marche", "ouvre", "aube", "enfants", "jouent", "dans",
    "parc", "vieux", "pont", "grand",
]

_MAX_LEN = 80  # the reference drops train pairs longer than this


def _dict(words, dict_size):
    d = dict(_RESERVED)
    for w in words[: max(0, dict_size - len(_RESERVED))]:
        d[w] = len(d)
    return d


def _pairs(count, seed):
    rng = np.random.default_rng(seed)
    for _ in range(count):
        n_src = int(rng.integers(3, 10))
        n_trg = int(rng.integers(3, 10))
        src = [_SRC_WORDS[int(rng.integers(0, len(_SRC_WORDS)))] for _ in range(n_src)]
        trg = [_TRG_WORDS[int(rng.integers(0, len(_TRG_WORDS)))] for _ in range(n_trg)]
        yield src, trg


def reader_creator(dict_size, count, seed):
    def reader():
        src_dict = _dict(_SRC_WORDS, dict_size)
        trg_dict = _dict(_TRG_WORDS, dict_size)
        for src_words, trg_words in _pairs(count, seed):
            src_ids = [src_dict.get(w, UNK_IDX) for w in [START] + src_words + [END]]
            trg_ids = [trg_dict.get(w, UNK_IDX) for w in trg_words]
            if len(src_ids) > _MAX_LEN or len(trg_ids) > _MAX_LEN:
                continue
            trg_ids_next = trg_ids + [trg_dict[END]]
            trg_ids = [trg_dict[START]] + trg_ids
            yield src_ids, trg_ids, trg_ids_next

    return reader


def train(dict_size, count: int = 256):
    """Each sample: (src word-id seq, <s>-prefixed trg seq, next-word seq)."""
    return reader_creator(dict_size, count, seed=0)


def test(dict_size, count: int = 64):
    return reader_creator(dict_size, count, seed=1)


def gen(dict_size, count: int = 64):
    """The reference's held-out generation split."""
    return reader_creator(dict_size, count, seed=2)


def get_dict(dict_size, reverse=True):
    """(src_dict, trg_dict); reverse=True returns id→word maps like the
    reference (used to print generated translations)."""
    src_dict = _dict(_SRC_WORDS, dict_size)
    trg_dict = _dict(_TRG_WORDS, dict_size)
    if reverse:
        src_dict = {v: k for k, v in src_dict.items()}
        trg_dict = {v: k for k, v in trg_dict.items()}
    return src_dict, trg_dict


def fetch():
    return None
