"""reference: python/paddle/dataset/voc2012.py — VOC2012 segmentation
readers: train/test/val yield (HWC uint8 image, HW uint8 label map) with
the 0-20 class palette plus 255 = void. Synthetic-backed (zero-egress)
with the exact pair contract.
"""
from __future__ import annotations

import numpy as np

__all__ = ["train", "test", "val"]

NUM_CLASSES = 21  # 20 object classes + background
VOID_LABEL = 255


def _pairs(count, seed):
    rng = np.random.default_rng(seed)
    for _ in range(count):
        h = int(rng.integers(120, 220))
        w = int(rng.integers(120, 220))
        img = rng.integers(0, 256, (h, w, 3)).astype(np.uint8)
        # blocky label map: a few rectangles of random classes over
        # background, a thin void border like the real annotations
        label = np.zeros((h, w), np.uint8)
        for _k in range(int(rng.integers(1, 4))):
            cls = int(rng.integers(1, NUM_CLASSES))
            y0, x0 = int(rng.integers(0, h // 2)), int(rng.integers(0, w // 2))
            y1 = int(rng.integers(y0 + 1, h))
            x1 = int(rng.integers(x0 + 1, w))
            label[y0:y1, x0:x1] = cls
            if y1 - y0 > 2 and x1 - x0 > 2:
                label[y0, x0:x1] = VOID_LABEL
        yield img, label


def reader_creator(sub_name, count=48):
    seed = {"trainval": 20, "train": 21, "val": 22}[sub_name]

    def reader():
        for img, label in _pairs(count, seed):
            yield img, label

    return reader


def train(count: int = 48):
    """Each sample: (HWC uint8 image, HW uint8 segmentation label)."""
    return reader_creator("trainval", count)


def test(count: int = 48):
    return reader_creator("train", count)


def val(count: int = 48):
    return reader_creator("val", count)


def fetch():
    return None
