"""reference: python/paddle/dataset/conll05.py — CoNLL-2005 semantic-role
-labeling reader. test() yields 9-slot samples
(word_idx, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2, pred_idx, mark, label_idx)
— the five predicate-context columns and the predicate column are
broadcast to sentence length, mark flags the ±2 window around the verb,
and labels use the B-/I-/O tagging with exactly one B-V at the predicate.
Synthetic-backed (zero-egress) with the reference's exact slot layout and
context/mark derivation.
"""
from __future__ import annotations

import numpy as np

__all__ = ["get_dict", "get_embedding", "test"]

UNK_IDX = 0

_WORDS = [
    "the", "company", "said", "it", "will", "buy", "shares", "from",
    "investors", "board", "approved", "plan", "to", "sell", "unit",
    "profit", "rose", "in", "quarter", "analysts",
]
_VERBS = ["said", "buy", "approved", "sell", "rose"]
_LABELS = [
    "B-A0", "I-A0", "B-A1", "I-A1", "B-A2", "B-AM-TMP", "B-AM-LOC",
    "B-V", "O",
]


def get_dict():
    """(word_dict, verb_dict, label_dict) — <unk> is id 0 in word_dict
    like the reference's wordDict.txt."""
    word_dict = {"<unk>": UNK_IDX}
    for w in _WORDS:
        word_dict[w] = len(word_dict)
    verb_dict = {v: i for i, v in enumerate(_VERBS)}
    label_dict = {l: i for i, l in enumerate(_LABELS)}
    return word_dict, verb_dict, label_dict


def get_embedding(dim: int = 32):
    """The reference returns a path to trained wikipedia embeddings; here:
    a deterministic (len(word_dict), dim) float32 array."""
    word_dict, _, _ = get_dict()
    rng = np.random.default_rng(5)
    return rng.standard_normal((len(word_dict), dim)).astype(np.float32) * 0.1


def _sentences(count, seed):
    rng = np.random.default_rng(seed)
    for _ in range(count):
        length = int(rng.integers(5, 14))
        sent = [_WORDS[int(rng.integers(0, len(_WORDS)))] for _ in range(length)]
        verb_index = int(rng.integers(0, length))
        verb = _VERBS[int(rng.integers(0, len(_VERBS)))]
        sent[verb_index] = verb
        labels = []
        for i in range(length):
            if i == verb_index:
                labels.append("B-V")
            else:
                labels.append(_LABELS[int(rng.integers(0, len(_LABELS) - 2))])
        yield sent, verb, labels


def reader_creator(count, seed):
    word_dict, predicate_dict, label_dict = get_dict()

    def reader():
        for sentence, predicate, labels in _sentences(count, seed):
            sen_len = len(sentence)
            verb_index = labels.index("B-V")

            # ±2 context window around the predicate; out-of-range slots
            # read bos/eos sentinels (reference conll05.py:151-198)
            mark = [0] * len(labels)
            if verb_index > 0:
                mark[verb_index - 1] = 1
                ctx_n1 = sentence[verb_index - 1]
            else:
                ctx_n1 = "bos"
            if verb_index > 1:
                mark[verb_index - 2] = 1
                ctx_n2 = sentence[verb_index - 2]
            else:
                ctx_n2 = "bos"
            mark[verb_index] = 1
            ctx_0 = sentence[verb_index]
            if verb_index < len(labels) - 1:
                mark[verb_index + 1] = 1
                ctx_p1 = sentence[verb_index + 1]
            else:
                ctx_p1 = "eos"
            if verb_index < len(labels) - 2:
                mark[verb_index + 2] = 1
                ctx_p2 = sentence[verb_index + 2]
            else:
                ctx_p2 = "eos"

            word_idx = [word_dict.get(w, UNK_IDX) for w in sentence]
            ctx_n2_idx = [word_dict.get(ctx_n2, UNK_IDX)] * sen_len
            ctx_n1_idx = [word_dict.get(ctx_n1, UNK_IDX)] * sen_len
            ctx_0_idx = [word_dict.get(ctx_0, UNK_IDX)] * sen_len
            ctx_p1_idx = [word_dict.get(ctx_p1, UNK_IDX)] * sen_len
            ctx_p2_idx = [word_dict.get(ctx_p2, UNK_IDX)] * sen_len
            pred_idx = [predicate_dict.get(predicate)] * sen_len
            label_idx = [label_dict.get(w) for w in labels]

            yield (word_idx, ctx_n2_idx, ctx_n1_idx, ctx_0_idx, ctx_p1_idx,
                   ctx_p2_idx, pred_idx, mark, label_idx)

    return reader


def test(count: int = 64):
    return reader_creator(count, seed=3)


def fetch():
    return None
