"""reference: python/paddle/dataset/flowers.py — Oxford 102-flowers
readers: train/test/valid yield (CHW float image, label) after the
mapper (resize_short 256 → crop 224 ± flip). Synthetic-backed
(zero-egress) with the exact mapper pipeline and sample contract; the
`cycle` and `use_xmap` knobs behave like the reference's.
"""
from __future__ import annotations

import functools

import numpy as np

from . import image as _image
from .. import reader as _reader_mod

__all__ = ["train", "test", "valid"]

NUM_CLASSES = 102


def default_mapper(is_train, sample):
    """bytes-free variant of the reference's mapper: the synthetic reader
    already yields decoded HWC uint8, so only the geometric transform
    runs (resize_short 256 → 224 crop ± flip → CHW float)."""
    img, label = sample
    img = _image.simple_transform(
        img, 256, 224, is_train, mean=[103.94, 116.78, 123.68]
    )
    return img.flatten(), label  # simple_transform already yields float32


train_mapper = functools.partial(default_mapper, True)
test_mapper = functools.partial(default_mapper, False)


def _synthetic_images(count, seed):
    rng = np.random.default_rng(seed)
    for i in range(count):
        h = int(rng.integers(260, 320))
        w = int(rng.integers(260, 320))
        img = rng.integers(0, 256, (h, w, 3)).astype(np.uint8)
        label = int(rng.integers(1, NUM_CLASSES + 1))  # labels are 1-based
        yield img, label


def reader_creator(dataset_name, mapper, buffered_size=1024,
                   use_xmap=True, cycle=False, count=64):
    seed = {"trnid": 10, "tstid": 11, "valid": 12}[dataset_name]

    def reader():
        while True:
            for sample in _synthetic_images(count, seed):
                yield sample
            if not cycle:
                break

    if use_xmap:
        return _reader_mod.xmap_readers(mapper, reader, 4, buffered_size)
    return _reader_mod.map_readers(mapper, reader)


def train(mapper=train_mapper, buffered_size=1024, use_xmap=True,
          cycle=False):
    """Each sample: (flattened CHW float32 image, 1-based label)."""
    return reader_creator("trnid", mapper, buffered_size, use_xmap, cycle)


def test(mapper=test_mapper, buffered_size=1024, use_xmap=True,
         cycle=False):
    return reader_creator("tstid", mapper, buffered_size, use_xmap, cycle)


def valid(mapper=test_mapper, buffered_size=1024, use_xmap=True):
    return reader_creator("valid", mapper, buffered_size, use_xmap)


def fetch():
    return None
