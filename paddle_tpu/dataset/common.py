"""reference: python/paddle/dataset/common.py — download/cache helpers.
Zero-egress: download() raises with a clear message; the hashing and
cluster-split helpers work as in the reference."""
from __future__ import annotations

import hashlib
import os
import pickle

__all__ = ["DATA_HOME", "md5file", "download", "split", "cluster_files_reader"]

DATA_HOME = os.path.expanduser("~/.cache/paddle/dataset")


def md5file(fname: str) -> str:
    h = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(4096), b""):
            h.update(chunk)
    return h.hexdigest()


def download(url: str, module_name: str, md5sum: str, save_name=None):
    raise RuntimeError(
        f"paddle.dataset.common.download({url!r}) is unavailable in this "
        "zero-egress environment; the paddle_tpu.dataset readers are "
        "synthetic-backed and need no downloads"
    )


def split(reader, line_count, suffix="%05d.pickle", dumper=pickle.dump):
    """Split a reader's samples into pickle files of line_count each
    (reference: common.py split)."""
    indx = 0
    batch = []
    for d in reader():
        batch.append(d)
        if len(batch) == line_count:
            with open(suffix % indx, "wb") as f:
                dumper(batch, f)
            batch = []
            indx += 1
    if batch:
        with open(suffix % indx, "wb") as f:
            dumper(batch, f)


def cluster_files_reader(files_pattern, trainer_count, trainer_id,
                         loader=pickle.load):
    """Read this trainer's share of split files (reference: common.py)."""
    import glob

    def reader():
        flist = sorted(glob.glob(files_pattern))
        my = flist[trainer_id::trainer_count]
        for fn in my:
            with open(fn, "rb") as f:
                yield from loader(f)

    return reader
