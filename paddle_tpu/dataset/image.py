"""reference: python/paddle/dataset/image.py — numpy/cv2 image utilities
(resize_short, crops, flip, simple_transform, CHW conversion) feeding the
legacy readers. No cv2 dependency: decode goes through PIL (the same path
as vision.ops.decode_jpeg), resize_short through vision's bilinear
jax.image resize (matching cv2's default interpolation), and the crop/
flip/normalize transforms are exact numpy equivalents.
"""
from __future__ import annotations

import numpy as np

__all__ = [
    "load_image_bytes", "load_image", "resize_short", "to_chw",
    "center_crop", "random_crop", "left_right_flip", "simple_transform",
    "load_and_transform",
]


def load_image_bytes(bytes_, is_color=True):
    """Decode encoded image bytes → HWC uint8 (same PIL decode path as
    vision.ops.decode_jpeg, without the Tensor round trip)."""
    import io

    from PIL import Image

    img = Image.open(io.BytesIO(bytes_))
    img = img.convert("RGB" if is_color else "L")
    return np.asarray(img)


def load_image(file, is_color=True):
    with open(file, "rb") as f:
        return load_image_bytes(f.read(), is_color=is_color)


def resize_short(im, size):
    """Scale so the SHORT side equals `size` (reference image.py:202 uses
    cv2's default bilinear) — delegates to vision's bilinear resize
    (jax.image), one implementation for both surfaces; dtype preservation
    lives there too."""
    from ..vision.transforms_functional import resize as _v_resize

    return np.asarray(_v_resize(np.asarray(im), int(size),
                                interpolation="bilinear"))


def to_chw(im, order=(2, 0, 1)):
    return im.transpose(order)


def center_crop(im, size, is_color=True):
    h, w = im.shape[:2]
    h0 = (h - size) // 2
    w0 = (w - size) // 2
    return im[h0:h0 + size, w0:w0 + size]


def random_crop(im, size, is_color=True):
    h, w = im.shape[:2]
    h0 = np.random.randint(0, h - size + 1)
    w0 = np.random.randint(0, w - size + 1)
    return im[h0:h0 + size, w0:w0 + size]


def left_right_flip(im, is_color=True):
    return im[:, ::-1, :] if (is_color and im.ndim == 3) else im[:, ::-1]


def simple_transform(im, resize_size, crop_size, is_train, is_color=True,
                     mean=None):
    """resize_short → crop(±flip when training) → CHW float32 (−mean)
    (reference image.py:332)."""
    im = resize_short(im, resize_size)
    if is_train:
        im = random_crop(im, crop_size, is_color=is_color)
        if np.random.randint(2) == 0:
            im = left_right_flip(im, is_color)
    else:
        im = center_crop(im, crop_size, is_color=is_color)
    if im.ndim == 3:
        im = to_chw(im)
    im = im.astype(np.float32)
    if mean is not None:
        mean = np.array(mean, np.float32)
        if mean.ndim == 1 and im.ndim == 3:
            mean = mean[:, None, None]
        im -= mean
    return im


def load_and_transform(filename, resize_size, crop_size, is_train,
                       is_color=True, mean=None):
    return simple_transform(load_image(filename, is_color), resize_size,
                            crop_size, is_train, is_color, mean)
