"""paddle.dataset — the legacy reader-creator dataset package.

Reference analogue: python/paddle/dataset/ (mnist.py, cifar.py, imdb.py,
uci_housing.py, common.py ...) — each module exposes reader creators
(`train()`, `test()`) yielding numpy samples, composed with paddle.reader
combinators and fed through paddle.io / fleet datasets.

Zero-egress environment: the download mirrors are unreachable, so every
reader is backed by DETERMINISTIC synthetic data with the exact shapes,
dtypes, and value ranges of the originals (the same strategy as
paddle_tpu.vision.datasets). Sample counts are scaled down; every
reader takes an explicit sizing knob (`n=` for the image/tabular readers,
`count=` for imikolov, where `n` is the n-gram order).
"""
from . import (  # noqa: F401
    cifar,
    common,
    conll05,
    flowers,
    image,
    imdb,
    imikolov,
    mnist,
    movielens,
    uci_housing,
    voc2012,
    wmt14,
    wmt16,
)

__all__ = ["mnist", "cifar", "imdb", "imikolov", "movielens",
           "uci_housing", "common", "wmt14", "wmt16", "conll05",
           "flowers", "voc2012", "image"]
