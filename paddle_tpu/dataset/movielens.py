"""reference: python/paddle/dataset/movielens.py — ML-1M readers yielding
(user_id, gender, age, job, movie_id, categories, title_ids, rating) rows
plus movie/user info accessors. Synthetic-backed here with the original
category vocabulary and field ranges."""
from __future__ import annotations

import numpy as np

__all__ = ["train", "test", "get_movie_title_dict", "movie_categories",
           "max_movie_id", "max_user_id", "max_job_id", "age_table",
           "movie_info", "user_info", "MovieInfo", "UserInfo"]

age_table = [1, 18, 25, 35, 45, 50, 56]

_CATEGORIES = [
    "Action", "Adventure", "Animation", "Children's", "Comedy", "Crime",
    "Documentary", "Drama", "Fantasy", "Film-Noir", "Horror", "Musical",
    "Mystery", "Romance", "Sci-Fi", "Thriller", "War", "Western",
]
_TITLE_WORDS = ["the", "of", "movie", "night", "day", "man", "story",
                "city", "love", "war"]
_N_USERS = 200
_N_MOVIES = 400


class MovieInfo:
    def __init__(self, index, categories, title):
        self.index = int(index)
        self.categories = categories
        self.title = title

    def value(self):
        return [
            self.index,
            [_CAT_DICT[c] for c in self.categories],
            [_TITLE_DICT[w] for w in self.title.lower().split()],
        ]


class UserInfo:
    def __init__(self, index, gender, age, job_id):
        self.index = int(index)
        self.is_male = gender == "M"
        self.age = age_table.index(int(age))
        self.job_id = int(job_id)

    def value(self):
        return [self.index, 0 if self.is_male else 1, self.age, self.job_id]


def movie_categories():
    return dict(_CAT_DICT)


def get_movie_title_dict():
    return dict(_TITLE_DICT)


def max_movie_id():
    return _N_MOVIES


def max_user_id():
    return _N_USERS


def max_job_id():
    return 20


_CAT_DICT = {c: i for i, c in enumerate(_CATEGORIES)}
_TITLE_DICT = {w: i for i, w in enumerate(_TITLE_WORDS)}


def movie_info():
    """movie_id -> MovieInfo (reference: movielens.py movie_info)."""
    rng = np.random.default_rng(42)
    return {mid: _movie(mid, rng) for mid in range(1, _N_MOVIES + 1)}


def user_info():
    """user_id -> UserInfo (reference: movielens.py user_info)."""
    return {
        uid: UserInfo(uid, "M" if uid % 2 else "F",
                      age_table[uid % len(age_table)], uid % 21)
        for uid in range(1, _N_USERS + 1)
    }


def _movie(mid, rng):
    cats = [
        _CATEGORIES[int(c)]
        for c in rng.choice(len(_CATEGORIES), size=1 + int(mid) % 3,
                            replace=False)
    ]
    title = " ".join(
        _TITLE_WORDS[int(w)]
        for w in rng.choice(len(_TITLE_WORDS), size=3, replace=False)
    )
    return MovieInfo(mid, cats, title)


def _reader(n, seed):
    def reader():
        rng = np.random.default_rng(seed)
        for _ in range(n):
            uid = int(rng.integers(1, _N_USERS + 1))
            user = UserInfo(uid, "M" if uid % 2 else "F",
                            age_table[uid % len(age_table)], uid % 21)
            movie = _movie(int(rng.integers(1, _N_MOVIES + 1)), rng)
            rating = float(rng.integers(1, 6))
            yield user.value() + movie.value() + [[rating]]

    return reader


def train(n: int = 512):
    return _reader(n, seed=0)


def test(n: int = 128):
    return _reader(n, seed=1)
