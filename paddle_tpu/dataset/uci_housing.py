"""reference: python/paddle/dataset/uci_housing.py — train()/test()
readers yielding (13-float32 normalized features, 1-float32 price).
Synthetic-backed with a fixed linear ground truth + noise so regression
examples converge like the real data."""
from __future__ import annotations

import numpy as np

__all__ = ["train", "test", "feature_names"]

feature_names = [
    "CRIM", "ZN", "INDUS", "CHAS", "NOX", "RM", "AGE", "DIS", "RAD",
    "TAX", "PTRATIO", "B", "LSTAT",
]

_W = np.linspace(-1.5, 2.0, 13).astype(np.float32)


def _reader(n, seed):
    def reader():
        rng = np.random.default_rng(seed)
        for _ in range(n):
            x = rng.normal(0.0, 1.0, 13).astype(np.float32)
            y = np.float32(x @ _W + 22.5 + rng.normal(0.0, 0.5))
            yield x, np.array([y], np.float32)

    return reader


def train(n: int = 404):
    return _reader(n, seed=0)


def test(n: int = 102):
    return _reader(n, seed=1)
