"""reference: python/paddle/dataset/imdb.py — word_dict() plus
train(word_idx)/test(word_idx) readers yielding (word-id list, 0/1 label).
Synthetic-backed here with a small fixed vocabulary."""
from __future__ import annotations

import numpy as np

__all__ = ["train", "test", "word_dict"]

_POS = ["great", "excellent", "wonderful", "loved", "best", "amazing"]
_NEG = ["terrible", "awful", "boring", "hated", "worst", "poor"]
_FILL = ["movie", "film", "plot", "acting", "scene", "story", "the", "a"]


def word_dict():
    """word -> id; id len(dict) is reserved for <unk> like the reference."""
    words = sorted(set(_POS + _NEG + _FILL))
    return {w: i for i, w in enumerate(words)}


def _reader(n, seed):
    def reader():
        rng = np.random.default_rng(seed)
        wd = word_dict()
        for i in range(n):
            label = i % 2
            pool = _POS if label else _NEG
            length = int(rng.integers(5, 30))
            doc = [
                wd[pool[int(rng.integers(len(pool)))]]
                if rng.random() < 0.4
                else wd[_FILL[int(rng.integers(len(_FILL)))]]
                for _ in range(length)
            ]
            yield doc, label

    return reader


def train(word_idx=None, n: int = 512):
    return _reader(n, seed=0)


def test(word_idx=None, n: int = 128):
    return _reader(n, seed=1)
