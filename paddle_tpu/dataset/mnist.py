"""reference: python/paddle/dataset/mnist.py — train()/test() readers
yielding (784-float32 in [-1, 1], int label). Synthetic-backed here."""
from __future__ import annotations

import numpy as np

__all__ = ["train", "test"]


def _reader(n, seed):
    def reader():
        rng = np.random.default_rng(seed)
        for i in range(n):
            label = i % 10
            img = rng.normal(0.0, 0.3, 784).astype(np.float32)
            # class-dependent blob so models can actually learn
            img[label * 70:(label + 1) * 70] += 1.0
            yield np.clip(img, -1.0, 1.0), int(label)

    return reader


def train(n: int = 1024):
    return _reader(n, seed=0)


def test(n: int = 256):
    return _reader(n, seed=1)
