"""reference: python/paddle/dataset/imikolov.py — PTB language-model
readers: build_dict() then train(word_idx, n)/test(word_idx, n) yielding
n-gram tuples of word ids (or (src, trg) sequence pairs with
data_type=SEQ). Synthetic-backed here."""
from __future__ import annotations

import numpy as np

__all__ = ["build_dict", "train", "test"]


class DataType:
    NGRAM = 1
    SEQ = 2


_WORDS = [
    "the", "of", "and", "to", "in", "a", "is", "that", "for", "it",
    "market", "stock", "trade", "company", "year", "share", "price",
    "bank", "rate", "government",
]


def build_dict(min_word_freq: int = 50):
    """word -> id; <unk> and <e> reserved like the reference."""
    d = {w: i for i, w in enumerate(_WORDS)}
    d["<unk>"] = len(d)
    d["<e>"] = len(d)
    return d


def _sentences(n, seed):
    rng = np.random.default_rng(seed)
    for _ in range(n):
        length = int(rng.integers(4, 12))
        yield [int(rng.integers(0, len(_WORDS))) for _ in range(length)]


def _reader(word_idx, n, data_type, count, seed):
    def reader():
        for sent in _sentences(count, seed):
            if data_type == DataType.NGRAM:
                if len(sent) >= n:
                    for i in range(n - 1, len(sent)):
                        yield tuple(sent[i - n + 1:i + 1])
            else:
                yield sent[:-1], sent[1:]

    return reader


def train(word_idx=None, n: int = 5, data_type=DataType.NGRAM,
          count: int = 256):
    return _reader(word_idx, n, data_type, count, seed=0)


def test(word_idx=None, n: int = 5, data_type=DataType.NGRAM,
         count: int = 64):
    return _reader(word_idx, n, data_type, count, seed=1)
