"""reference: python/paddle/dataset/cifar.py — train10/test10 (10-way)
and train100/test100 (100-way) readers yielding (3072-float32 in [0, 1],
int label). Synthetic-backed here."""
from __future__ import annotations

import numpy as np

__all__ = ["train10", "test10", "train100", "test100"]


def _reader(n, classes, seed):
    def reader():
        rng = np.random.default_rng(seed)
        for i in range(n):
            label = i % classes
            img = rng.uniform(0.0, 1.0, 3072).astype(np.float32)
            img[(label % 32) * 96:(label % 32 + 1) * 96] *= 0.2
            yield img, int(label)

    return reader


def train10(cycle: bool = False, n: int = 1024):
    base = _reader(n, 10, seed=0)
    if not cycle:
        return base

    def cycled():
        while True:
            yield from base()

    return cycled


def test10(cycle: bool = False, n: int = 256):
    base = _reader(n, 10, seed=1)
    if not cycle:
        return base

    def cycled():
        while True:
            yield from base()

    return cycled


def train100(n: int = 1024):
    return _reader(n, 100, seed=2)


def test100(n: int = 256):
    return _reader(n, 100, seed=3)
