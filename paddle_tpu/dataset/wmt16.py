"""reference: python/paddle/dataset/wmt16.py — WMT16 en↔de multimodal
translation readers. train/test/validation(src_dict_size, trg_dict_size,
src_lang) yield (src_ids, trg_ids, trg_ids_next); start/end/unk ids are
shared across languages; dict sizes are capped at the corpus vocabulary
(TOTAL_EN_WORDS / TOTAL_DE_WORDS). Synthetic-backed (zero-egress) with
the reference's exact tuple structure, language routing, and caps.
"""
from __future__ import annotations

import numpy as np

__all__ = ["train", "test", "validation", "get_dict"]

START_MARK = "<s>"
END_MARK = "<e>"
UNK_MARK = "<unk>"

TOTAL_EN_WORDS = 11250
TOTAL_DE_WORDS = 19220

_EN_WORDS = [
    "a", "man", "in", "an", "orange", "hat", "starring", "at", "something",
    "boston", "terrier", "is", "running", "on", "lush", "green", "grass",
    "front", "of", "white", "fence", "girl", "karate", "uniform", "breaking",
]
_DE_WORDS = [
    "ein", "mann", "mit", "einem", "orangefarbenen", "hut", "der", "etwas",
    "anstarrt", "boston", "terrier", "lauft", "uber", "saftig", "grunes",
    "gras", "vor", "weisen", "zaun", "madchen", "im", "karateanzug",
    "bricht", "ein", "brett",
]


def _words(lang):
    return _EN_WORDS if lang == "en" else _DE_WORDS


def _total(lang):
    return TOTAL_EN_WORDS if lang == "en" else TOTAL_DE_WORDS


def _load_dict(lang, dict_size, reverse=False):
    # ids 0/1/2 are <s>/<e>/<unk> in every wmt16 dict (reference
    # __build_dict writes the three marks first)
    d = {START_MARK: 0, END_MARK: 1, UNK_MARK: 2}
    for w in _words(lang)[: max(0, dict_size - 3)]:
        if w not in d:
            d[w] = len(d)
    if reverse:
        return {v: k for k, v in d.items()}
    return d


def __get_dict_size(src_dict_size, trg_dict_size, src_lang):
    src_dict_size = min(src_dict_size, _total(src_lang))
    trg_dict_size = min(trg_dict_size, _total("de" if src_lang == "en" else "en"))
    return src_dict_size, trg_dict_size


def _pairs(count, seed):
    rng = np.random.default_rng(seed)
    for _ in range(count):
        n_en = int(rng.integers(3, 12))
        n_de = int(rng.integers(3, 12))
        en = [_EN_WORDS[int(rng.integers(0, len(_EN_WORDS)))] for _ in range(n_en)]
        de = [_DE_WORDS[int(rng.integers(0, len(_DE_WORDS)))] for _ in range(n_de)]
        yield en, de


def reader_creator(src_dict_size, trg_dict_size, src_lang, count, seed):
    def reader():
        src_dict = _load_dict(src_lang, src_dict_size)
        trg_dict = _load_dict("de" if src_lang == "en" else "en", trg_dict_size)
        start_id = src_dict[START_MARK]
        end_id = src_dict[END_MARK]
        unk_id = src_dict[UNK_MARK]
        for en, de in _pairs(count, seed):
            src_words, trg_words = (en, de) if src_lang == "en" else (de, en)
            src_ids = (
                [start_id] + [src_dict.get(w, unk_id) for w in src_words] + [end_id]
            )
            trg_ids = [trg_dict.get(w, unk_id) for w in trg_words]
            trg_ids_next = trg_ids + [end_id]
            trg_ids = [start_id] + trg_ids
            yield src_ids, trg_ids, trg_ids_next

    return reader


def _check_lang(src_lang):
    if src_lang not in ("en", "de"):
        raise ValueError(
            "An error language type. Only support: en (for English); "
            "de (for Germany)."
        )


def train(src_dict_size, trg_dict_size, src_lang="en", count: int = 256):
    _check_lang(src_lang)
    src_dict_size, trg_dict_size = __get_dict_size(
        src_dict_size, trg_dict_size, src_lang
    )
    return reader_creator(src_dict_size, trg_dict_size, src_lang, count, seed=0)


def test(src_dict_size, trg_dict_size, src_lang="en", count: int = 64):
    _check_lang(src_lang)
    src_dict_size, trg_dict_size = __get_dict_size(
        src_dict_size, trg_dict_size, src_lang
    )
    return reader_creator(src_dict_size, trg_dict_size, src_lang, count, seed=1)


def validation(src_dict_size, trg_dict_size, src_lang="en", count: int = 64):
    _check_lang(src_lang)
    src_dict_size, trg_dict_size = __get_dict_size(
        src_dict_size, trg_dict_size, src_lang
    )
    return reader_creator(src_dict_size, trg_dict_size, src_lang, count, seed=2)


def get_dict(lang, dict_size, reverse=False):
    dict_size = min(dict_size, _total(lang))
    return _load_dict(lang, dict_size, reverse=reverse)


def fetch():
    return None
