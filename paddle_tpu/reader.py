"""paddle.reader — reader-creator combinators.

Reference analogue: python/paddle/reader/decorator.py — a reader is a
zero-arg callable returning an iterable of samples; these combinators
compose readers (cache/shuffle/batch windows/parallel map). Pure host-side
python; the TPU path consumes the composed reader through paddle.io /
fleet datasets.
"""
from __future__ import annotations

import itertools
import queue as _queue
import random as _random
import threading
from typing import Callable

__all__ = ["cache", "map_readers", "shuffle", "chain", "compose",
           "buffered", "firstn", "xmap_readers", "multiprocess_reader"]


def cache(reader: Callable) -> Callable:
    """Cache the FIRST pass in memory; later passes replay it (reference:
    decorator.py:52)."""
    all_data = tuple(reader())

    def cached_reader():
        return iter(all_data)

    return cached_reader


def map_readers(func: Callable, *readers) -> Callable:
    """Zip readers and map func over the per-reader samples (reference:
    decorator.py:92)."""

    def reader():
        rs = [r() for r in readers]
        for vals in zip(*rs):
            yield func(*vals)

    return reader


def shuffle(reader: Callable, buf_size: int) -> Callable:
    """Window shuffle with a buf_size reservoir (reference:
    decorator.py:134)."""

    def reader_():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            _random.shuffle(buf)
            yield from buf

    return reader_


def chain(*readers) -> Callable:
    """Concatenate readers (reference: decorator.py:183)."""

    def reader():
        return itertools.chain(*[r() for r in readers])

    return reader


def compose(*readers, **kwargs) -> Callable:
    """Zip readers into flattened tuples (reference: decorator.py:248).
    check_alignment=True (default) raises when readers are uneven."""
    check_alignment = kwargs.pop("check_alignment", True)
    if kwargs:
        raise TypeError(f"unexpected kwargs {sorted(kwargs)}")

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def reader():
        rs = [r() for r in readers]
        if not check_alignment:
            for outputs in zip(*rs):
                yield sum((make_tuple(o) for o in outputs), ())
            return
        for outputs in itertools.zip_longest(*rs):
            if any(o is None for o in outputs):
                raise ValueError(
                    "outputs of readers are not aligned (use "
                    "check_alignment=False to truncate)"
                )
            yield sum((make_tuple(o) for o in outputs), ())

    return reader


def buffered(reader: Callable, size: int) -> Callable:
    """Producer-thread buffering up to `size` samples (reference:
    decorator.py:308) — overlaps the reader's IO with the consumer."""

    class _End:
        pass

    def reader_():
        q: _queue.Queue = _queue.Queue(maxsize=size)

        def produce():
            try:
                for d in reader():
                    q.put(d)
            finally:
                q.put(_End)

        t = threading.Thread(target=produce, daemon=True)
        t.start()
        while True:
            e = q.get()
            if e is _End:
                break
            yield e

    return reader_


def firstn(reader: Callable, n: int) -> Callable:
    """First n samples (reference: decorator.py:367)."""

    def reader_():
        return itertools.islice(reader(), n)

    return reader_


def xmap_readers(mapper: Callable, reader: Callable, process_num: int,
                 buffer_size: int, order: bool = False) -> Callable:
    """Thread-pool map over a reader (reference: decorator.py:412 — the
    'process_num' workers are threads there too). order=True preserves
    sample order."""

    def reader_():
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=process_num) as pool:
            if order:
                yield from pool.map(mapper, reader())
            else:
                futures = []
                for sample in reader():
                    futures.append(pool.submit(mapper, sample))
                    if len(futures) >= buffer_size:
                        done = [f for f in futures if f.done()]
                        if not done:
                            done = [futures[0]]
                        for f in done:
                            futures.remove(f)
                            yield f.result()
                for f in futures:
                    yield f.result()

    return reader_


def multiprocess_reader(readers, use_pipe: bool = True,
                        queue_size: int = 1000) -> Callable:
    """Merge readers, one OS process per reader (reference:
    decorator.py:505). One sentinel per worker ends the merge; a worker
    that dies mid-stream sends an error marker so the consumer raises
    instead of hanging.

    Workers are started via the 'fork' context like the reference —
    samples stream back over a Queue (use_pipe=False) or one Pipe per
    worker (use_pipe=True, the default). Samples must be picklable.
    Prefer spawning the composed reader BEFORE any jax device work: fork
    duplicates the parent's threads' locks (the usual fork-vs-jax
    caveat)."""
    if not isinstance(readers, (list, tuple)) or not readers:
        raise TypeError("`readers` must be a non-empty list or tuple")
    import multiprocessing as _mp
    import pickle as _pickle

    ctx = _mp.get_context("fork")
    _ERR = "__multiprocess_reader_error__"

    def _read_into_queue(reader, q):
        try:
            for sample in reader():
                if sample is None:
                    raise ValueError("sample has None")
                q.put(sample)
            q.put(None)
        except Exception:
            q.put(_ERR)
            raise

    def _cleanup(procs, clean_exit):
        # early exit / error: workers may be blocked in put()/send() on a
        # full channel — terminate FIRST, then reap (join-first would burn
        # its full timeout per blocked worker)
        for p in procs:
            if not clean_exit and p.is_alive():
                p.terminate()
            p.join(timeout=5)
            if p.is_alive():
                p.kill()

    def queue_reader():
        q = ctx.Queue(queue_size)
        procs = [
            ctx.Process(target=_read_into_queue, args=(r, q), daemon=True)
            for r in readers
        ]
        for p in procs:
            p.start()
        finished = 0
        strikes = 0
        try:
            while finished < len(readers):
                try:
                    sample = q.get(timeout=1.0)
                except _queue.Empty:
                    # a worker that died without its sentinel (hard kill,
                    # sys.exit — ANY exitcode) would hang the merge: more
                    # dead workers than sentinels received means at least
                    # one such death. Two consecutive empty timeouts guard
                    # against a sentinel still in the feeder pipe.
                    dead = [p for p in procs if not p.is_alive()]
                    if len(dead) > finished and q.empty():
                        strikes += 1
                        if strikes >= 2:
                            codes = [p.exitcode for p in dead]
                            raise ValueError(
                                "multiprocess_reader: a worker process "
                                "died without finishing (exitcodes "
                                f"{codes})"
                            )
                    continue
                strikes = 0
                if sample is None:
                    finished += 1
                elif isinstance(sample, str) and sample == _ERR:
                    raise ValueError(
                        "multiprocess_reader: a worker reader raised"
                    )
                else:
                    yield sample
        finally:
            _cleanup(procs, clean_exit=finished >= len(readers))

    def _read_into_pipe(reader, conn):
        try:
            for sample in reader():
                if sample is None:
                    raise ValueError("sample has None")
                conn.send(_pickle.dumps(sample))
            conn.send(_pickle.dumps(None))
        except Exception:
            conn.send(_pickle.dumps(_ERR))
            raise
        finally:
            conn.close()

    def pipe_reader():
        conns = []
        procs = []
        for r in readers:
            parent, child = ctx.Pipe()
            p = ctx.Process(target=_read_into_pipe, args=(r, child),
                            daemon=True)
            p.start()
            child.close()
            conns.append(parent)
            procs.append(p)
        clean = False
        try:
            live = list(conns)
            while live:
                for conn in _mp.connection.wait(live):
                    try:
                        buf = conn.recv()
                    except EOFError:
                        # pipe closed WITHOUT the pickled None sentinel:
                        # the worker died mid-stream — raising beats
                        # silently truncating the merged dataset
                        p = procs[conns.index(conn)]
                        p.join(timeout=5)
                        raise ValueError(
                            "multiprocess_reader: a worker process died "
                            f"mid-stream (exitcode {p.exitcode})"
                        )
                    sample = _pickle.loads(buf)
                    if sample is None:
                        live.remove(conn)
                        conn.close()
                    elif isinstance(sample, str) and sample == _ERR:
                        raise ValueError(
                            "multiprocess_reader: a worker reader raised"
                        )
                    else:
                        yield sample
            clean = True
        finally:
            _cleanup(procs, clean_exit=clean)

    return pipe_reader if use_pipe else queue_reader
