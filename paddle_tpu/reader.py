"""paddle.reader — reader-creator combinators.

Reference analogue: python/paddle/reader/decorator.py — a reader is a
zero-arg callable returning an iterable of samples; these combinators
compose readers (cache/shuffle/batch windows/parallel map). Pure host-side
python; the TPU path consumes the composed reader through paddle.io /
fleet datasets.
"""
from __future__ import annotations

import itertools
import queue as _queue
import random as _random
import threading
from typing import Callable

__all__ = ["cache", "map_readers", "shuffle", "chain", "compose",
           "buffered", "firstn", "xmap_readers"]


def cache(reader: Callable) -> Callable:
    """Cache the FIRST pass in memory; later passes replay it (reference:
    decorator.py:52)."""
    all_data = tuple(reader())

    def cached_reader():
        return iter(all_data)

    return cached_reader


def map_readers(func: Callable, *readers) -> Callable:
    """Zip readers and map func over the per-reader samples (reference:
    decorator.py:92)."""

    def reader():
        rs = [r() for r in readers]
        for vals in zip(*rs):
            yield func(*vals)

    return reader


def shuffle(reader: Callable, buf_size: int) -> Callable:
    """Window shuffle with a buf_size reservoir (reference:
    decorator.py:134)."""

    def reader_():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            _random.shuffle(buf)
            yield from buf

    return reader_


def chain(*readers) -> Callable:
    """Concatenate readers (reference: decorator.py:183)."""

    def reader():
        return itertools.chain(*[r() for r in readers])

    return reader


def compose(*readers, **kwargs) -> Callable:
    """Zip readers into flattened tuples (reference: decorator.py:248).
    check_alignment=True (default) raises when readers are uneven."""
    check_alignment = kwargs.pop("check_alignment", True)
    if kwargs:
        raise TypeError(f"unexpected kwargs {sorted(kwargs)}")

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def reader():
        rs = [r() for r in readers]
        if not check_alignment:
            for outputs in zip(*rs):
                yield sum((make_tuple(o) for o in outputs), ())
            return
        for outputs in itertools.zip_longest(*rs):
            if any(o is None for o in outputs):
                raise ValueError(
                    "outputs of readers are not aligned (use "
                    "check_alignment=False to truncate)"
                )
            yield sum((make_tuple(o) for o in outputs), ())

    return reader


def buffered(reader: Callable, size: int) -> Callable:
    """Producer-thread buffering up to `size` samples (reference:
    decorator.py:308) — overlaps the reader's IO with the consumer."""

    class _End:
        pass

    def reader_():
        q: _queue.Queue = _queue.Queue(maxsize=size)

        def produce():
            try:
                for d in reader():
                    q.put(d)
            finally:
                q.put(_End)

        t = threading.Thread(target=produce, daemon=True)
        t.start()
        while True:
            e = q.get()
            if e is _End:
                break
            yield e

    return reader_


def firstn(reader: Callable, n: int) -> Callable:
    """First n samples (reference: decorator.py:367)."""

    def reader_():
        return itertools.islice(reader(), n)

    return reader_


def xmap_readers(mapper: Callable, reader: Callable, process_num: int,
                 buffer_size: int, order: bool = False) -> Callable:
    """Thread-pool map over a reader (reference: decorator.py:412 — the
    'process_num' workers are threads there too). order=True preserves
    sample order."""

    def reader_():
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=process_num) as pool:
            if order:
                yield from pool.map(mapper, reader())
            else:
                futures = []
                for sample in reader():
                    futures.append(pool.submit(mapper, sample))
                    if len(futures) >= buffer_size:
                        done = [f for f in futures if f.done()]
                        if not done:
                            done = [futures[0]]
                        for f in done:
                            futures.remove(f)
                            yield f.result()
                for f in futures:
                    yield f.result()

    return reader_
