"""Fleet serving front door: health-routed dispatch over N engine replicas,
mid-decode failover, and coordinator-driven autoscale — zero drops.

One :class:`FrontDoor` owns a routing table of replicas — in-process
:class:`~paddle_tpu.serving.Engine` instances (wrapped in a
:class:`LocalReplica`, each supervised by serving/supervisor.py) and
cross-host :class:`RemoteReplica` entries discovered through the obs-lease
plane (``fleet/obs.py``): every lease snapshot carries a ``serving``
section with each engine's :meth:`~paddle_tpu.serving.Engine.routing_signals`
(queue depth, in-flight count, measured prefill/decode cost EMAs, health,
``serve_addr``), so routing is **cost-predicted from each replica's own
measured EMAs**, not round-robin — the same CheckFreq measure-then-decide
discipline the admission controller applies inside one engine, applied
across the fleet.

Routing honors health: ``draining``/``dead`` replicas are never picked,
``degraded`` is last-resort. The failure contract extends the engine's
zero-drop guarantee across replica death:

- a replica that dies (process SIGKILL, wedge past its restart budget,
  lease lost mid-decode, sustained transport failures) has ALL of its
  queued and in-flight requests re-dispatched to survivors — greedy decode
  is deterministic, so the re-run reproduces **bitwise-identical tokens**;
- reroutes are counted separately (``router_reroutes``) and NEVER burn the
  request's engine-level retry budget; past ``FLAGS_router_reroute_budget``
  the request answers a structured error response — never a hang;
- a lease-master partition (a FAILED lease read) keeps the last-known
  routing table and counts ``router_lease_read_failures``; only a replica
  absent from a SUCCESSFUL read past ``FLAGS_router_lease_grace_s`` is
  declared lost;
- ``run_until_idle`` ends with the same drop audit the engine runs:
  every submitted request must hold exactly one terminal response
  (``router_requests_dropped`` counts violations — the chaos gate fails
  on any).

Shed (``overloaded``) responses are re-dispatched to a sibling, honoring
the response's ``retry_after_ms`` hint (the shedding replica's measured
queue-wait EMA) as a backoff before retrying the same replica.

Autoscale: a sustained fleet queue-wait-p99 breach
(``FLAGS_router_autoscale_p99_ms`` for ``FLAGS_router_autoscale_sustain_s``)
proposes a GROW through the PR 14 ``RescaleCoordinator`` serve-scale
document (``elastic.propose_serve_scale``); a sustained fully-idle fleet
proposes a SHRINK and gracefully drains the least-loaded local replica.
Both are debounced by ``FLAGS_router_autoscale_cooldown_s``.

SIGTERM on the router (``install_preemption_handler``) drains everything:
router-queued work is handed to serviceable peers (remote preferred —
``router_drain_handoffs``), local engines finish their in-flight work
under their own drain contract, and new submits answer a structured
rejection.

``tools/serve_fleet_probe.py`` is the multi-process chaos gate: replica
SIGKILL mid-decode, lease-master partition, 2x oversubscription storm, and
scale-up-under-storm — all with zero dropped requests and answered tokens
bitwise-equal to a single-replica baseline.
"""
from __future__ import annotations

import itertools
import json
import signal as _signal
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Sequence as Seq
from urllib import request as _urlreq
from urllib.parse import parse_qs, urlparse

import numpy as np

from ..core import flags
from .scheduler import Response
from .supervisor import Supervisor

__all__ = [
    "FleetAutoscaler",
    "FrontDoor",
    "LocalReplica",
    "RemoteReplica",
    "ReplicaServer",
    "ReplicaUnreachable",
    "health_pool",
    "pick_serviceable",
]

_FRONTDOOR_IDS = itertools.count(1)


def health_pool(candidates):
    """The fleet health-preference rule, in one place: serviceable
    candidates only (never draining/dead), with 'degraded' demoted to
    last-resort. Returns the preferred pool (healthy if any, else the
    degraded survivors); empty when nothing serves."""
    ok = [c for c in candidates if c.serviceable()]
    healthy = [c for c in ok if c.health() != "degraded"]
    return healthy or ok


def pick_serviceable(candidates, rr: int = 0) -> Optional[int]:
    """Round-robin index pick under the same health-preference rule —
    the inference PredictorPool's acquire policy, shared here so the
    pool is a thin shim over the FrontDoor's routing rather than a
    second, drifting copy of it. Returns None when no candidate is
    serviceable."""
    n = len(candidates)
    degraded = None
    for i in range(n):
        idx = (rr + i) % n
        c = candidates[idx]
        if not c.serviceable():
            continue
        if c.health() == "degraded":
            if degraded is None:
                degraded = idx
            continue
        return idx
    return degraded


class ReplicaUnreachable(RuntimeError):
    """A transport failure talking to a remote replica (connect/timeout).
    NOT a request failure: the router retries elsewhere, and sustained
    unreachability (FLAGS_router_replica_retries) declares the replica
    lost."""


def _response_to_doc(r: Response) -> Dict[str, Any]:
    """Response → wire doc. Logits never cross the wire (parity/debug
    only, and per-token [vocab] rows would dwarf the payload)."""
    return {
        "request_id": int(r.request_id),
        "status": r.status,
        "tokens": [int(t) for t in r.tokens],
        "error": r.error,
        "retriable": bool(r.retriable),
        "prompt_len": int(r.prompt_len),
        "submit_time": r.submit_time,
        "first_token_time": r.first_token_time,
        "done_time": r.done_time,
        "retry_after_ms": r.retry_after_ms,
    }


def _response_from_doc(d: Dict[str, Any]) -> Response:
    return Response(
        request_id=int(d["request_id"]),
        status=str(d["status"]),
        tokens=[int(t) for t in (d.get("tokens") or [])],
        error=d.get("error"),
        retriable=bool(d.get("retriable")),
        prompt_len=int(d.get("prompt_len") or 0),
        submit_time=float(d.get("submit_time") or 0.0),
        first_token_time=d.get("first_token_time"),
        done_time=d.get("done_time"),
        retry_after_ms=d.get("retry_after_ms"),
    )


# ---------------------------------------------------------------------------
# replicas
# ---------------------------------------------------------------------------
class LocalReplica:
    """An in-process Engine behind the uniform replica interface. The
    engine is driven by the FrontDoor's own pump (one supervised tick per
    pump), so a wedge restarts the engine in place and a restart budget
    exhaustion surfaces as health 'dead' — which the router's sweep turns
    into a failover, not an error passthrough."""

    kind = "local"

    def __init__(self, engine, *, supervised: bool = True,
                 max_restarts: Optional[int] = None):
        self.engine = engine
        self.name = f"local:{engine._uid}"
        self._sup = Supervisor(engine, max_restarts) if supervised else None
        self._lost = False

    def health(self) -> str:
        return self.engine.health

    def serviceable(self) -> bool:
        return not self._lost and self.engine.serviceable()

    def signals(self) -> Dict[str, Any]:
        return self.engine.routing_signals()

    def submit(self, prompt, **kw) -> int:
        return self.engine.submit(prompt, **kw)

    def poll(self, rids) -> Dict[int, Optional[Response]]:
        return {rid: self.engine.pop_response(rid) for rid in rids}

    def pending(self) -> int:
        return self.engine.pending

    def step(self) -> bool:
        """One engine tick if there is work; True when a tick ran."""
        if self.engine.pending and self.engine.health != "dead":
            (self._sup or self.engine).step()
            return True
        return False

    def idle_audit(self):
        """At fleet idle: run the engine's own zero-drop/leak audit and
        stand its watchdog heartbeat down (the run_until_idle
        discipline — an idle engine must not read as stalled)."""
        if self.engine.pending:
            return
        from ..profiler import trace as _trace

        self.engine._audit_drops()
        _trace.watchdog_disarm(f"serve[{self.engine._uid}]")

    def begin_drain(self):
        self.engine.begin_drain()

    def close(self):
        if self._sup is not None:
            self._sup.close()
        self.engine.close()


class RemoteReplica:
    """A cross-host replica behind a :class:`ReplicaServer`, discovered
    from the obs-lease ``serving`` section. Routing signals come from the
    lease snapshot (refreshed at the aggregator cadence); submit/poll go
    over loopback-style HTTP to ``serve_addr``. Death is declared two
    ways: sustained transport failures (FLAGS_router_replica_retries), or
    absence from a SUCCESSFUL lease read past
    FLAGS_router_lease_grace_s — a FAILED read (master partition) starts
    neither clock."""

    kind = "remote"

    def __init__(self, node: str, addr: str, *, engine=None,
                 http_timeout: float = 2.0):
        self.node = str(node)
        self.addr = str(addr)
        self.name = (f"remote:{self.node}/"
                     f"{engine if engine is not None else self.addr}")
        self.http_timeout = float(http_timeout)
        self._signals: Dict[str, Any] = {}
        self._lost = False
        self._missing_since: Optional[float] = None
        self._transport_fails = 0

    def refresh(self, row: Dict[str, Any]):
        """A fresh lease row for this replica (the serving section)."""
        self._signals = dict(row or {})
        self._missing_since = None

    def health(self) -> str:
        if self._lost:
            return "dead"
        return str(self._signals.get("health") or "ready")

    def serviceable(self) -> bool:
        return not self._lost and self.health() not in ("draining", "dead")

    def signals(self) -> Dict[str, Any]:
        return self._signals

    def pending(self) -> int:
        sig = self._signals
        return (int(sig.get("queue_depth") or 0)
                + int(sig.get("inflight") or 0))

    def step(self) -> bool:
        return False  # remote replicas drive their own loop

    def idle_audit(self):
        pass

    def _http(self, method: str, path: str, body=None) -> Dict[str, Any]:
        url = f"http://{self.addr}{path}"
        data = None if body is None else json.dumps(body).encode()
        req = _urlreq.Request(url, data=data, method=method,
                              headers={"Content-Type": "application/json"})
        try:
            with _urlreq.urlopen(req, timeout=self.http_timeout) as resp:
                out = json.loads(resp.read().decode() or "{}")
        except Exception as e:
            self._transport_fails += 1
            raise ReplicaUnreachable(
                f"{self.name} {method} {path}: {e}") from e
        self._transport_fails = 0
        if "health" in out:
            # the wire reply is fresher than the lease snapshot
            self._signals["health"] = out["health"]
        return out

    def submit(self, prompt, *, max_new_tokens=None, eos_token_id=None,
               deadline_ms=None, priority: str = "interactive") -> int:
        doc = {
            "prompt": [int(t) for t in np.asarray(prompt).reshape(-1)],
            "max_new_tokens": max_new_tokens,
            "eos_token_id": eos_token_id,
            "deadline_ms": deadline_ms,
            "priority": priority,
        }
        return int(self._http("POST", "/submit", doc)["rid"])

    def poll(self, rids) -> Dict[int, Optional[Response]]:
        rids = list(rids)
        if not rids:
            return {}
        q = ",".join(str(r) for r in rids)
        out = self._http("GET", f"/responses?rids={q}")
        docs = out.get("responses") or {}
        res: Dict[int, Optional[Response]] = {}
        for rid in rids:
            d = docs.get(str(rid))
            res[rid] = None if d is None else _response_from_doc(d)
        return res

    def begin_drain(self):
        try:
            self._http("POST", "/drain", {})
        except ReplicaUnreachable:
            pass  # best-effort: an unreachable replica can't drain anyway

    def close(self):
        pass  # the remote process owns its engine


class ReplicaServer:
    """Hosts one Engine behind a loopback HTTP plane so a cross-host
    FrontDoor can route to it: POST /submit, GET /responses?rids=..,
    POST /drain, GET /healthz. Sets ``engine.serve_addr`` so the obs
    lease advertises the endpoint.

    A coarse lock serializes handler threads against the pump — the
    engine stays effectively single-threaded (its counter/queue
    discipline assumes it), and a submit landing mid-tick waits for the
    tick instead of racing it."""

    def __init__(self, engine, *, host: str = "127.0.0.1", port: int = 0,
                 supervised: bool = True,
                 max_restarts: Optional[int] = None):
        self._engine = engine
        self._sup = Supervisor(engine, max_restarts) if supervised else None
        self._lock = threading.RLock()
        self._was_busy = False
        server = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # noqa: N802 - stdlib name
                pass  # keep probe/test stdout clean

            def _send(self, code: int, doc: Dict[str, Any]):
                body = json.dumps(doc).encode()
                try:
                    self.send_response(code)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                except (BrokenPipeError, ConnectionResetError):
                    # the client timed out / died mid-response — its
                    # router re-polls or reroutes; a handler-thread
                    # traceback dump is the only thing to suppress here
                    pass

            def do_POST(self):  # noqa: N802 - stdlib name
                n = int(self.headers.get("Content-Length") or 0)
                try:
                    body = json.loads(self.rfile.read(n).decode() or "{}")
                except ValueError:
                    return self._send(400, {"error": "bad json"})
                path = urlparse(self.path).path
                if path == "/submit":
                    return self._send(200, server._handle_submit(body))
                if path == "/drain":
                    return self._send(200, server._handle_drain())
                self._send(404, {"error": f"no such endpoint {path}"})

            def do_GET(self):  # noqa: N802 - stdlib name
                u = urlparse(self.path)
                if u.path == "/responses":
                    rids: List[int] = []
                    for part in (parse_qs(u.query).get("rids") or []):
                        rids += [int(t) for t in part.split(",")
                                 if t.strip()]
                    return self._send(200, server._handle_poll(rids))
                if u.path == "/healthz":
                    with server._lock:
                        return self._send(200, {
                            "health": server._engine.health,
                            "signals": server._engine.routing_signals(),
                        })
                self._send(404, {"error": f"no such endpoint {u.path}"})

        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self.addr = f"{host}:{self._httpd.server_address[1]}"
        engine.serve_addr = self.addr
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name=f"replica-server[{engine._uid}]", daemon=True)
        self._started = False

    # -- handlers (HTTP threads, serialized by the lock) -----------------
    def _handle_submit(self, body: Dict[str, Any]) -> Dict[str, Any]:
        with self._lock:
            rid = self._engine.submit(
                np.asarray(body["prompt"], np.int64),
                max_new_tokens=body.get("max_new_tokens"),
                eos_token_id=body.get("eos_token_id"),
                deadline_ms=body.get("deadline_ms"),
                priority=body.get("priority") or "interactive",
            )
            return {"rid": int(rid), "health": self._engine.health}

    def _handle_poll(self, rids: List[int]) -> Dict[str, Any]:
        with self._lock:
            out: Dict[str, Any] = {}
            for rid in rids:
                r = self._engine.pop_response(rid)
                out[str(rid)] = None if r is None else _response_to_doc(r)
            return {"responses": out, "health": self._engine.health}

    def _handle_drain(self) -> Dict[str, Any]:
        with self._lock:
            self._engine.begin_drain()
            return {"health": self._engine.health}

    # -- the serving loop ------------------------------------------------
    def start(self) -> "ReplicaServer":
        if not self._started:
            self._thread.start()
            self._started = True
        return self

    def pump(self) -> bool:
        """One supervised engine tick if there is work; the idle edge
        runs the engine's drop/leak audit and stands its watchdog down."""
        with self._lock:
            busy = self._engine.pending and self._engine.health != "dead"
            if busy:
                (self._sup or self._engine).step()
                self._was_busy = True
            elif self._was_busy:
                self._was_busy = False
                from ..profiler import trace as _trace

                self._engine._audit_drops()
                _trace.watchdog_disarm(f"serve[{self._engine._uid}]")
            return bool(busy)

    def run(self, *, publisher=None, publish_every_s: float = 0.5,
            poll_s: float = 0.005,
            should_stop: Optional[Callable[[], bool]] = None):
        """Drive the replica: pump the engine, publish the obs lease at
        a fixed cadence, sleep only when idle. This is the replica
        worker's main loop in tools/serve_fleet_probe.py."""
        self.start()
        last_pub = 0.0
        while should_stop is None or not should_stop():
            busy = self.pump()
            now = time.monotonic()
            if publisher is not None and now - last_pub >= publish_every_s:
                last_pub = now
                try:
                    publisher.publish()
                except Exception:
                    pass  # obs is observability: fail soft, keep serving
            if not busy:
                time.sleep(poll_s)
            else:
                # the handler threads contend on the same coarse lock;
                # an unfair back-to-back reacquire would starve submits
                # and polls for as long as the engine stays busy
                time.sleep(0.001)

    def close(self):
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except Exception:
            pass
        if self._sup is not None:
            self._sup.close()


# ---------------------------------------------------------------------------
# the front door
# ---------------------------------------------------------------------------
class _Tracked:
    """One request the front door owns until it holds a terminal
    response. ``reroutes`` is the router-level failover count — separate
    from the engine-level Request.retries budget by design."""

    __slots__ = ("frid", "prompt", "max_new_tokens", "eos_token_id",
                 "deadline_ms", "priority", "submit_time", "replica", "rid",
                 "reroutes", "not_before", "last_response")

    def __init__(self, frid, prompt, max_new_tokens, eos_token_id,
                 deadline_ms, priority, submit_time):
        self.frid = frid
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.eos_token_id = eos_token_id
        self.deadline_ms = deadline_ms
        self.priority = priority
        self.submit_time = submit_time
        self.replica = None
        self.rid: Optional[int] = None
        self.reroutes = 0
        # earliest re-dispatch time for shed work (the retry_after_ms hint)
        self.not_before: Optional[float] = None
        # last shed response: passed through if the reroute budget runs out
        self.last_response: Optional[Response] = None


class FrontDoor:
    """The fleet request router. See the module docstring for the
    contract; the short version:

        fd = paddle.serving.FrontDoor([engine_a, engine_b])
        frids = [fd.submit(p, max_new_tokens=16) for p in prompts]
        fd.run_until_idle()
        out = [fd.pop_response(i) for i in frids]

    ``engines`` may mix raw Engines (wrapped into LocalReplica) with
    pre-built Local/RemoteReplica objects; ``aggregator`` (a
    fleet.obs.FleetAggregator) adds lease-discovered remote replicas;
    ``coordinator`` (a fleet.elastic.RescaleCoordinator) receives
    autoscale proposals when FLAGS_router_autoscale_p99_ms > 0."""

    def __init__(self, engines: Seq = (), *, aggregator=None,
                 coordinator=None, supervised: bool = True,
                 max_restarts: Optional[int] = None,
                 on_grow: Optional[Callable] = None,
                 on_shrink: Optional[Callable] = None,
                 http_timeout: float = 2.0):
        self._replicas: List[Any] = []
        for eng in engines:
            if isinstance(eng, (LocalReplica, RemoteReplica)):
                self._replicas.append(eng)
            else:
                self._replicas.append(LocalReplica(
                    eng, supervised=supervised, max_restarts=max_restarts))
        self._aggregator = aggregator
        self.http_timeout = float(http_timeout)
        self._remote_by_addr: Dict[str, RemoteReplica] = {
            rep.addr: rep for rep in self._replicas
            if isinstance(rep, RemoteReplica)}
        self._tracked: Dict[int, _Tracked] = {}
        self._parked: List[int] = []
        self._responses: Dict[int, Response] = {}
        self._submitted: set = set()
        self._retiring: List[Any] = []   # replicas draining toward close
        self._draining = False
        self._drain_flushed = False
        self._rr = 0                      # round-robin tiebreak cursor
        self._last_refresh: Optional[float] = None
        self._prev_handlers: Dict[int, Any] = {}
        self._poll_s = 0.005
        self._autoscaler = FleetAutoscaler(
            self, coordinator=coordinator, on_grow=on_grow,
            on_shrink=on_shrink)

    # -- clock (a method so tests drive it virtually) --------------------
    def _now(self) -> float:
        return time.time()

    # -- replica management ----------------------------------------------
    @property
    def replicas(self) -> List[Any]:
        return list(self._replicas)

    def add_replica(self, engine_or_replica, *, supervised: bool = True,
                    max_restarts: Optional[int] = None):
        """Attach one more replica (the scale-up path: a freshly started
        engine or a newly discovered remote)."""
        from ..core import dispatch

        rep = engine_or_replica
        if not isinstance(rep, (LocalReplica, RemoteReplica)):
            rep = LocalReplica(rep, supervised=supervised,
                               max_restarts=max_restarts)
        self._replicas.append(rep)
        if isinstance(rep, RemoteReplica):
            self._remote_by_addr[rep.addr] = rep
        dispatch._emit("route", site="frontdoor", phase="replica_join",
                       replica=rep.name, replica_kind=rep.kind)
        return rep

    def _alive(self, rep) -> bool:
        return not getattr(rep, "_lost", False) and rep.health() != "dead"

    def _inflight_to(self, rep) -> int:
        return sum(1 for t in self._tracked.values() if t.replica is rep)

    def _local_addrs(self) -> set:
        return {rep.engine.serve_addr for rep in self._replicas
                if isinstance(rep, LocalReplica)
                and rep.engine.serve_addr}

    # -- lease-plane refresh ----------------------------------------------
    def refresh_routing(self, force: bool = False):
        """Re-read the obs leases: join newly advertised replicas, update
        remote signals/health, and start the grace clock for replicas
        absent from a SUCCESSFUL read. A failed read (master partition)
        keeps the last-known table — routing degrades to stale signals,
        never to a dropped fleet."""
        from ..core import dispatch

        if self._aggregator is None:
            return
        now = self._now()
        if (not force and self._last_refresh is not None
                and now - self._last_refresh < float(
                    flags.flag("router_refresh_s"))):
            return
        self._last_refresh = now
        try:
            snaps = self._aggregator.snapshots()
        except Exception:
            dispatch._counters["router_lease_read_failures"] += 1
            dispatch._emit("route", site="frontdoor",
                           phase="lease_read_failed")
            return
        local_addrs = self._local_addrs()
        seen: set = set()
        for node in sorted(snaps):
            for row in (snaps[node].get("serving") or []):
                addr = (row or {}).get("serve_addr")
                if not addr or addr in local_addrs:
                    continue  # our own engines are routed live, not by lease
                seen.add(addr)
                rep = self._remote_by_addr.get(addr)
                if rep is None:
                    rep = RemoteReplica(node, addr,
                                        engine=row.get("engine"),
                                        http_timeout=self.http_timeout)
                    self._remote_by_addr[addr] = rep
                    self._replicas.append(rep)
                    dispatch._emit("route", site="frontdoor",
                                   phase="replica_join", replica=rep.name,
                                   replica_kind="remote")
                rep.refresh(row)
        grace = float(flags.flag("router_lease_grace_s"))
        for addr, rep in list(self._remote_by_addr.items()):
            if rep._lost or addr in seen:
                continue
            if rep._missing_since is None:
                rep._missing_since = now
            elif now - rep._missing_since > grace:
                self._lose_replica(
                    rep, f"lease lost (absent {now - rep._missing_since:.1f}"
                         f"s > FLAGS_router_lease_grace_s)")

    # -- routing -----------------------------------------------------------
    def _pick(self, t: _Tracked, exclude=()):
        """Cost-predicted replica choice: lowest predicted completion from
        the replica's own measured EMAs, backlog-weighted; degraded is
        last-resort; during a router drain remote peers are preferred
        (the local engines are about to stop admitting)."""
        pool = health_pool([r for r in self._replicas
                            if r not in exclude
                            and r not in self._retiring])
        if not pool:
            return None
        if self._draining:
            remote = [r for r in pool if r.kind == "remote"]
            pool = remote or pool
        max_new = int(t.max_new_tokens
                      or flags.flag("serving_max_new_tokens"))
        best = None
        best_key = None
        for i, r in enumerate(pool):
            sig = r.signals() or {}
            tok = float(sig.get("tok_ema_ms") or 0.0)
            pre = float(sig.get("prefill_ema_ms") or 0.0)
            # lease signals lag: trust whichever backlog estimate is
            # larger — the replica's own count or what WE routed there
            backlog = max(
                int(sig.get("queue_depth") or 0)
                + int(sig.get("inflight") or 0),
                self._inflight_to(r))
            predicted = pre + max_new * tok * (1 + backlog)
            key = (predicted, backlog, (i - self._rr) % len(pool))
            if best_key is None or key < best_key:
                best, best_key = r, key
        self._rr += 1
        return best

    def _dispatch(self, t: _Tracked, exclude=()) -> bool:
        """Route one request to the best replica; False → caller parks."""
        from ..core import dispatch

        tried = set(exclude)
        while True:
            rep = self._pick(t, exclude=tried)
            if rep is None:
                t.replica, t.rid = None, None
                return False
            dl = None
            if t.deadline_ms is not None:
                # the deadline is wall-clock from the ORIGINAL submit:
                # a reroute dispatches with the remaining budget, not a
                # fresh one
                elapsed = (self._now() - t.submit_time) * 1000.0
                dl = max(1.0, t.deadline_ms - elapsed)
            try:
                rid = rep.submit(
                    t.prompt, max_new_tokens=t.max_new_tokens,
                    eos_token_id=t.eos_token_id,
                    # explicit 0 = the engine's documented no-deadline
                    # opt-out (None would re-apply the engine default)
                    deadline_ms=(0 if dl is None else dl),
                    priority=t.priority)
            except ReplicaUnreachable:
                tried.add(rep)
                self._check_transport(rep)
                continue
            t.replica, t.rid = rep, rid
            t.not_before = None
            dispatch._counters["router_routed"] += 1
            if self._draining and rep.kind == "remote":
                dispatch._counters["router_drain_handoffs"] += 1
                dispatch._emit("route", site="frontdoor",
                               phase="drain_handoff", frid=t.frid,
                               replica=rep.name)
            dispatch._emit("route", site="frontdoor", phase="dispatch",
                           frid=t.frid, replica=rep.name, rid=rid,
                           reroutes=t.reroutes)
            return True

    def _park(self, t: _Tracked):
        if t.frid not in self._parked:
            self._parked.append(t.frid)

    def _check_transport(self, rep):
        if (rep.kind == "remote" and not rep._lost
                and rep._transport_fails > int(
                    flags.flag("router_replica_retries"))):
            self._lose_replica(
                rep, f"unreachable after {rep._transport_fails} "
                     "consecutive transport failures")

    def _lose_replica(self, rep, why: str):
        """Declare one replica dead and fail ALL of its work over to
        survivors — queued and in-flight alike (greedy decode makes the
        re-runs bitwise-identical)."""
        from ..core import dispatch

        if getattr(rep, "_lost", False):
            return
        rep._lost = True
        dispatch._counters["router_replicas_lost"] += 1
        dispatch._emit("route", site="frontdoor", phase="replica_lost",
                       replica=rep.name, why=why[:160])
        for t in list(self._tracked.values()):
            if t.replica is rep:
                self._reroute(t, f"replica {rep.name} lost: {why}")

    def _reroute(self, t: _Tracked, why: str, *,
                 shed_hint_ms: Optional[float] = None,
                 kind: str = "reroute"):
        """Re-dispatch one request to a survivor. Counted in
        router_reroutes / router_shed_reroutes — NEVER in the engine-level
        retry budget. Past FLAGS_router_reroute_budget: the last shed
        response passes through (still retriable), or a structured error."""
        from ..core import dispatch

        prev = t.replica
        t.replica, t.rid = None, None
        t.reroutes += 1
        budget = int(flags.flag("router_reroute_budget"))
        if t.reroutes > budget:
            resp = t.last_response
            if resp is None:
                resp = Response(
                    request_id=t.frid, status="error",
                    error=(f"reroute budget exhausted after {t.reroutes - 1}"
                           f" reroutes (FLAGS_router_reroute_budget="
                           f"{budget}): {why}"),
                    retriable=True, prompt_len=int(t.prompt.size),
                    submit_time=t.submit_time, done_time=time.time())
            dispatch._emit("route", site="frontdoor",
                           phase="reroute_exhausted", frid=t.frid,
                           reroutes=t.reroutes - 1, why=why[:160])
            self._finish(t, resp)
            return
        counter = ("router_shed_reroutes" if kind == "shed"
                   else "router_reroutes")
        dispatch._counters[counter] += 1
        dispatch._emit("route", site="frontdoor", phase=kind, frid=t.frid,
                       prev=(prev.name if prev is not None else None),
                       n=t.reroutes, why=why[:160])
        if shed_hint_ms is not None:
            # honor the shedding replica's retry_after_ms before trying
            # again; a DIFFERENT sibling may take it immediately
            t.not_before = self._now() + float(shed_hint_ms) / 1000.0
            if self._dispatch(t, exclude=(prev,) if prev else ()):
                return
        elif self._dispatch(t, exclude=(prev,) if prev else ()):
            return
        self._park(t)

    # -- terminal bookkeeping ---------------------------------------------
    def _finish(self, t: _Tracked, resp: Response):
        from ..core import dispatch

        resp.request_id = t.frid  # responses live in the ROUTER id space
        self._responses[t.frid] = resp
        self._tracked.pop(t.frid, None)
        if t.frid in self._parked:
            self._parked.remove(t.frid)
        dispatch._emit("route", site="frontdoor", phase="final",
                       frid=t.frid, status=resp.status,
                       reroutes=t.reroutes)

    def _handle_response(self, t: _Tracked, resp: Response):
        rep = t.replica
        st = resp.status
        if st == "overloaded":
            # shed: the replica was busy, not broken — re-dispatch to a
            # sibling, honoring the measured retry_after_ms hint
            t.last_response = resp
            self._reroute(
                t, f"shed by {rep.name if rep else '?'}",
                shed_hint_ms=resp.retry_after_ms, kind="shed")
            return
        if st == "rejected" and rep is not None and not rep.serviceable():
            # draining/dead replica refusing admission: replica-state
            # rejection, not a verdict on the request — try a survivor
            self._reroute(t, f"rejected by non-serviceable {rep.name}")
            return
        if st == "error" and rep is not None and not rep.serviceable():
            # the replica failed (fail_clean / drain teardown), not the
            # request: greedy decode re-runs it identically elsewhere
            self._reroute(t, f"replica failure on {rep.name}: {resp.error}")
            return
        # ok / timeout / intrinsic rejection / genuine request error
        self._finish(t, resp)

    # -- public API --------------------------------------------------------
    def submit(self, prompt, max_new_tokens: Optional[int] = None,
               eos_token_id: Optional[int] = None,
               deadline_ms: Optional[float] = None,
               priority: str = "interactive") -> int:
        """Route one request into the fleet; returns the front-door
        request id (the router's own id space — replica-local ids are an
        implementation detail that changes across failovers)."""
        from ..core import dispatch

        frid = next(_FRONTDOOR_IDS)
        if deadline_ms is None:
            default_dl = float(flags.flag("serving_default_deadline_ms"))
            deadline_ms = default_dl if default_dl > 0 else None
        elif deadline_ms == 0:
            deadline_ms = None
        dispatch._counters["router_requests"] += 1
        self._submitted.add(frid)
        if self._draining:
            self._responses[frid] = Response(
                request_id=frid, status="rejected",
                error="front door is draining (preemption)",
                prompt_len=int(np.asarray(prompt).size),
                submit_time=self._now(), done_time=time.time())
            dispatch._emit("route", site="frontdoor", phase="reject",
                           frid=frid, why="draining")
            return frid
        t = _Tracked(
            frid=frid,
            prompt=np.asarray(prompt, np.int64).reshape(-1),
            max_new_tokens=max_new_tokens, eos_token_id=eos_token_id,
            deadline_ms=deadline_ms, priority=priority,
            submit_time=self._now())
        self._tracked[frid] = t
        dispatch._emit("route", site="frontdoor", phase="accept",
                       frid=frid, prompt_len=int(t.prompt.size),
                       priority=priority)
        if not self._dispatch(t):
            self._park(t)
        return frid

    def response(self, frid: int) -> Optional[Response]:
        return self._responses.get(frid)

    def pop_response(self, frid: int) -> Optional[Response]:
        r = self._responses.pop(frid, None)
        if r is not None:
            self._submitted.discard(frid)
        return r

    @property
    def pending(self) -> int:
        """Requests the front door has accepted but not yet answered."""
        return len(self._tracked)

    def pump(self) -> bool:
        """One router tick: refresh the table, sweep dead replicas (fail
        their work over), step local engines, poll for responses,
        re-dispatch parked work, tick the autoscaler. Returns True when a
        local engine made progress (the run_until_idle sleep gate)."""
        now = self._now()
        if self._draining and not self._drain_flushed:
            self._flush_drain()
        self.refresh_routing()
        self._sweep_replicas()
        progressed = False
        for rep in list(self._replicas):
            if rep.kind == "local" and not rep._lost:
                progressed = rep.step() or progressed
        self._poll()
        self._redispatch_parked(now)
        self._finish_orphans()
        self._autoscaler.tick(now)
        self._close_retired()
        return progressed

    def _sweep_replicas(self):
        for rep in list(self._replicas):
            if getattr(rep, "_lost", False):
                continue
            if rep.kind == "local" and rep.health() == "dead":
                # fail_clean already answered everything with terminal
                # errors INSIDE the engine — the router reroutes instead
                # of passing an engine's death through to callers
                self._lose_replica(
                    rep, "engine dead (restart budget exhausted)")
            elif (rep.kind == "remote"
                  and rep._transport_fails > int(
                      flags.flag("router_replica_retries"))):
                self._lose_replica(
                    rep, f"unreachable after {rep._transport_fails} "
                         "consecutive transport failures")

    def _poll(self):
        by_rep: Dict[int, List[_Tracked]] = {}
        reps: Dict[int, Any] = {}
        for t in self._tracked.values():
            if t.replica is not None and not getattr(t.replica, "_lost",
                                                     False):
                key = id(t.replica)
                reps[key] = t.replica
                by_rep.setdefault(key, []).append(t)
        for key, ts in by_rep.items():
            rep = reps[key]
            try:
                res = rep.poll([t.rid for t in ts])
            except ReplicaUnreachable:
                self._check_transport(rep)
                continue  # responses stay queued on the replica
            for t in ts:
                resp = res.get(t.rid)
                if resp is not None and t.frid in self._tracked:
                    self._handle_response(t, resp)

    def _redispatch_parked(self, now: float):
        still: List[int] = []
        for frid in self._parked:
            t = self._tracked.get(frid)
            if t is None or t.replica is not None:
                continue  # finished or re-dispatched since parking
            if t.not_before is not None and now < t.not_before:
                still.append(frid)
                continue
            t.not_before = None
            if not self._dispatch(t):
                still.append(frid)
        self._parked = still

    def _finish_orphans(self):
        """When EVERY replica is dead/lost, outstanding work can never
        complete — answer it with a structured retriable error now (zero
        hangs) instead of spinning until a timeout."""
        if not self._tracked or any(self._alive(r) for r in self._replicas):
            return
        for frid in list(self._tracked):
            t = self._tracked[frid]
            self._finish(t, Response(
                request_id=frid, status="error",
                error="no serviceable replica remains (every replica is "
                      "dead or lost)",
                retriable=True, prompt_len=int(t.prompt.size),
                submit_time=t.submit_time, done_time=time.time()))

    def run_until_idle(self, timeout_s: Optional[float] = None):
        """Drive the fleet until every submitted request holds a terminal
        response, then run the zero-drop audit (the engine
        run_until_idle contract, fleet-wide). ``timeout_s`` is a backstop
        for remote fleets: on expiry the outstanding work answers a
        structured error — never a hang."""
        deadline = (None if timeout_s is None
                    else time.monotonic() + float(timeout_s))
        while self._tracked:
            progressed = self.pump()
            if deadline is not None and time.monotonic() > deadline:
                for frid in list(self._tracked):
                    t = self._tracked[frid]
                    self._finish(t, Response(
                        request_id=frid, status="error",
                        error=(f"front door run_until_idle timed out after "
                               f"{timeout_s:.1f}s with the request still "
                               "outstanding"),
                        retriable=True, prompt_len=int(t.prompt.size),
                        submit_time=t.submit_time, done_time=time.time()))
                break
            if not progressed and self._tracked:
                time.sleep(self._poll_s)  # remote-only work: don't busy-spin
        self._audit()
        for rep in self._replicas:
            rep.idle_audit()

    def _audit(self):
        """The fleet drop tripwire: every submitted id must hold exactly
        one response. Violations count router_requests_dropped (the chaos
        gate fails on any) and answer an error so no caller hangs."""
        from ..core import dispatch

        missing = self._submitted - set(self._responses)
        for frid in missing:
            dispatch._counters["router_requests_dropped"] += 1
            self._responses[frid] = Response(
                request_id=frid, status="error",
                error="request lost by the front door (dropped) — "
                      "router bug",
                done_time=time.time())
        self._submitted -= missing

    def serve(self, prompts: Seq, **submit_kw) -> List[Response]:
        """Submit every prompt, run the fleet to idle, return (and evict)
        the responses in submit order."""
        frids = [self.submit(p, **submit_kw) for p in prompts]
        self.run_until_idle()
        return [self.pop_response(i) for i in frids]

    # -- preemption drain --------------------------------------------------
    def begin_drain(self):
        """Stop admitting; the flush (hand parked work to peers, drain
        local engines) runs at the next pump — this method is safe to
        call from a signal handler (flag writes only)."""
        from ..core import dispatch

        if self._draining:
            return
        self._draining = True
        dispatch._emit("route", site="frontdoor", phase="drain_begin",
                       outstanding=len(self._tracked))

    def _flush_drain(self):
        """The drain choreography, in order: (1) dispatch router-parked
        work while replicas still admit — remote peers preferred by
        _pick's drain rule; (2) THEN drain the local engines (their
        in-flight completes under the engine drain contract)."""
        self._drain_flushed = True
        for frid in list(self._parked):
            t = self._tracked.get(frid)
            if t is None or t.replica is not None:
                continue
            t.not_before = None
            if self._dispatch(t):
                self._parked.remove(frid)
        for rep in self._replicas:
            if rep.kind == "local" and not rep._lost:
                rep.begin_drain()

    def drain(self) -> List[Response]:
        """begin_drain + run to idle; returns every retained response."""
        self.begin_drain()
        self.run_until_idle()
        return list(self._responses.values())

    def install_preemption_handler(self, signals=(_signal.SIGTERM,)):
        for s in signals:
            if s in self._prev_handlers:
                continue  # already installed — keep the ORIGINAL previous
            self._prev_handlers[s] = _signal.signal(
                s, lambda signum, frame: self.begin_drain())

    def uninstall_preemption_handler(self):
        for s, h in self._prev_handlers.items():
            _signal.signal(s, h)
        self._prev_handlers.clear()

    # -- autoscale plumbing ------------------------------------------------
    def _retire_one(self):
        """Graceful shrink: drain the least-loaded serviceable LOCAL
        replica (never the last live one); it closes at idle in
        _close_retired. Remote-only fleets just emit the proposal — the
        external fleet manager owns those processes."""
        from ..core import dispatch

        cands = [r for r in self._replicas
                 if r.kind == "local" and r.serviceable()
                 and r not in self._retiring]
        if not cands or sum(1 for r in self._replicas
                            if self._alive(r)) <= 1:
            return None
        victim = min(cands, key=lambda r: (r.pending(),
                                           self._inflight_to(r)))
        victim.begin_drain()
        self._retiring.append(victim)
        dispatch._emit("route", site="frontdoor", phase="replica_retire",
                       replica=victim.name)
        return victim

    def _close_retired(self):
        for rep in list(self._retiring):
            if rep.pending() == 0 and self._inflight_to(rep) == 0:
                from ..core import dispatch

                rep.idle_audit()
                rep.close()
                self._retiring.remove(rep)
                if rep in self._replicas:
                    self._replicas.remove(rep)
                dispatch._emit("route", site="frontdoor",
                               phase="replica_retired", replica=rep.name)

    # -- introspection -----------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        return {
            "replicas": [{
                "name": r.name, "kind": r.kind, "health": r.health(),
                "lost": bool(getattr(r, "_lost", False)),
                "retiring": r in self._retiring,
                "signals": r.signals(),
            } for r in self._replicas],
            "outstanding": len(self._tracked),
            "parked": len(self._parked),
            "draining": self._draining,
            "autoscale": self._autoscaler.state(),
        }

    def close(self, close_replicas: bool = True):
        self.uninstall_preemption_handler()
        if close_replicas:
            for rep in self._replicas:
                try:
                    rep.close()
                except Exception:
                    pass
        self._replicas = []
        self._remote_by_addr = {}


class FleetAutoscaler:
    """Debounced fleet-size proposals from measured serving signals.

    GROW: the fleet-merged queue-wait p99 (max over live replicas' PR 10
    trip-wire windows) above FLAGS_router_autoscale_p99_ms for
    FLAGS_router_autoscale_sustain_s proposes n+1 through the
    RescaleCoordinator serve-scale document (and the on_grow callback —
    the probe's fleet manager spawns the replica and acks).

    SHRINK: a fully idle fleet (no tracked, queued, or in-flight work
    anywhere) for FLAGS_router_autoscale_idle_s proposes n-1 and
    gracefully drains the least-loaded local replica.

    Entirely off while FLAGS_router_autoscale_p99_ms is 0 (the default).
    All state is wall-clock-parameterized through tick(now) so tests
    drive it with a virtual clock."""

    def __init__(self, frontdoor: FrontDoor, *, coordinator=None,
                 on_grow: Optional[Callable] = None,
                 on_shrink: Optional[Callable] = None):
        self._fd = frontdoor
        self._coordinator = coordinator
        self._on_grow = on_grow
        self._on_shrink = on_shrink
        self._breach_since: Optional[float] = None
        self._idle_since: Optional[float] = None
        self._cooldown_until: Optional[float] = None
        self.grow_proposals = 0
        self.shrink_proposals = 0
        self._last: Optional[Dict[str, Any]] = None

    def fleet_queue_wait_p99(self) -> Optional[float]:
        """Max of the live replicas' recent-window queue-wait p99s — the
        conservative fleet SLO view (one overwhelmed replica IS a breach;
        routing should have balanced it away, so a sustained max means
        the whole fleet is out of headroom)."""
        vals = []
        for rep in self._fd._replicas:
            if not self._fd._alive(rep):
                continue
            adm = (rep.signals() or {}).get("admission") or {}
            v = adm.get("queue_wait_p99_ms")
            if v is not None:
                vals.append(float(v))
        return max(vals) if vals else None

    def _fleet_idle(self) -> bool:
        if self._fd._tracked:
            return False
        for rep in self._fd._replicas:
            if not self._fd._alive(rep):
                continue
            sig = rep.signals() or {}
            if (sig.get("queue_depth") or 0) or (sig.get("inflight") or 0):
                return False
        return True

    def _n_live(self) -> int:
        return sum(1 for r in self._fd._replicas
                   if self._fd._alive(r) and r not in self._fd._retiring)

    def tick(self, now: float) -> Optional[int]:
        """One debounce step; returns the proposal id when one fired."""
        from ..core import dispatch

        breach_ms = float(flags.flag("router_autoscale_p99_ms"))
        if breach_ms <= 0:
            return None  # autoscale proposals off (the default)
        if self._cooldown_until is not None and now < self._cooldown_until:
            return None
        sustain = float(flags.flag("router_autoscale_sustain_s"))
        idle_s = float(flags.flag("router_autoscale_idle_s"))
        n = self._n_live()
        p99 = self.fleet_queue_wait_p99()
        if p99 is not None and p99 > breach_ms:
            self._idle_since = None
            if self._breach_since is None:
                self._breach_since = now
                dispatch._emit("route", site="autoscaler",
                               phase="breach_open", p99_ms=round(p99, 3))
            elif now - self._breach_since >= sustain:
                return self._propose(
                    "grow", n + 1, now,
                    f"fleet queue-wait p99 {p99:.1f} ms > "
                    f"{breach_ms:.1f} ms sustained "
                    f"{now - self._breach_since:.1f}s", p99)
            return None
        self._breach_since = None
        if idle_s > 0 and n > 1 and self._fleet_idle():
            if self._idle_since is None:
                self._idle_since = now
            elif now - self._idle_since >= idle_s:
                return self._propose(
                    "shrink", n - 1, now,
                    f"fleet idle {now - self._idle_since:.1f}s", p99)
        else:
            self._idle_since = None
        return None

    def _propose(self, kind: str, target: int, now: float, why: str,
                 p99: Optional[float]) -> Optional[int]:
        from ..core import dispatch

        self._cooldown_until = now + float(
            flags.flag("router_autoscale_cooldown_s"))
        self._breach_since = None
        self._idle_since = None
        proposal = None
        if self._coordinator is not None:
            try:
                proposal = self._coordinator.propose_serve_scale(
                    target, reason=why, kind=kind,
                    signals={"queue_wait_p99_ms": p99,
                             "replicas": self._n_live()})
            except Exception as e:
                dispatch._emit("route", site="autoscaler",
                               phase="propose_failed",
                               error=str(e)[:160])
        if kind == "grow":
            dispatch._counters["router_autoscale_grow_proposals"] += 1
            self.grow_proposals += 1
        else:
            dispatch._counters["router_autoscale_shrink_proposals"] += 1
            self.shrink_proposals += 1
        dispatch._emit("route", site="autoscaler", phase=kind,
                       target=target, proposal=proposal, why=why[:160])
        self._last = {"kind": kind, "target": target,
                      "proposal": proposal, "at": now, "why": why}
        if kind == "grow" and self._on_grow is not None:
            try:
                self._on_grow(target, proposal)
            except Exception:
                pass  # the callback is advisory; the doc is the contract
        if kind == "shrink":
            self._fd._retire_one()
            if self._on_shrink is not None:
                try:
                    self._on_shrink(target, proposal)
                except Exception:
                    pass
        return proposal

    def state(self) -> Dict[str, Any]:
        return {
            "enabled": float(flags.flag("router_autoscale_p99_ms")) > 0,
            "grow_proposals": self.grow_proposals,
            "shrink_proposals": self.shrink_proposals,
            "breach_since": self._breach_since,
            "idle_since": self._idle_since,
            "cooldown_until": self._cooldown_until,
            "last": self._last,
        }
