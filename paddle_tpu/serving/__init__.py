"""paddle.serving — the continuous-batching inference runtime.

The front door that turns the framework's inference ingredients into
requests/second (ROADMAP open item 2): a request queue feeding
shape-bucketed continuous batches (the ``io/bucketing.py`` padding-policy
idiom), a **paged KV cache** whose block pool is sized up front by the PR 4
memory planner (``analysis.memory.plan_block_pool`` — admission is refused
past ``FLAGS_memory_budget_mb`` instead of OOMing), and prefill/decode
steps captured as **one donated XLA program per bucket signature** via the
decode-mode capture in ``core/lazy.py`` (the CUDA-Graphs capture/replay
contract from PAPERS.md, generalized beyond training). The resilience
ladder runs through the serve loop: a transient fault mid-decode demotes
that bucket's program captured → lazy → per-op and retries the batch
without dropping requests; SIGTERM drains in-flight sequences before exit.

    import paddle_tpu as paddle
    from paddle_tpu.models import GPTConfig, GPTForPretraining

    model = GPTForPretraining(GPTConfig(...))
    engine = paddle.serving.Engine(model)
    rid = engine.submit(prompt_ids, max_new_tokens=64, eos_token_id=0)
    engine.run_until_idle()
    print(engine.response(rid).tokens)

See SERVING.md for the queue/bucket/paged-cache design and the flags
(``paddle.describe_flags('serving')``).
"""
from __future__ import annotations

from .cache import BlockPool, PagedCacheView  # noqa: F401
from .engine import Engine, ServingConfig  # noqa: F401
from .scheduler import (  # noqa: F401
    Request,
    RequestQueue,
    Response,
    ServingBuckets,
)

__all__ = [
    "BlockPool",
    "Engine",
    "PagedCacheView",
    "Request",
    "RequestQueue",
    "Response",
    "ServingBuckets",
    "ServingConfig",
    "create_engine",
]


def create_engine(model, **kwargs) -> Engine:
    """Build an :class:`Engine` with keyword config (the
    ``inference.create_predictor`` idiom for the serving surface)."""
    return Engine(model, ServingConfig(**kwargs) if kwargs else None)
