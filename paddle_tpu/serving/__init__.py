"""paddle.serving — the continuous-batching inference runtime.

The front door that turns the framework's inference ingredients into
requests/second (ROADMAP open item 2): a request queue feeding
shape-bucketed continuous batches (the ``io/bucketing.py`` padding-policy
idiom), a **paged KV cache** whose block pool is sized up front by the PR 4
memory planner (``analysis.memory.plan_block_pool`` — admission is refused
past ``FLAGS_memory_budget_mb`` instead of OOMing), and prefill/decode
steps captured as **one donated XLA program per bucket signature** via the
decode-mode capture in ``core/lazy.py`` (the CUDA-Graphs capture/replay
contract from PAPERS.md, generalized beyond training). The resilience
ladder runs through the serve loop: a transient fault mid-decode demotes
that bucket's program captured → lazy → per-op and retries the batch
without dropping requests; SIGTERM drains in-flight sequences before exit.

Overload robustness (ISSUE 11): per-request **deadlines** enforced at
every stage (queue / prefill / mid-decode, with partial 'timeout'
responses), an **SLO-aware admission controller** that predicts completion
from measured cost EMAs and sheds what cannot make its deadline (two
priority classes — batch sheds first), and a **Supervisor** that restarts
a wedged engine (bounded, then fails cleanly) while ``Engine.health``
(warming/ready/degraded/draining/dead) lets the inference PredictorPool
route traffic around unhealthy replicas.

    import paddle_tpu as paddle
    from paddle_tpu.models import GPTConfig, GPTForPretraining

    model = GPTForPretraining(GPTConfig(...))
    engine = paddle.serving.Engine(model)
    rid = engine.submit(prompt_ids, max_new_tokens=64, eos_token_id=0)
    engine.run_until_idle()
    print(engine.response(rid).tokens)

The fleet **FrontDoor** (ISSUE 20, ``serving/frontdoor.py``) routes
requests across N replicas — in-process Engines and cross-host
ReplicaServers discovered through the obs-lease plane — with
cost-predicted dispatch, bitwise-identical mid-decode failover (bounded
by FLAGS_router_reroute_budget, audited to zero drops), shed re-dispatch
honoring ``retry_after_ms``, and coordinator-driven autoscale proposals.

See SERVING.md for the queue/bucket/paged-cache design and the flags
(``paddle.describe_flags('serving')``).
"""
from __future__ import annotations

from .admission import AdmissionController  # noqa: F401
from .cache import BlockPool, PagedCacheView  # noqa: F401
from .engine import HEALTH_STATES, Engine, ServingConfig  # noqa: F401
from .frontdoor import (  # noqa: F401
    FleetAutoscaler,
    FrontDoor,
    LocalReplica,
    RemoteReplica,
    ReplicaServer,
    ReplicaUnreachable,
)
from .scheduler import (  # noqa: F401
    PRIORITIES,
    Request,
    RequestQueue,
    Response,
    ServingBuckets,
)
from .supervisor import Supervisor  # noqa: F401

__all__ = [
    "AdmissionController",
    "BlockPool",
    "Engine",
    "FleetAutoscaler",
    "FrontDoor",
    "HEALTH_STATES",
    "LocalReplica",
    "PRIORITIES",
    "PagedCacheView",
    "RemoteReplica",
    "ReplicaServer",
    "ReplicaUnreachable",
    "Request",
    "RequestQueue",
    "Response",
    "ServingBuckets",
    "ServingConfig",
    "Supervisor",
    "create_engine",
]


def create_engine(model, **kwargs) -> Engine:
    """Build an :class:`Engine` with keyword config (the
    ``inference.create_predictor`` idiom for the serving surface)."""
    return Engine(model, ServingConfig(**kwargs) if kwargs else None)
