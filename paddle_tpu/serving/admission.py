"""SLO-aware admission control: predict, then shed — never hang.

The PR 7 engine refused admission only when the KV block pool could never
fit a request; everything else queued, unbounded, and a request could wait
(and hold host memory) forever. This module closes that gap with the
CheckFreq idiom the checkpoint cadence tuner established (PR 8): tune the
knob — here, *which requests to accept* — against **measured** costs, and
keep re-measuring so the policy tracks drift.

Costs come from the serving engine's own timings (the same samples that
feed the PR 9 ``serve_token_lat_ms`` histogram):

- per-bucket **prefill cost** EMA (one per prompt bucket — each bucket is
  its own compiled program with its own cost);
- per-row **decode token cost** EMA (decode-step ms divided by the live
  rows in the batch — continuous batching amortizes the step across rows);
- a **queue-wait trip wire**: waits are recorded both into the PR 9
  ``serve_queue_wait_ms`` streaming histogram (lifetime, for
  observability) and a bounded recent window whose p99 is the overload
  signal — storms age out of the window, so the trip wire recovers.

An incoming request's predicted completion time is

    backlog_ms(ahead of it) + prefill_ema[its bucket] + max_new * tok_ema

and admission sheds — a structured, *retriable* ``overloaded`` response,
never a silent queue-in-to-time-out — when:

1. the queue is at ``FLAGS_serving_queue_max`` (hard cap, both classes);
2. the queue-wait p99 exceeds ``FLAGS_serving_queue_wait_p99_ms``
   (trip wire — batch only: interactive rides through a storm);
3. the prediction misses the request's deadline (both classes; batch
   counts ALL queued work ahead of it while interactive counts only
   interactive, which is the other half of "batch sheds first").

Cold start admits optimistically: with no measured costs yet there is no
prediction, and the deadline enforcement in the engine (queue/prefill/
decode expiry) is the backstop.
"""
from __future__ import annotations

import time as _time
from collections import deque
from typing import List, Optional, Tuple

from ..core import flags

__all__ = ["AdmissionController", "ShedDecision"]

# EMA smoothing for the cost estimates — a handful of samples dominates,
# matching the checkpoint cadence tuner's drift-tracking discipline
_ALPHA = 0.25
# minimum queue-wait samples before the p99 trip wire may fire (a single
# slow wait must not flip the engine into shedding)
_TRIP_MIN_SAMPLES = 8
# the trip wire's p99 is computed over a RECENT window, not the lifetime
# histogram: a lifetime p99 would stay tripped long after a storm passed
# (and while tripped, shed batch traffic contributes no new samples to
# dilute it), so recovery would depend on unrelated interactive volume
_TRIP_WINDOW = 128
# samples also age out by WALL TIME: a batch-only workload that trips the
# wire stops admitting (and therefore stops sampling), so a count-bounded
# window alone would latch the trip forever — after this horizon with no
# fresh slow waits the wire stands down and batch traffic probes again
_TRIP_MAX_AGE_S = 30.0


class ShedDecision:
    """Why admission shed a request (reason is the counter label)."""

    __slots__ = ("reason", "detail")

    def __init__(self, reason: str, detail: str):
        self.reason = reason
        self.detail = detail

    def __repr__(self):
        return f"<ShedDecision {self.reason}: {self.detail}>"


class AdmissionController:
    """Measured-cost admission policy for one engine."""

    def __init__(self, engine_uid: int, bucket_of=None):
        from ..profiler import metrics as _metrics

        self._uid = str(engine_uid)
        # prompt length -> padded prompt bucket (the prefill-program key);
        # identity when the engine doesn't provide its bucket table
        self._bucket_of = bucket_of or (lambda n: int(n))
        self._prefill_ema = {}  # prompt bucket -> ms
        self._decode_tok_ema: Optional[float] = None  # ms per live row
        self._queue_wait = _metrics.default_registry().histogram(
            "serve_queue_wait_ms",
            doc="queue wait from submit to admission (prefill pop), ms",
            labels={"engine": self._uid},
        )
        # bounded recent-wait window for the trip wire (the registered
        # histogram above stays lifetime, for observability)
        self._recent_waits = deque(maxlen=_TRIP_WINDOW)
        # queue-wait EMA: the retry_after_ms hint on shed responses — how
        # long admitted work is currently waiting, i.e. roughly when a
        # retry would land in a shorter queue
        self._queue_wait_ema: Optional[float] = None

    # -- cost feedback (engine calls these with its measured timings) -----
    def note_prefill(self, bucket: int, ms: float):
        prev = self._prefill_ema.get(bucket)
        self._prefill_ema[bucket] = (
            ms if prev is None else prev + _ALPHA * (ms - prev))

    def note_decode(self, ms: float, rows: int):
        if rows < 1:
            return
        per_row = ms / rows
        prev = self._decode_tok_ema
        self._decode_tok_ema = (
            per_row if prev is None else prev + _ALPHA * (per_row - prev))

    def note_queue_wait(self, ms: float):
        self._queue_wait.observe(ms)
        self._recent_waits.append((_time.monotonic(), float(ms)))
        prev = self._queue_wait_ema
        self._queue_wait_ema = (
            float(ms) if prev is None else prev + _ALPHA * (ms - prev))

    def retry_after_ms(self) -> Optional[float]:
        """The hint shed ('overloaded') responses carry: the measured
        queue-wait EMA — what admitted work is waiting right now, so a
        retry after this long lands once the current backlog has drained a
        queue-slot's worth. None during cold start (no measured waits):
        the caller retries at its own cadence."""
        if self._queue_wait_ema is None:
            return None
        return round(max(1.0, self._queue_wait_ema), 3)

    # -- prediction -------------------------------------------------------
    def _prefill_cost(self, bucket: int) -> Optional[float]:
        c = self._prefill_ema.get(bucket)
        if c is not None:
            return c
        if self._prefill_ema:  # unseen bucket: borrow the known average
            return sum(self._prefill_ema.values()) / len(self._prefill_ema)
        return None

    def _request_cost_ms(self, bucket: int, max_new: int) -> Optional[float]:
        pre = self._prefill_cost(bucket)
        tok = self._decode_tok_ema
        if pre is None or tok is None:
            return None  # cold start: no prediction available
        return pre + max_new * tok

    def predict_completion_ms(self, *, bucket: int, max_new: int,
                              backlog: List[Tuple[Optional[int], int]],
                              ) -> Optional[float]:
        """Predicted ms until a request with (bucket, max_new) completes,
        given the work ahead of it as (prefill_bucket_or_None,
        remaining_tokens) items — None bucket means the prefill already
        ran (an in-flight sequence: only its decode tail remains).
        Returns None while costs are unmeasured (cold start admits)."""
        own = self._request_cost_ms(bucket, max_new)
        if own is None:
            return None
        total = own
        tok = self._decode_tok_ema or 0.0
        for b, remaining in backlog:
            pre = self._prefill_cost(b) if b is not None else 0.0
            total += (pre or 0.0) + max(0, remaining) * tok
        return total

    # -- the decision -----------------------------------------------------
    def queue_wait_p99(self) -> Optional[float]:
        """p99 of the RECENT queue waits (the trip-wire signal). Storms
        age out two ways: displaced by fresh samples (count window) or by
        wall time (_TRIP_MAX_AGE_S) — the latter matters when tripping
        itself stops the sampling (batch-only traffic shed pre-queue
        would otherwise freeze the window and latch the trip forever)."""
        horizon = _time.monotonic() - _TRIP_MAX_AGE_S
        while self._recent_waits and self._recent_waits[0][0] < horizon:
            self._recent_waits.popleft()
        waits = sorted(ms for _, ms in self._recent_waits)
        if len(waits) < _TRIP_MIN_SAMPLES:
            return None
        i = min(len(waits) - 1, int(0.99 * (len(waits) - 1) + 0.5))
        return waits[i]

    def decide(self, req, *, queue, active, now: float):
        """None to admit, or a :class:`ShedDecision`. ``queue`` is the
        engine's RequestQueue, ``active`` its in-flight Sequence list."""
        cap = int(flags.flag("serving_queue_max"))
        if cap > 0 and len(queue) >= cap:
            return ShedDecision(
                "queue_full",
                f"queue at FLAGS_serving_queue_max={cap}")
        trip_ms = float(flags.flag("serving_queue_wait_p99_ms"))
        if trip_ms > 0 and req.priority == "batch":
            p99 = self.queue_wait_p99()
            if p99 is not None and p99 > trip_ms:
                return ShedDecision(
                    "queue_p99",
                    f"queue-wait p99 {p99:.1f} ms > trip wire "
                    f"{trip_ms:.1f} ms — batch sheds first")
        remaining = req.remaining_ms(now)
        if remaining is None:
            return None  # no deadline, nothing to predict against
        backlog: List[Tuple[Optional[int], int]] = [
            (None, s.req.max_new_tokens - len(s.tokens)) for s in active]
        # interactive jumps the batch queue, so only interactive work is
        # ahead of it; batch waits behind everything
        ahead = (queue.iter_priority("interactive")
                 if req.priority == "interactive" else iter(queue))
        for q in ahead:
            backlog.append((self._bucket_of(int(q.prompt.size)),
                            q.max_new_tokens))
        predicted = self.predict_completion_ms(
            bucket=self._bucket_of(int(req.prompt.size)),
            max_new=req.max_new_tokens, backlog=backlog)
        if predicted is not None and predicted > remaining:
            return ShedDecision(
                "predicted_deadline_miss",
                f"predicted completion {predicted:.1f} ms > remaining "
                f"deadline {remaining:.1f} ms")
        return None

    def state(self) -> dict:
        """Snapshot for Engine.stats() / postmortems. ``queue_wait_p99_ms``
        is the recent-window value admission actually acts on; the
        lifetime distribution lives in the serve_queue_wait_ms
        histogram."""
        p99 = self.queue_wait_p99()
        return {
            "prefill_ema_ms": {k: round(v, 3)
                               for k, v in sorted(self._prefill_ema.items())},
            "decode_tok_ema_ms": (
                None if self._decode_tok_ema is None
                else round(self._decode_tok_ema, 4)),
            "queue_wait_p99_ms": None if p99 is None else round(p99, 3),
            "queue_wait_ema_ms": (
                None if self._queue_wait_ema is None
                else round(self._queue_wait_ema, 3)),
            "queue_wait_samples": self._queue_wait.count,
        }

    def close(self):
        from ..profiler import metrics as _metrics

        _metrics.default_registry().remove(
            "serve_queue_wait_ms", labels={"engine": self._uid})
