"""Request queue + shape-bucketed continuous-batching scheduler state.

Under XLA every distinct shape is a compilation, so the scheduler's whole
job is to funnel arbitrary traffic into a SMALL set of program signatures
(the ``io/bucketing.py`` padding-policy idiom, applied twice):

  - prompts pad up to a prompt-length bucket → one cached prefill program
    per (prompt bucket, context bucket);
  - each decode step pads its active-sequence batch up to a batch-size
    bucket → one captured decode program per (batch bucket, context
    bucket), idle rows pointed at per-slot scratch blocks.

Admission is planner-budgeted: a request whose context chain can never fit
the block pool is REJECTED up front (``CacheOverflow`` → an error response,
not a dead engine), and a request that merely has to wait for free blocks
queues — continuous batching refills decode slots as sequences complete.
"""
from __future__ import annotations

import itertools
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from ..core import flags
from ..io.bucketing import BucketSpec

__all__ = ["Request", "Response", "RequestQueue", "ServingBuckets"]

_REQUEST_IDS = itertools.count(1)


@dataclass
class Request:
    """One generation request: a prompt and its decode limits."""

    prompt: np.ndarray
    max_new_tokens: int
    eos_token_id: Optional[int] = None
    request_id: int = field(default_factory=lambda: next(_REQUEST_IDS))
    submit_time: float = field(default_factory=time.time)
    # times the engine has torn this request down and re-enqueued it after
    # a non-recoverable fault (bounded by FLAGS_serving_request_retries)
    retries: int = 0

    def __post_init__(self):
        self.prompt = np.asarray(self.prompt, np.int64).reshape(-1)
        if self.prompt.size == 0:
            raise ValueError("empty prompt")
        self.max_new_tokens = int(self.max_new_tokens)
        if self.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")


@dataclass
class Response:
    """The engine's answer. ``status`` is one of:

    - ``"ok"``        every requested token generated (or EOS hit)
    - ``"rejected"``  refused at admission (budget overflow / draining)
    - ``"error"``     accepted but failed after the retry budget

    A request is NEVER silently dropped: every submitted request gets
    exactly one Response (the chaos serve gate fails otherwise)."""

    request_id: int
    status: str
    tokens: List[int] = field(default_factory=list)
    error: Optional[str] = None
    prompt_len: int = 0
    # wall-clock timing (seconds since epoch): submit → first token → done
    submit_time: float = 0.0
    first_token_time: Optional[float] = None
    done_time: Optional[float] = None
    # per-generated-token logits rows ([vocab] float arrays) when the
    # engine runs with keep_logits=True (parity tests / debugging)
    logits: Optional[List[np.ndarray]] = None

    @property
    def ok(self) -> bool:
        return self.status == "ok"

    @property
    def ttft_ms(self) -> Optional[float]:
        if self.first_token_time is None:
            return None
        return (self.first_token_time - self.submit_time) * 1000.0

    @property
    def latency_ms(self) -> Optional[float]:
        if self.done_time is None:
            return None
        return (self.done_time - self.submit_time) * 1000.0


class RequestQueue:
    """FIFO admission queue. Single-threaded engines drive it directly;
    ``submit`` is safe to call from a signal handler (deque.append is
    atomic)."""

    def __init__(self):
        self._q: deque = deque()

    def push(self, req: Request):
        self._q.append(req)

    def push_front(self, req: Request):
        self._q.appendleft(req)

    def peek(self) -> Optional[Request]:
        return self._q[0] if self._q else None

    def pop(self) -> Optional[Request]:
        return self._q.popleft() if self._q else None

    def __len__(self):
        return len(self._q)

    def __bool__(self):
        return bool(self._q)


def _validate_buckets(out: List[int], origin) -> List[int]:
    if not out or sorted(out) != out or any(b <= 0 for b in out):
        raise ValueError(
            f"bucket list {origin!r} must be ascending positive ints")
    return out


def _parse_buckets(text: str) -> List[int]:
    out = [int(t) for t in str(text).split(",") if t.strip()]
    return _validate_buckets(out, text)


class ServingBuckets:
    """Both bucket tables plus the context arithmetic, validated against the
    block size once at engine construction."""

    def __init__(self, *, block_size: int,
                 prompt_buckets: Optional[List[int]] = None,
                 decode_batch_buckets: Optional[List[int]] = None):
        self.block_size = int(block_size)
        pb = (_validate_buckets([int(b) for b in prompt_buckets],
                                prompt_buckets)
              if prompt_buckets is not None
              else _parse_buckets(flags.flag("serving_prompt_buckets")))
        for b in pb:
            if b % self.block_size != 0:
                raise ValueError(
                    f"prompt bucket {b} is not a multiple of "
                    f"FLAGS_serving_block_size={self.block_size}"
                )
        # BucketSpec gives the rounding rule AND the recompile-budget
        # warning (each distinct padded shape is one compiled prefill)
        self.prompt_spec = BucketSpec(boundaries=pb, axis=-1, pad_value=0)
        db = (_validate_buckets([int(b) for b in decode_batch_buckets],
                                decode_batch_buckets)
              if decode_batch_buckets is not None
              else _parse_buckets(flags.flag("serving_decode_batch_buckets")))
        self.decode_batch_buckets = db

    @property
    def max_decode_batch(self) -> int:
        return self.decode_batch_buckets[-1]

    def prompt_bucket(self, length: int) -> int:
        return self.prompt_spec.bucket_for(int(length))

    def batch_bucket(self, n: int) -> int:
        for b in self.decode_batch_buckets:
            if n <= b:
                return b
        return self.decode_batch_buckets[-1]

    def ctx_blocks(self, prompt_len: int, max_new: int) -> int:
        """Logical blocks a sequence needs for its whole life: the padded
        prompt plus every token it may generate, rounded up to blocks."""
        ctx = self.prompt_bucket(prompt_len) + int(max_new)
        return -(-ctx // self.block_size)

    def pad_prompt(self, prompt: np.ndarray) -> np.ndarray:
        return self.prompt_spec.pad(np.asarray(prompt, np.int64))


class Sequence:
    """One admitted, in-flight generation."""

    __slots__ = ("req", "blocks", "n_blk", "length", "tokens", "last_token",
                 "logits")

    def __init__(self, req: Request, blocks: List[int], n_blk: int):
        self.req = req
        self.blocks = blocks
        self.n_blk = int(n_blk)
        self.length = 0          # tokens currently cached (post-prefill)
        self.tokens: List[int] = []
        self.last_token: int = 0
        self.logits: List[np.ndarray] = []

    @property
    def done(self) -> bool:
        if len(self.tokens) >= self.req.max_new_tokens:
            return True
        eos = self.req.eos_token_id
        return eos is not None and bool(self.tokens) and self.tokens[-1] == eos

    def table_row(self) -> List[int]:
        return list(self.blocks)


def group_for_decode(active: List[Sequence]) -> Dict[int, List[Sequence]]:
    """Continuous batching: bucket the active set by context width (table
    shape) — each group decodes as one padded batch per step."""
    groups: Dict[int, List[Sequence]] = {}
    for s in active:
        groups.setdefault(s.n_blk, []).append(s)
    return groups
