"""Request queue + shape-bucketed continuous-batching scheduler state.

Under XLA every distinct shape is a compilation, so the scheduler's whole
job is to funnel arbitrary traffic into a SMALL set of program signatures
(the ``io/bucketing.py`` padding-policy idiom, applied twice):

  - prompts pad up to a prompt-length bucket → one cached prefill program
    per (prompt bucket, context bucket);
  - each decode step pads its active-sequence batch up to a batch-size
    bucket → one captured decode program per (batch bucket, context
    bucket), idle rows pointed at per-slot scratch blocks.

Admission is planner-budgeted: a request whose context chain can never fit
the block pool is REJECTED up front (``CacheOverflow`` → an error response,
not a dead engine), and a request that merely has to wait for free blocks
queues — continuous batching refills decode slots as sequences complete.
"""
from __future__ import annotations

import itertools
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from ..core import flags
from ..io.bucketing import BucketSpec

__all__ = ["PRIORITIES", "Request", "Response", "RequestQueue",
           "ServingBuckets"]

_REQUEST_IDS = itertools.count(1)


PRIORITIES = ("interactive", "batch")


@dataclass
class Request:
    """One generation request: a prompt, its decode limits, and its SLO
    (deadline + priority class)."""

    prompt: np.ndarray
    max_new_tokens: int
    eos_token_id: Optional[int] = None
    request_id: int = field(default_factory=lambda: next(_REQUEST_IDS))
    submit_time: float = field(default_factory=time.time)
    # times the engine has torn this request down and re-enqueued it after
    # a non-recoverable fault (bounded by FLAGS_serving_request_retries)
    retries: int = 0
    # SLO: wall-clock deadline in ms from submit (None = inherit
    # FLAGS_serving_default_deadline_ms at admission; 0/None after that =
    # no deadline), and the priority class — 'interactive' admits and pops
    # ahead of 'batch', and 'batch' sheds first under overload
    deadline_ms: Optional[float] = None
    priority: str = "interactive"

    def __post_init__(self):
        self.prompt = np.asarray(self.prompt, np.int64).reshape(-1)
        if self.prompt.size == 0:
            raise ValueError("empty prompt")
        self.max_new_tokens = int(self.max_new_tokens)
        if self.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if self.priority not in PRIORITIES:
            raise ValueError(
                f"priority must be one of {PRIORITIES}, got "
                f"{self.priority!r}")
        if self.deadline_ms is not None:
            self.deadline_ms = float(self.deadline_ms)
            if self.deadline_ms < 0:
                raise ValueError(
                    "deadline_ms must be >= 0 (0/None = no deadline)")
            if self.deadline_ms == 0:
                # the documented opt-out: an explicit 0 means NO deadline —
                # it is the only way to override a configured
                # FLAGS_serving_default_deadline_ms (None inherits it)
                self.deadline_ms = None

    @property
    def deadline_time(self) -> Optional[float]:
        """Absolute wall-clock deadline (seconds since epoch), or None."""
        if self.deadline_ms is None:
            return None
        return self.submit_time + self.deadline_ms / 1000.0

    def expired(self, now: float) -> bool:
        dl = self.deadline_time
        return dl is not None and now >= dl

    def remaining_ms(self, now: float) -> Optional[float]:
        dl = self.deadline_time
        return None if dl is None else (dl - now) * 1000.0


@dataclass
class Response:
    """The engine's answer. ``status`` is one of:

    - ``"ok"``          every requested token generated (or EOS hit)
    - ``"rejected"``    refused at admission (budget overflow / draining)
    - ``"overloaded"``  shed by SLO-aware admission (queue cap, queue-wait
                        p99 trip wire, or a predicted deadline miss) —
                        structured and ``retriable``: resubmit later
    - ``"timeout"``     the request's deadline passed; ``tokens`` carries
                        the partial output when the expiry was mid-decode
                        and FLAGS_serving_deadline_partial is on
    - ``"error"``       accepted but failed after the retry budget

    A request is NEVER silently dropped: every submitted request gets
    exactly one terminal Response (the chaos serve gate fails otherwise)."""

    request_id: int
    status: str
    tokens: List[int] = field(default_factory=list)
    error: Optional[str] = None
    # True for load-shedding responses ('overloaded'): the request itself
    # was fine, the engine was not — resubmitting later can succeed
    retriable: bool = False
    prompt_len: int = 0
    # wall-clock timing (seconds since epoch): submit → first token → done
    submit_time: float = 0.0
    first_token_time: Optional[float] = None
    done_time: Optional[float] = None
    # per-generated-token logits rows ([vocab] float arrays) when the
    # engine runs with keep_logits=True (parity tests / debugging)
    logits: Optional[List[np.ndarray]] = None
    # for 'overloaded' (shed) responses: how long the admission controller
    # estimates the caller (or the FrontDoor re-dispatching to a sibling)
    # should wait before retrying, from the measured queue-wait EMA; None
    # when the controller has no measured waits yet
    retry_after_ms: Optional[float] = None

    @property
    def ok(self) -> bool:
        return self.status == "ok"

    @property
    def ttft_ms(self) -> Optional[float]:
        if self.first_token_time is None:
            return None
        return (self.first_token_time - self.submit_time) * 1000.0

    @property
    def latency_ms(self) -> Optional[float]:
        if self.done_time is None:
            return None
        return (self.done_time - self.submit_time) * 1000.0


class RequestQueue:
    """Two-class admission queue: FIFO within a priority class, and
    ``interactive`` always pops ahead of ``batch`` — so batch traffic can
    never starve interactive under a storm (the shed policy is the other
    half: batch sheds first). Single-threaded engines drive it directly;
    per-class ``submit`` is safe to call from a signal handler
    (deque.append is atomic).

    The queue itself is pure mechanism — the CAP (FLAGS_serving_queue_max)
    is enforced by the engine's admission path, which must answer the
    over-cap request with a structured 'overloaded' response rather than
    silently refuse."""

    def __init__(self):
        self._qs: Dict[str, deque] = {"interactive": deque(),
                                      "batch": deque()}

    def push(self, req: Request):
        self._qs[req.priority].append(req)

    def push_front(self, req: Request):
        self._qs[req.priority].appendleft(req)

    def peek(self) -> Optional[Request]:
        for p in PRIORITIES:
            if self._qs[p]:
                return self._qs[p][0]
        return None

    def pop(self) -> Optional[Request]:
        for p in PRIORITIES:
            if self._qs[p]:
                return self._qs[p].popleft()
        return None

    def iter_priority(self, priority: str):
        """Queued requests of one class, pop order."""
        return iter(list(self._qs[priority]))

    def take_expired(self, now: float) -> List[Request]:
        """Remove and return every queued request whose deadline has
        passed — expired work must answer 'timeout' instead of wasting a
        prefill (and the blocks behind it)."""
        out: List[Request] = []
        for p in PRIORITIES:
            q = self._qs[p]
            # scan a snapshot, delete by IDENTITY: deque.remove would go
            # through Request's dataclass == (ambiguous ndarray truth
            # value), and a rotation would scramble FIFO order against a
            # concurrent signal-handler push. The common case (no
            # deadlines configured) never mutates the deque at all.
            for r in list(q):
                if not r.expired(now):
                    continue
                # indexed access, not an iterator: a concurrent
                # signal-handler append must not raise 'deque mutated
                # during iteration' out of the engine tick
                for i in range(len(q)):
                    try:
                        if q[i] is r:
                            del q[i]
                            out.append(r)
                            break
                    except IndexError:
                        break  # raced with a concurrent pop
        return out

    def __iter__(self):
        for p in PRIORITIES:
            yield from list(self._qs[p])

    def __len__(self):
        return sum(len(q) for q in self._qs.values())

    def __bool__(self):
        return any(self._qs.values())


def _validate_buckets(out: List[int], origin) -> List[int]:
    if not out or sorted(out) != out or any(b <= 0 for b in out):
        raise ValueError(
            f"bucket list {origin!r} must be ascending positive ints")
    return out


def _parse_buckets(text: str) -> List[int]:
    out = [int(t) for t in str(text).split(",") if t.strip()]
    return _validate_buckets(out, text)


class ServingBuckets:
    """Both bucket tables plus the context arithmetic, validated against the
    block size once at engine construction."""

    def __init__(self, *, block_size: int,
                 prompt_buckets: Optional[List[int]] = None,
                 decode_batch_buckets: Optional[List[int]] = None):
        self.block_size = int(block_size)
        pb = (_validate_buckets([int(b) for b in prompt_buckets],
                                prompt_buckets)
              if prompt_buckets is not None
              else _parse_buckets(flags.flag("serving_prompt_buckets")))
        for b in pb:
            if b % self.block_size != 0:
                raise ValueError(
                    f"prompt bucket {b} is not a multiple of "
                    f"FLAGS_serving_block_size={self.block_size}"
                )
        # BucketSpec gives the rounding rule AND the recompile-budget
        # warning (each distinct padded shape is one compiled prefill)
        self.prompt_spec = BucketSpec(boundaries=pb, axis=-1, pad_value=0)
        db = (_validate_buckets([int(b) for b in decode_batch_buckets],
                                decode_batch_buckets)
              if decode_batch_buckets is not None
              else _parse_buckets(flags.flag("serving_decode_batch_buckets")))
        self.decode_batch_buckets = db

    @property
    def max_decode_batch(self) -> int:
        return self.decode_batch_buckets[-1]

    def prompt_bucket(self, length: int) -> int:
        return self.prompt_spec.bucket_for(int(length))

    def batch_bucket(self, n: int) -> int:
        for b in self.decode_batch_buckets:
            if n <= b:
                return b
        return self.decode_batch_buckets[-1]

    def ctx_blocks(self, prompt_len: int, max_new: int) -> int:
        """Logical blocks a sequence needs for its whole life: the padded
        prompt plus every token it may generate, rounded up to blocks."""
        ctx = self.prompt_bucket(prompt_len) + int(max_new)
        return -(-ctx // self.block_size)

    def pad_prompt(self, prompt: np.ndarray) -> np.ndarray:
        return self.prompt_spec.pad(np.asarray(prompt, np.int64))


class Sequence:
    """One admitted, in-flight generation."""

    __slots__ = ("req", "blocks", "n_blk", "length", "tokens", "last_token",
                 "logits")

    def __init__(self, req: Request, blocks: List[int], n_blk: int):
        self.req = req
        self.blocks = blocks
        self.n_blk = int(n_blk)
        self.length = 0          # tokens currently cached (post-prefill)
        self.tokens: List[int] = []
        self.last_token: int = 0
        self.logits: List[np.ndarray] = []

    @property
    def done(self) -> bool:
        if len(self.tokens) >= self.req.max_new_tokens:
            return True
        eos = self.req.eos_token_id
        return eos is not None and bool(self.tokens) and self.tokens[-1] == eos

    def table_row(self) -> List[int]:
        return list(self.blocks)


def group_for_decode(active: List[Sequence]) -> Dict[int, List[Sequence]]:
    """Continuous batching: bucket the active set by context width (table
    shape) — each group decodes as one padded batch per step."""
    groups: Dict[int, List[Sequence]] = {}
    for s in active:
        groups.setdefault(s.n_blk, []).append(s)
    return groups
