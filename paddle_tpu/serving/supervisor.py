"""Self-healing engine supervision: restart the engine, not the fleet.

The PR 7 engine recovers from faults *inside* a tick (the resilience
ladder retries, demotes, requeues), but a tick exception that escapes the
ladder — a scheduler bug, a poisoned captured program, a device wedge —
used to propagate to whoever was driving the loop and take every queued
request with it. The :class:`Supervisor` is the layer above: it drives the
serve loop, consumes the two wedge signals, and restarts the engine in
place.

Signals:

- **tick exceptions** — any ``Exception`` escaping ``Engine.step()``
  (``Preempted``/``KeyboardInterrupt``/``SystemExit`` pass through: those
  are control flow, not faults);
- **the PR 9 step-stall watchdog** — ``FLAGS_trace_stall_ms`` > 0 starts
  the trace-module watchdog; the supervisor registers a stall listener,
  and a tick that trips it (no heartbeat inside the threshold) is treated
  as a wedge once control returns.

A restart (``Engine.restart``) evicts the engine's captured programs,
rebuilds the pool, and re-enqueues in-flight sequences through the
existing requeue path — greedy decode is deterministic, so the re-run
reproduces **bitwise-identical tokens**. Restarts are bounded by
``FLAGS_serving_max_engine_restarts``; past the budget the supervisor
fails *cleanly* (``Engine.fail_clean``): every queued and in-flight
request gets a terminal error response, the engine goes ``dead``, and a
postmortem is dumped — zero hangs, zero silent drops.
"""
from __future__ import annotations

from typing import List, Optional, Sequence as Seq

from ..core import flags

__all__ = ["Supervisor"]


class Supervisor:
    """Drives one :class:`~paddle_tpu.serving.Engine`'s serve loop with
    wedge detection and bounded self-healing restarts.

        sup = paddle.serving.Supervisor(engine)
        rids = [engine.submit(p, deadline_ms=500) for p in prompts]
        sup.run_until_idle()          # restarts the engine if it wedges

    ``max_restarts=None`` reads FLAGS_serving_max_engine_restarts live.

    The stall watchdog's heartbeat is process-global (every engine tick
    and training step feeds it), so stall trips are only attributed to
    this supervisor's engine while one of ITS ticks is in flight, and
    ``run_until_idle`` disarms the watchdog when it goes idle — run one
    supervised serve loop at a time per process for stall detection
    (tick-exception wedge recovery is always per-engine regardless).
    """

    def __init__(self, engine, max_restarts: Optional[int] = None):
        import weakref

        from ..profiler import trace as _trace

        self._engine = engine
        self._max_restarts = max_restarts
        self._restarts = 0
        self._stalled_ms: Optional[float] = None
        self._in_tick = False
        # the listener holds only a WEAK reference to this supervisor: the
        # global listener registry must not pin the supervisor (and through
        # it the engine, the model, and the pool's K/V arrays) alive when a
        # caller drops the supervisor without close() — the dead closures
        # leak class the serving engine's own close() exists to prevent.
        # A trip after collection removes the stale closure itself.
        ref = weakref.ref(self)

        def _listener(stalled_ms, _ref=ref):
            sup = _ref()
            if sup is None:
                _trace.remove_stall_listener(_listener)
                return
            sup._note_stall(stalled_ms)

        self._listener = _listener  # stable identity for remove
        _trace.add_stall_listener(self._listener)

    # -- stall-watchdog plumbing ----------------------------------------
    def _note_stall(self, stalled_ms: float):
        # called from the watchdog daemon thread; consumed at the next
        # tick boundary on the driving thread. The watchdog heartbeat is
        # process-global, so only latch trips that fired while OUR engine
        # was mid-tick — another engine's (or a training loop's) stall
        # must not restart a healthy engine and burn its requests'
        # requeue budgets
        if self._in_tick:
            self._stalled_ms = stalled_ms

    def _take_stall(self) -> Optional[float]:
        ms, self._stalled_ms = self._stalled_ms, None
        return ms

    def close(self):
        from ..profiler import trace as _trace

        _trace.remove_stall_listener(self._listener)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- supervision ----------------------------------------------------
    @property
    def restarts(self) -> int:
        return self._restarts

    def _budget(self) -> int:
        if self._max_restarts is not None:
            return int(self._max_restarts)
        return int(flags.flag("serving_max_engine_restarts"))

    def _recover(self, err: BaseException):
        self._restarts += 1
        if self._restarts > self._budget():
            self._engine.fail_clean(err)
            return
        self._engine.restart(err)

    @staticmethod
    def _progress_marker() -> float:
        """Cheap observable-progress sum: a tick that prefilled, decoded,
        completed, or expired anything was slow, not wedged."""
        from ..core import dispatch

        c = dispatch._counters
        return (c["serve_prefills"] + c["serve_decode_steps"]
                + c["serve_requests_completed"]
                + c["serve_deadline_expired"])

    def step(self):
        """One supervised tick: run ``Engine.step()``, convert a wedge
        into an engine restart. A wedge is an exception escaping the tick,
        or a stall-watchdog trip during a tick that made NO observable
        progress — a slow-but-productive tick (first-serve XLA compiles
        routinely exceed FLAGS_trace_stall_ms) must not trigger a restart
        that evicts the very programs it just built."""
        from ..core import dispatch

        self._take_stall()  # stalls from BEFORE this tick aren't its fault
        before = self._progress_marker()
        self._in_tick = True
        try:
            self._engine.step()
        except Exception as e:
            # Preempted (a SystemExit subclass) propagates past this
            # handler on its own — a preemption drain is control flow,
            # not a wedge, and must not burn the restart budget
            self._recover(e)
            return
        finally:
            self._in_tick = False
        stalled = self._take_stall()
        if stalled is not None:
            if self._progress_marker() > before:
                dispatch._emit("serve", site="supervisor",
                               phase="stall_benign",
                               stalled_ms=round(stalled, 1))
                return  # slow tick, real work done — not a wedge
            self._recover(TimeoutError(
                f"step-stall watchdog fired mid-tick with no progress "
                f"({stalled:.0f} ms > FLAGS_trace_stall_ms)"))

    def run_until_idle(self):
        """Drive the supervised loop until every accepted request has a
        terminal response — including through restarts, and including the
        fail-clean path (a dead engine has already answered everything)."""
        from ..profiler import trace as _trace

        eng = self._engine
        try:
            while eng.pending and eng.health != "dead":
                self.step()
            eng._audit_drops()
        finally:
            # an idle serving loop looks exactly like a stalled one to the
            # watchdog — stand THIS engine's source down (the
            # train_step_range discipline); a co-resident training loop or
            # sibling engine stays armed
            _trace.watchdog_disarm(f"serve[{eng._uid}]")

    def serve(self, requests: Seq, **submit_kw) -> List:
        """Submit every prompt, run supervised to completion, return (and
        evict) the responses in submit order."""
        ids = [self._engine.submit(p, **submit_kw) for p in requests]
        self.run_until_idle()
        return [self._engine.pop_response(i) for i in ids]

    def state(self) -> dict:
        return {
            "restarts": self._restarts,
            "budget": self._budget(),
            "engine_health": self._engine.health,
            "last_restart_error": self._engine._last_restart_error,
        }
