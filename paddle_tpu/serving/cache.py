"""Paged KV cache: a planner-budgeted block pool + per-layer batch views.

The vLLM PagedAttention idiom applied to this framework's fixed-shape
decode: instead of one private ``[b, max_seq_len, h, d]`` K/V buffer per
sequence (``models/gpt.py`` dict caches), every sequence's context is a
chain of fixed-size blocks drawn from ONE shared pool per layer. HBM is
bounded by the pool — which the PR 4 memory planner sizes up front against
``FLAGS_memory_budget_mb`` (``analysis.memory.plan_block_pool``) — and the
scheduler refuses admission when no blocks are free instead of letting XLA
OOM mid-decode. Completed sequences recycle their blocks without
recompiling anything: the decode program is a function of the block TABLE,
not of which physical blocks a sequence happens to own.

The attention math itself lives in ``ops/nn_ops.py paged_decode_attention``
and is line-identical to ``cached_attention``'s einsum/mask/softmax chain,
so paged decode is bitwise-equal to the fixed-shape cache path over the
same context length (tests/test_serving.py asserts this).
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from ..core import flags

__all__ = ["BlockPool", "PagedCacheView"]


class BlockPool:
    """The shared K/V block storage plus its free-list.

    One *logical* block spans every layer: ``alloc`` hands out physical ids
    valid across all ``layers`` pool arrays, so a sequence's block table is
    layer-independent (the vLLM layout). Ids ``0..scratch_slots-1`` are
    reserved scratch blocks — one per decode-batch slot — that padded batch
    rows write into, each slot its own block so no two rows ever scatter
    into the same physical block.
    """

    def __init__(self, *, layers: int, heads: int, head_dim: int,
                 block_size: int, num_blocks: int, scratch_slots: int,
                 dtype: str = "float32"):
        if num_blocks < 1:
            raise ValueError(
                f"BlockPool needs at least 1 allocatable block, got "
                f"{num_blocks} — raise FLAGS_memory_budget_mb or "
                "FLAGS_serving_num_blocks"
            )
        self.layers = int(layers)
        self.block_size = int(block_size)
        self.scratch_slots = int(scratch_slots)
        self._num_blocks = int(num_blocks)
        total = self._num_blocks + self.scratch_slots
        shape = (total, self.block_size, int(heads), int(head_dim))
        self.dtype = np.dtype(dtype)
        # raw jax arrays (not Tensors): the decode program takes and returns
        # them wholesale, donated in place under the captured tier
        self.k: List = [jnp.zeros(shape, self.dtype) for _ in range(layers)]
        self.v: List = [jnp.zeros(shape, self.dtype) for _ in range(layers)]
        self._free = list(range(self.scratch_slots, total))
        self._peak_used = 0

    # -- bookkeeping --------------------------------------------------------
    @property
    def num_blocks(self) -> int:
        """Allocatable blocks (excluding scratch)."""
        return self._num_blocks

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        return self._num_blocks - len(self._free)

    def occupancy(self) -> float:
        return self.used_blocks / max(1, self._num_blocks)

    @property
    def peak_occupancy(self) -> float:
        return self._peak_used / max(1, self._num_blocks)

    def block_bytes(self) -> int:
        """Bytes of ONE logical block across all layers (K and V)."""
        head_shape = self.k[0].shape[2:]
        per_layer = self.block_size * int(np.prod(head_shape)) * self.dtype.itemsize
        return 2 * self.layers * per_layer

    # -- alloc/free ---------------------------------------------------------
    def alloc(self, n: int) -> Optional[List[int]]:
        """n physical block ids, or None when the pool is momentarily full
        (backpressure — the scheduler waits for a completion). A request
        that could NEVER fit raises CacheOverflow — the request-level
        reject, not an OOM."""
        from ..models.gpt import CacheOverflow  # deferred: import-cycle safe

        if n > self._num_blocks:
            raise CacheOverflow(
                n, self._num_blocks,
                detail="blocks needed exceed the planner-budgeted pool",
            )
        if n > len(self._free):
            return None
        ids, self._free = self._free[:n], self._free[n:]
        self._peak_used = max(self._peak_used, self.used_blocks)
        return ids

    def free(self, ids: Sequence[int]):
        for i in ids:
            if i < self.scratch_slots:
                raise ValueError(f"block {i} is a reserved scratch slot")
        self._free.extend(int(i) for i in ids)

    def reset_storage(self):
        """Fresh zeroed pool arrays (same shapes/free-list untouched) — the
        conservative recovery after a REAL fault mid-decode on the donated
        tier, when the consumed pool buffers can no longer be trusted."""
        shape, dt = self.k[0].shape, self.k[0].dtype
        self.k = [jnp.zeros(shape, dt) for _ in range(self.layers)]
        self.v = [jnp.zeros(shape, dt) for _ in range(self.layers)]

    def reclaim_all(self) -> int:
        """Rebuild the free-list as if nothing were allocated; returns how
        many blocks were still outstanding. This is the repair half of the
        pool-leak tripwire: at engine idle (no active sequences) every
        block must be free — a nonzero return is an engine bug
        (``serve_block_leaks``), and reclaiming keeps the pool serviceable
        instead of slowly starving admission."""
        leaked = self.used_blocks
        total = self._num_blocks + self.scratch_slots
        self._free = list(range(self.scratch_slots, total))
        return leaked


class _BatchState:
    """Per-forward holder threading the pool arrays through the layer stack:
    each layer's view reads its pool entry and writes back the updated one,
    so after the forward the state holds the post-step pools."""

    __slots__ = ("k_pools", "v_pools", "tables", "lens", "prefill")

    def __init__(self, k_pools, v_pools, tables, lens, prefill: bool):
        self.k_pools = list(k_pools)
        self.v_pools = list(v_pools)
        self.tables = tables
        self.lens = lens
        self.prefill = prefill


class PagedCacheView:
    """What ``GPTAttention.forward`` sees as its ``cache``: a per-layer
    handle onto the shared :class:`_BatchState`. ``append_attend`` writes
    this chunk's K/V into the pool at each row's next positions and attends
    over the gathered block view — one fused op
    (``ops.nn_ops.paged_decode_attention``) dispatched through the normal
    per-op path, so it works identically per-op eager, under lazy dispatch,
    and inside a decode-mode capture trace."""

    __slots__ = ("_state", "layer", "block_size")

    def __init__(self, state: _BatchState, layer: int, block_size: int):
        self._state = state
        self.layer = int(layer)
        self.block_size = int(block_size)

    def append_attend(self, q, k, v, *, scale):
        from ..core.dispatch import apply as _apply
        from ..ops import nn_ops as _ops

        st = self._state
        out, nk, nv = _apply(
            _ops.paged_decode_attention, q,
            st.k_pools[self.layer], st.v_pools[self.layer],
            st.tables, st.lens, k, v,
            scale=scale, block_size=self.block_size, prefill=st.prefill,
            op_name="paged_decode_attention",
        )
        st.k_pools[self.layer] = nk
        st.v_pools[self.layer] = nv
        return out


def default_num_blocks() -> int:
    """Pool size when neither FLAGS_serving_num_blocks nor any memory budget
    is configured."""
    n = int(flags.flag("serving_num_blocks"))
    return n if n > 0 else 256
