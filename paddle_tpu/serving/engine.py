"""The serving engine: continuous batching over a paged, planner-budgeted
KV cache, with prefill and decode captured as single donated XLA programs.

One ``Engine`` owns one model and runs a simple synchronous loop:

    admit (queue → blocks → prefill)  →  decode every active group once
    →  recycle completed sequences' blocks  →  repeat

Every program launch goes through three execution tiers, the serving
instance of the resilience ladder (captured → lazy → per-op):

  captured   ``jit(step_fn, donate_argnums=pools)`` — ONE donated program
             per bucket signature (decode-mode capture, ``core/lazy.py``),
             pool buffers updated in place;
  lazy       the same jitted program WITHOUT donation — the retry-safe
             middle rung (inputs retained, so a transient fault replays);
  per-op     the same Python function eagerly — the ladder floor, each op
             individually retried by the per-op resilience site.

All three tiers run the SAME function over the SAME buffers, so numerics
never change across rungs — a mid-decode fault demotes the bucket's
program and the batch retries without dropping a request. Injected faults
(FLAGS_fault_inject) raise before the program runs, so the fallback rungs
reuse the intact pool; a REAL fault on the donated rung conservatively
resets the pool and re-enqueues every in-flight sequence (greedy decode is
deterministic, so re-runs reproduce the same tokens).

Overload robustness (ISSUE 11) wraps that loop in three layers:

  deadlines   every request may carry ``deadline_ms``; expiry is enforced
              in queue (before wasting a prefill), at the admit pop, and
              mid-decode (partial 'timeout' response per
              FLAGS_serving_deadline_partial) — expired sequences recycle
              their blocks and leave the decode group without perturbing
              other rows;
  admission   the SLO-aware controller (serving/admission.py) predicts a
              request's completion from measured prefill/decode cost EMAs
              and sheds predicted deadline misses, over-cap submits
              (FLAGS_serving_queue_max), and — batch class first — storm
              arrivals past the queue-wait p99 trip wire, always with a
              structured retriable 'overloaded' response;
  health      the engine exposes warming/ready/degraded/draining/dead
              (``Engine.health``) so a Supervisor (serving/supervisor.py)
              and the inference PredictorPool can route traffic around an
              unhealthy replica, restart a wedged engine, or fail cleanly.
"""
from __future__ import annotations

import itertools
import signal as _signal
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence as Seq

import jax
import jax.numpy as jnp
import numpy as np

from ..core import flags
from ..core.dispatch import no_grad
from .admission import AdmissionController
from .cache import BlockPool, PagedCacheView, _BatchState, default_num_blocks
from .scheduler import (
    Request,
    RequestQueue,
    Response,
    Sequence,
    ServingBuckets,
    group_for_decode,
)

__all__ = ["Engine", "HEALTH_STATES", "ServingConfig"]

_ENGINE_IDS = itertools.count(1)

# the engine health lifecycle (Engine.health). 'degraded' still serves —
# it marks a replica the PredictorPool should deprioritize (fresh restart,
# pool rebuild) until _DEGRADED_COOLDOWN_TICKS clean ticks pass; 'dead'
# and 'draining' refuse new admissions.
HEALTH_STATES = ("warming", "ready", "degraded", "draining", "dead")
_DEGRADED_COOLDOWN_TICKS = 8


# -- module-level op helpers (cacheable tokens for the per-op jit cache) ----
def _decode_pick(logits):
    """Greedy next token from a decode chunk's last position."""
    row = logits[:, -1, :]
    return row, jnp.argmax(row, axis=-1).astype(jnp.int32)


def _prefill_pick(logits, plen):
    """Greedy next token from the TRUE last prompt position (the prompt is
    padded to its bucket; positions >= plen are pad lanes)."""
    idx = (plen.astype(jnp.int32) - 1)[:, None, None]
    row = jnp.take_along_axis(logits, idx, axis=1)[:, 0]
    return row, jnp.argmax(row, axis=-1).astype(jnp.int32)


def _raw(t):
    """Tensor → raw (materialized) jax value; raw values pass through."""
    from ..core.lazy import materialize
    from ..core.tensor import Tensor

    return materialize(t._value if isinstance(t, Tensor) else t)


class _PoolsConsumed(RuntimeError):
    """A REAL (non-injected) fault escaped the donated rung: the pool
    buffers may have been consumed by XLA. Recovery resets the pool."""

    def __init__(self, cause: BaseException):
        super().__init__(str(cause))
        self.cause = cause


@dataclass
class ServingConfig:
    """Engine knobs. ``None``/0 fields fall back to their FLAGS_serving_*
    defaults (see ``paddle.describe_flags('serving')``)."""

    block_size: int = 0
    num_blocks: int = 0              # 0 = planner-budgeted (plan_block_pool)
    prompt_buckets: Optional[List[int]] = None
    decode_batch_buckets: Optional[List[int]] = None
    max_new_tokens: int = 0          # default per-request cap
    memory_budget_mb: Optional[float] = None  # None = FLAGS_memory_budget_mb
    keep_logits: bool = False        # responses carry per-token logits rows
    dtype: str = "float32"
    # model geometry — inferred from model.cfg when present
    layers: Optional[int] = None
    heads: Optional[int] = None
    head_dim: Optional[int] = None
    max_positions: Optional[int] = None


class Engine:
    """Continuous-batching serving runtime over one generative model.

    ``model`` must accept ``model(ids, caches=views, pos_offset=tensor)``
    with a list of per-layer cache views and return ``[b, s, vocab]``
    logits — ``models.gpt.GPTForPretraining`` is the flagship shape.
    """

    def __init__(self, model, config: Optional[ServingConfig] = None):
        cfg = config or ServingConfig()
        self._uid = next(_ENGINE_IDS)
        self._model = model
        if hasattr(model, "eval"):
            model.eval()
        mcfg = getattr(model, "cfg", None)
        self._layers = cfg.layers or getattr(mcfg, "num_layers", None)
        heads = cfg.heads or getattr(mcfg, "num_heads", None)
        head_dim = cfg.head_dim
        if head_dim is None and mcfg is not None:
            head_dim = mcfg.hidden_size // mcfg.num_heads
        if not (self._layers and heads and head_dim):
            raise ValueError(
                "cannot infer model geometry; pass ServingConfig(layers=, "
                "heads=, head_dim=)"
            )
        self._max_positions = (
            cfg.max_positions or getattr(mcfg, "max_seq_len", None) or 1 << 30
        )
        self._block_size = int(cfg.block_size) or int(
            flags.flag("serving_block_size"))
        self._default_max_new = int(cfg.max_new_tokens) or int(
            flags.flag("serving_max_new_tokens"))
        self._keep_logits = bool(cfg.keep_logits)
        self._buckets = ServingBuckets(
            block_size=self._block_size,
            prompt_buckets=cfg.prompt_buckets,
            decode_batch_buckets=cfg.decode_batch_buckets,
        )
        scratch = self._buckets.max_decode_batch

        self._decode_fn = self._make_decode_fn()
        self._prefill_fn = self._make_prefill_fn()

        # -- block-pool sizing: explicit > planner budget > default --------
        self._pool_plan = None
        # planner-budgeted engines also bound per-request context by the
        # geometry the planner actually traced (set in _plan_pool): the
        # budget guarantee only covers signatures no larger than the traced
        # worst case, so bigger requests are refused at admission
        self._plan_ctx_blocks: Optional[int] = None
        num_blocks = int(cfg.num_blocks) or int(flags.flag("serving_num_blocks"))
        block_bytes = (
            2 * self._layers * self._block_size * int(heads) * int(head_dim)
            * np.dtype(cfg.dtype).itemsize
        )
        if num_blocks <= 0:
            self._pool_plan = self._plan_pool(
                heads=int(heads), head_dim=int(head_dim), dtype=cfg.dtype,
                scratch=scratch, block_bytes=block_bytes,
                budget_mb=cfg.memory_budget_mb,
            )
            if self._pool_plan.num_blocks is None:
                num_blocks = default_num_blocks()
                self._plan_ctx_blocks = None  # no budget — nothing to cap
            else:
                num_blocks = int(self._pool_plan.num_blocks)
                if num_blocks < 1:
                    raise ValueError(
                        "memory budget leaves no room for a KV block pool: "
                        f"decode-program overhead is ~"
                        f"{self._pool_plan.overhead_bytes / 2**20:.1f} MB of "
                        f"a {self._pool_plan.budget_bytes / 2**20:.1f} MB "
                        "budget (FLAGS_memory_budget_mb)"
                    )
        self._pool = BlockPool(
            layers=self._layers, heads=int(heads), head_dim=int(head_dim),
            block_size=self._block_size, num_blocks=num_blocks,
            scratch_slots=scratch, dtype=cfg.dtype,
        )

        self._queue = RequestQueue()
        self._active: List[Sequence] = []
        self._responses: Dict[int, Response] = {}
        # ids accepted into the queue but not yet answered — the drop
        # tripwire run_until_idle audits (every accepted request must end
        # with exactly one Response; anything else is a counted drop)
        self._accepted: set = set()
        self._draining = False
        # the drain BARRIER: the ids the preemption-drain contract covers
        # (snapshot at begin_drain). A concurrent Supervisor restart may
        # requeue in-flight work only from inside the barrier; anything
        # else lands as a terminal response, never re-admitted past it
        self._drain_barrier: Optional[set] = None
        self._prev_handlers: Dict[int, Any] = {}
        # set by a serving.frontdoor.ReplicaServer hosting this engine —
        # published in the obs lease so a cross-host FrontDoor can route
        # requests here
        self.serve_addr: Optional[str] = None
        # streaming log-bucketed histogram (paddle.profiler.metrics): O(1)
        # observe, fixed memory, LIFETIME coverage — replaces the old
        # 4096-entry recent-window reservoir whose stats() paid an
        # np.percentile over a copy on every call. Registered in the
        # default registry (labeled by engine uid) so Prometheus exposition
        # and postmortems see per-engine latency; close() unregisters.
        from ..profiler import metrics as _metrics

        self._token_lat = _metrics.default_registry().histogram(
            "serve_token_lat_ms",
            doc="per-token serving latency (first token incl. prefill, "
                "then one sample per decoded token), ms",
            labels={"engine": str(self._uid)},
        )
        self._decode_rows = 0
        # lifetime per-engine outcome counts (responses themselves are
        # evicted by serve()/pop_response, so stats can't scan them)
        self._n_completed = 0
        self._n_rejected = 0
        self._n_errors = 0
        self._n_shed = 0
        self._n_expired = 0
        # SLO-aware admission: measured prefill/decode cost EMAs + the
        # queue-wait trip wire (serving/admission.py)
        self._admission = AdmissionController(
            self._uid, bucket_of=self._buckets.prompt_bucket)
        # health lifecycle: warming until the first successful tick;
        # degraded after a restart/pool rebuild until a cooldown of clean
        # ticks; draining/dead refuse new admissions
        self._health = "warming"
        self._tick_no = 0
        self._degraded_until: Optional[int] = None
        self._restarts = 0
        self._last_restart_error: Optional[str] = None
        # ops plane (ISSUE 13): the diagnostics server aggregates every
        # live engine's health into /healthz + /readyz (weakly referenced;
        # close() unregisters eagerly)
        from ..profiler import diag as _diag

        _diag.register_engine(self)

    # ------------------------------------------------------------------
    # step functions (shared by all three execution tiers)
    # ------------------------------------------------------------------
    def _make_decode_fn(self) -> Callable:
        model, layers, bs = self._model, self._layers, self._block_size

        def decode_fn(k_pools, v_pools, tables, lens, tokens):
            from ..core.dispatch import apply as _apply
            from ..core.tensor import Tensor

            st = _BatchState(k_pools, v_pools, tables, lens, prefill=False)
            views = [PagedCacheView(st, i, bs) for i in range(layers)]
            ids = Tensor(tokens.astype(jnp.int64)[:, None], stop_gradient=True)
            pos = Tensor(lens, stop_gradient=True)
            with no_grad():
                logits = model(ids, caches=views, pos_offset=pos)
            row, nxt = _apply(_decode_pick, logits, op_name="serve_decode_pick")
            return (
                tuple(_raw(t) for t in st.k_pools),
                tuple(_raw(t) for t in st.v_pools),
                _raw(row), _raw(nxt),
            )

        return decode_fn

    def _make_prefill_fn(self) -> Callable:
        model, layers, bs = self._model, self._layers, self._block_size

        def prefill_fn(k_pools, v_pools, tables, ids, plen):
            from ..core.dispatch import apply as _apply
            from ..core.tensor import Tensor

            lens = jnp.zeros((ids.shape[0],), jnp.int32)
            st = _BatchState(k_pools, v_pools, tables, lens, prefill=True)
            views = [PagedCacheView(st, i, bs) for i in range(layers)]
            with no_grad():
                logits = model(Tensor(ids, stop_gradient=True),
                               caches=views, pos_offset=0)
            row, nxt = _apply(_prefill_pick, logits, plen,
                              op_name="serve_prefill_pick")
            return (
                tuple(_raw(t) for t in st.k_pools),
                tuple(_raw(t) for t in st.v_pools),
                _raw(row), _raw(nxt),
            )

        return prefill_fn

    # ------------------------------------------------------------------
    # planner-budgeted pool sizing
    # ------------------------------------------------------------------
    def _plan_pool(self, *, heads, head_dim, dtype, scratch, block_bytes,
                   budget_mb):
        """Trace the WORST-CASE decode signature once (largest batch bucket
        × largest context bucket) and hand the liveness planner the job of
        splitting the budget between program overhead and the pool."""
        from ..analysis import memory as _mem

        B = self._buckets.max_decode_batch
        nblk = self._buckets.ctx_blocks(
            self._buckets.prompt_spec.boundaries[-1], self._default_max_new)
        self._plan_ctx_blocks = nblk
        n_total = scratch + B * nblk
        pshape = (n_total, self._block_size, heads, head_dim)
        pool_spec = jax.ShapeDtypeStruct(pshape, np.dtype(dtype))
        k_specs = tuple(pool_spec for _ in range(self._layers))
        t_spec = jax.ShapeDtypeStruct((B, nblk), np.int32)
        l_spec = jax.ShapeDtypeStruct((B,), np.int32)
        roles = (
            [("buffer", f"k_pool{i}") for i in range(self._layers)]
            + [("buffer", f"v_pool{i}") for i in range(self._layers)]
            + [("feed", "block_tables"), ("feed", "seq_lens"),
               ("feed", "tokens")]
        )
        donated = tuple(range(2 * self._layers))
        pool_bytes_in_trace = (
            2 * self._layers * int(np.prod(pshape)) * np.dtype(dtype).itemsize
        )
        return _mem.plan_block_pool(
            lambda: jax.make_jaxpr(self._decode_fn)(
                k_specs, k_specs, t_spec, l_spec, l_spec),
            block_bytes=block_bytes,
            pool_bytes_in_trace=pool_bytes_in_trace,
            budget_mb=budget_mb,
            roles=roles, donated=donated,
        )

    # ------------------------------------------------------------------
    # health lifecycle
    # ------------------------------------------------------------------
    @property
    def health(self) -> str:
        """One of :data:`HEALTH_STATES` — what a Supervisor / the
        inference PredictorPool route on."""
        return self._health

    def serviceable(self) -> bool:
        """May this engine accept NEW work right now?"""
        return self._health not in ("draining", "dead")

    def _set_health(self, state: str, why: str):
        from ..core import dispatch

        if state == self._health:
            return
        if state not in HEALTH_STATES:
            raise ValueError(f"unknown health state {state!r}")
        prev, self._health = self._health, state
        dispatch._counters["serve_health_transitions"] += 1
        dispatch._emit("serve", site="engine", phase="health",
                       engine=self._uid, prev=prev, state=state,
                       why=why[:120])

    @staticmethod
    def _now() -> float:
        """Deadline clock (wall seconds). A method so tests and the probe
        can drive expiry with a virtual clock instead of sleeps."""
        return time.time()

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------
    def submit(self, prompt, max_new_tokens: Optional[int] = None,
               eos_token_id: Optional[int] = None,
               deadline_ms: Optional[float] = None,
               priority: str = "interactive") -> int:
        """Queue one request; returns its request id. Requests that can
        NEVER be served (context exceeds the budgeted pool or the model's
        positions) are rejected immediately with a Response — admission
        refusal, not an OOM. ``deadline_ms`` (default
        FLAGS_serving_default_deadline_ms; 0/None = none) and ``priority``
        ('interactive' > 'batch') feed the SLO-aware admission controller:
        a submit the engine predicts it cannot serve in time — or one
        arriving past FLAGS_serving_queue_max / the queue-wait p99 trip
        wire — is shed with a structured retriable 'overloaded' response
        instead of queueing toward a timeout."""
        from ..core import dispatch

        if deadline_ms is None:
            default_dl = float(flags.flag("serving_default_deadline_ms"))
            deadline_ms = default_dl if default_dl > 0 else None
        req = Request(
            prompt=np.asarray(prompt),
            max_new_tokens=max_new_tokens or self._default_max_new,
            eos_token_id=eos_token_id,
            deadline_ms=deadline_ms,
            priority=priority,
        )
        if self._health == "dead":
            self._reject(req, "engine is dead (supervisor restarts "
                              "exhausted)")
            return req.request_id
        if self._draining:
            self._reject(req, "engine is draining (preemption)")
            return req.request_id
        plen = int(req.prompt.size)
        ctx = (self._buckets.prompt_bucket(plen) + req.max_new_tokens)
        if ctx > self._max_positions:
            self._reject(
                req,
                f"context {ctx} exceeds the model's max positions "
                f"{self._max_positions}",
            )
            return req.request_id
        n_blk = self._buckets.ctx_blocks(plen, req.max_new_tokens)
        cap = self._pool.num_blocks
        if self._plan_ctx_blocks is not None:
            # the memory budget was proven only for decode signatures up to
            # the planner's traced worst case — a wider context would gather
            # a bigger block view than the overhead estimate covers, exactly
            # the OOM the budget exists to prevent
            cap = min(cap, self._plan_ctx_blocks)
        if n_blk > cap:
            dispatch._counters["serve_admission_refusals"] += 1
            self._reject(
                req,
                f"KV cache overflow: request needs {n_blk} blocks > "
                f"admissible context {cap} "
                "(planner-budgeted by FLAGS_memory_budget_mb)",
            )
            return req.request_id
        shed = self._admission.decide(
            req, queue=self._queue, active=self._active, now=self._now())
        if shed is not None:
            self._shed(req, shed)
            return req.request_id
        self._queue.push(req)
        self._accepted.add(req.request_id)
        dispatch._emit("serve", site="engine", phase="admit",
                       rid=req.request_id, prompt_len=plen, blocks=n_blk,
                       priority=req.priority)
        return req.request_id

    def response(self, request_id: int) -> Optional[Response]:
        return self._responses.get(request_id)

    def pop_response(self, request_id: int) -> Optional[Response]:
        """``response()`` + evict — long-running callers retrieve results
        with this so the response map doesn't grow with total traffic.
        The id leaves the drop-audit set too: a retrieved response IS the
        answered contract, so a mid-run pop (the ReplicaServer poll
        pattern) must not read as a drop at the next idle edge."""
        r = self._responses.pop(request_id, None)
        if r is not None:
            self._accepted.discard(request_id)
        return r

    def step(self):
        """One scheduler tick: expire what already missed its deadline,
        admit + prefill what fits, then one decode step for every active
        group."""
        from ..resilience import runtime as _rt

        self._tick_no += 1
        self._expire_deadlines(stage="queued")
        self._admit()
        groups = group_for_decode(self._active)
        for n_blk in sorted(groups):
            seqs = groups[n_blk]
            cap = self._buckets.max_decode_batch
            for i in range(0, len(seqs), cap):
                # pool recovery (_recover_pools) tears down EVERY active
                # sequence mid-tick: drop stale snapshot entries and, if a
                # batch reports the pool was rebuilt, abort this tick —
                # the requeued sequences re-prefill on the next one
                chunk = [s for s in seqs[i:i + cap] if s in self._active]
                if chunk and not self._decode_batch(chunk, n_blk):
                    self._end_tick(_rt)
                    return
        self._end_tick(_rt)

    def _end_tick(self, _rt):
        # per-ENGINE source/key: a process-global 'serve' would interleave
        # every engine's tick cadence into one baseline (and one liveness
        # signal) — closing one engine would halve the other's measured
        # rate into a false perf_regression, and one engine draining would
        # erase a still-wedged sibling's stall signal
        _rt.on_step_end(source=f"serve[{self._uid}]")
        if self._health == "warming":
            self._set_health("ready", "first tick completed")
        elif (self._health == "degraded"
              and self._degraded_until is not None
              and self._tick_no >= self._degraded_until):
            self._degraded_until = None
            self._set_health("ready", "degraded cooldown elapsed")

    def _expire_deadlines(self, stage: str):
        """Answer every queued/active request whose deadline has passed.
        Queued expiry runs BEFORE admission so a dead-on-arrival request
        never wastes a prefill; active expiry removes the sequence from
        its decode group (the group is recomputed each tick, so the other
        rows are untouched) and recycles its blocks."""
        now = self._now()
        for req in self._queue.take_expired(now):
            self._expire(req, stage=stage)
        for seq in [s for s in self._active if s.req.expired(now)]:
            self._release(seq)
            self._expire(seq.req, stage="decode", seq=seq)

    def run_until_idle(self):
        """Drive the loop until every accepted request has a response."""
        from ..profiler import trace as _trace

        while self._queue or self._active:
            self.step()
        self._audit_drops()
        # an IDLE request-driven engine looks exactly like a stalled one
        # to the heartbeat-age liveness read (/healthz) and the stall
        # watchdog: stand THIS ENGINE's heartbeat down (the Supervisor /
        # train_step_range discipline) — the next tick re-arms it; the
        # training loop and any sibling engine are separate sources and
        # stay armed
        _trace.watchdog_disarm(f"serve[{self._uid}]")

    def _audit_drops(self):
        """The zero-drop tripwire: at idle, every accepted request must
        have produced exactly one Response, and — the pool-leak half —
        every KV block must be back on the free-list. Anything missing is
        counted (serve_requests_dropped / serve_block_leaks; the chaos
        gates fail on either), answered with an error response so no
        caller ever hangs on a lost id, and leaked blocks are reclaimed so
        the pool doesn't starve admission forever."""
        from ..core import dispatch

        missing = self._accepted - set(self._responses)
        for rid in missing:
            dispatch._counters["serve_requests_dropped"] += 1
            self._responses[rid] = Response(
                request_id=rid, status="error",
                error="request lost by the engine (dropped) — engine bug",
                done_time=time.time(),
            )
        self._accepted.clear()
        if not self._active and self._pool.used_blocks:
            leaked = self._pool.reclaim_all()
            dispatch._counters["serve_block_leaks"] += leaked
            dispatch._emit("serve", site="engine", phase="block_leak",
                           engine=self._uid, blocks=leaked)

    def serve(self, requests: Seq, **submit_kw) -> List[Response]:
        """Convenience: submit every prompt, run to completion, return (and
        evict) the responses in submit order."""
        ids = [self.submit(p, **submit_kw) for p in requests]
        self.run_until_idle()
        return [self.pop_response(i) for i in ids]

    # -- supervision -----------------------------------------------------
    def restart(self, err: BaseException):
        """Tear the runtime down to a known-good state after a wedge or a
        tick exception escaped the resilience ladder: evict this engine's
        captured programs (a wedged executable must not be replayed),
        requeue every in-flight sequence through the existing requeue path
        (greedy decode ⇒ the re-run reproduces bitwise-identical tokens),
        and rebuild the pool storage. The engine comes back 'degraded'
        until a cooldown of clean ticks. The Supervisor owns the restart
        BUDGET (FLAGS_serving_max_engine_restarts) and calls
        :meth:`fail_clean` past it."""
        from ..core import dispatch
        from ..core.lazy import reset_serve_programs

        self._restarts += 1
        self._last_restart_error = f"{type(err).__name__}: {err}"
        dispatch._counters["serve_engine_restarts"] += 1
        dispatch._emit("serve", site="engine", phase="restart",
                       engine=self._uid, restarts=self._restarts,
                       error=type(err).__name__)
        reset_serve_programs(owner=self._uid)
        for seq in list(self._active):
            if (self._draining and self._drain_barrier is not None
                    and seq.req.request_id not in self._drain_barrier):
                # restart racing an installed preemption drain: work that
                # landed AFTER the barrier snapshot (a submit or a router
                # dispatch racing the signal handler) must not be
                # re-admitted past the drain barrier — it answers a
                # terminal retriable response instead (the FrontDoor
                # re-dispatches it to a peer), never re-enters a draining
                # engine's queue where nothing may drive it again
                from ..core import dispatch as _dispatch

                self._release(seq)
                self._n_shed += 1
                _dispatch._counters["serve_requests_shed"] += 1
                self._responses[seq.req.request_id] = Response(
                    request_id=seq.req.request_id, status="overloaded",
                    error=("engine restarted while draining: request was "
                           "outside the drain barrier — retry on a peer"),
                    retriable=True,
                    prompt_len=int(seq.req.prompt.size),
                    submit_time=seq.req.submit_time, done_time=time.time(),
                    retry_after_ms=self._admission.retry_after_ms(),
                )
                _dispatch._emit("serve", site="engine",
                                phase="drain_barrier_refusal",
                                rid=seq.req.request_id, engine=self._uid)
                continue
            self._requeue_seq(seq, err, count_retry=False)
        self._pool.reset_storage()
        self._mark_degraded(f"engine restart: {type(err).__name__}")

    def fail_clean(self, err: BaseException):
        """The restart budget is exhausted: answer EVERY queued and
        in-flight request with a terminal error response (zero hangs, zero
        silent drops), release their blocks, and go 'dead' — submits from
        here on are rejected."""
        from ..profiler import trace as _trace

        why = (f"engine dead after {self._restarts} restarts "
               f"(FLAGS_serving_max_engine_restarts): {err}")
        for seq in list(self._active):
            self._release(seq)
            self._error(seq.req, why, seq)
        while True:
            req = self._queue.pop()
            if req is None:
                break
            self._error(req, why)
        self._set_health("dead", why)
        _trace.dump_postmortem("engine_dead", exc=err,
                               engine=self._uid, restarts=self._restarts)

    @property
    def pending(self) -> int:
        """Accepted-but-unanswered work (queued + in flight)."""
        return len(self._queue) + len(self._active)

    # -- preemption ------------------------------------------------------
    def begin_drain(self):
        """Stop admitting NEW requests; everything already submitted still
        completes (the SIGTERM drain contract — zero dropped requests)."""
        from ..core import dispatch

        if not self._draining:
            self._draining = True
            # snapshot the drain BARRIER: exactly the accepted-but-
            # unanswered ids the drain contract covers. A Supervisor
            # restart during the drain requeues in-flight work only from
            # inside this set; anything racing in past it (signal-handler
            # timing) terminal-errors instead of re-admitting
            self._drain_barrier = set(self._accepted) - set(self._responses)
            dispatch._counters["serve_preempt_drains"] += 1
            if self._health != "dead":
                self._set_health("draining", "preemption drain")

    def install_preemption_handler(self, signals=(_signal.SIGTERM,)):
        for s in signals:
            if s in self._prev_handlers:
                continue  # already installed — keep the ORIGINAL previous
            self._prev_handlers[s] = _signal.signal(
                s, lambda signum, frame: self.begin_drain())

    def uninstall_preemption_handler(self):
        for s, h in self._prev_handlers.items():
            _signal.signal(s, h)
        self._prev_handlers.clear()

    def drain(self) -> List[Response]:
        """begin_drain + run to idle; returns every retained response."""
        self.begin_drain()
        self.run_until_idle()
        return list(self._responses.values())

    def close(self):
        """Release this engine's captured programs from the decode-mode
        capture cache (their closures hold the model), unregister its
        latency histogram, and restore any signal handlers. Safe to call
        twice."""
        from ..core.lazy import reset_serve_programs
        from ..profiler import diag as _diag
        from ..profiler import metrics as _metrics
        from ..profiler import sentinel as _sentinel

        self.uninstall_preemption_handler()
        _diag.unregister_engine(self)
        reset_serve_programs(owner=self._uid)
        _metrics.default_registry().remove(
            "serve_token_lat_ms", labels={"engine": str(self._uid)})
        # retire this engine's sentinel baselines: a closed engine's keys
        # get no further observations, so a tripped one could never clear
        # and would degrade /healthz for a replica that no longer exists
        _sentinel.retire(f"serve[{self._uid}]")
        _sentinel.retire(f"serve_decode[{self._uid}:")
        _sentinel.retire(f"serve_queue_wait[{self._uid}]")
        # ... and its attribution cost-registry entries (program keys and
        # the step-lap key): registry state must not grow with replica
        # churn, and a dead engine's programs must drop out of /programz
        try:
            from ..profiler import attribution as _attribution

            _attribution.retire(f"serve:prefill:{self._uid}:")
            _attribution.retire(f"serve:decode:{self._uid}:")
            _attribution.retire(f"serve[{self._uid}]")
        except Exception:
            pass
        # ... and its heartbeat source: a closed-without-drain engine must
        # not leave a stale armed source pinning /healthz at 'stalled'
        try:
            from ..profiler import trace as _trace

            _trace.watchdog_disarm(f"serve[{self._uid}]")
        except Exception:
            pass
        self._admission.close()
        self._health = "dead"  # no transition event from __del__ paths

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass  # interpreter shutdown — caches are going away anyway

    # -- introspection ---------------------------------------------------
    def reset_stats(self):
        """Drop the latency histogram (e.g. after a warm-up window, so
        steady-state percentiles don't average in compile time). Counters
        in dispatch_counters() reset separately; pool peak occupancy is
        lifetime."""
        self._token_lat.reset()
        self._decode_rows = 0

    def stats(self) -> Dict[str, Any]:
        """Percentiles come from the streaming histogram: O(buckets), no
        reservoir copy, lifetime coverage (bounded relative error from the
        log bucketing — see profiler.metrics.Histogram)."""
        from ..core.lazy import serve_capture_state

        p50 = self._token_lat.quantile(0.5)
        p99 = self._token_lat.quantile(0.99)
        out = {
            "health": self._health,
            "completed": self._n_completed,
            "rejected": self._n_rejected,
            "shed": self._n_shed,
            "expired": self._n_expired,
            "errors": self._n_errors,
            "restarts": self._restarts,
            "admission": self._admission.state(),
            "pending": self.pending,
            "pool_blocks": self._pool.num_blocks,
            "pool_occupancy": round(self._pool.occupancy(), 4),
            "pool_peak_occupancy": round(self._pool.peak_occupancy, 4),
            "token_lat_p50_ms": None if p50 is None else round(p50, 3),
            "token_lat_p99_ms": None if p99 is None else round(p99, 3),
            "token_lat_count": self._token_lat.count,
            "capture": serve_capture_state(),
        }
        if self._pool_plan is not None:
            out["est_decode_peak_hbm_mb"] = round(
                self._pool_plan.est_peak_hbm_mb, 2)
            out["pool_overhead_mb"] = round(
                self._pool_plan.overhead_bytes / 2**20, 2)
        return out

    def routing_signals(self) -> Dict[str, Any]:
        """The cost/queue signals the fleet FrontDoor routes on — also
        what the obs lease publishes per engine (the ``serving`` section),
        so a cross-host router predicts completion from this replica's own
        measured costs instead of round-robining blind.
        ``prefill_ema_ms`` is the bucket-average scalar (the per-bucket
        table rides in ``admission``)."""
        adm = self._admission.state()
        pre = adm.get("prefill_ema_ms") or {}
        return {
            "engine": self._uid,
            "health": self._health,
            "queue_depth": len(self._queue),
            "inflight": len(self._active),
            "prefill_ema_ms": (round(sum(pre.values()) / len(pre), 3)
                               if pre else None),
            "tok_ema_ms": adm.get("decode_tok_ema_ms"),
            "admission": adm,
            "serve_addr": self.serve_addr,
        }

    # ------------------------------------------------------------------
    # internals
    # ------------------------------------------------------------------
    def _release(self, seq: Sequence):
        """The one teardown path every sequence exit goes through: out of
        the active set, blocks back on the free-list, exactly once — the
        leak audit in run_until_idle stays at zero because nothing frees
        by hand anymore."""
        if seq in self._active:
            self._active.remove(seq)
        if seq.blocks:
            self._pool.free(seq.blocks)
            seq.blocks = []

    def _reject(self, req: Request, why: str):
        from ..core import dispatch

        dispatch._counters["serve_requests_rejected"] += 1
        self._n_rejected += 1
        self._responses[req.request_id] = Response(
            request_id=req.request_id, status="rejected", error=why,
            prompt_len=int(req.prompt.size), submit_time=req.submit_time,
        )
        dispatch._emit("serve", site="engine", phase="reject",
                       rid=req.request_id, why=why[:120])

    def _shed(self, req: Request, decision):
        """Load shedding: a structured, retriable 'overloaded' response —
        the admission controller predicted this request cannot be served
        in time (or the queue is at cap / the trip wire is open), so the
        honest answer is 'retry elsewhere/later', not a queue slot that
        ends in a timeout."""
        from ..core import dispatch

        dispatch._counters["serve_requests_shed"] += 1
        reasons = dispatch._counters["serve_shed_reasons"]
        reasons[decision.reason] = reasons.get(decision.reason, 0) + 1
        self._n_shed += 1
        self._responses[req.request_id] = Response(
            request_id=req.request_id, status="overloaded",
            error=f"overloaded ({decision.reason}): {decision.detail}",
            retriable=True,
            prompt_len=int(req.prompt.size), submit_time=req.submit_time,
            done_time=time.time(),
            retry_after_ms=self._admission.retry_after_ms(),
        )
        dispatch._emit("serve", site="engine", phase="shed",
                       rid=req.request_id, reason=decision.reason,
                       priority=req.priority)

    def _expire(self, req: Request, stage: str,
                seq: Optional[Sequence] = None):
        """Deadline expiry: a terminal 'timeout' response. Mid-decode
        expiry keeps the partial output when FLAGS_serving_deadline_partial
        is on (greedy decode makes partials meaningful); the caller has
        already released the sequence's blocks."""
        from ..core import dispatch

        dispatch._counters["serve_deadline_expired"] += 1
        stages = dispatch._counters["serve_expire_stages"]
        stages[stage] = stages.get(stage, 0) + 1
        self._n_expired += 1
        partial = bool(flags.flag("serving_deadline_partial"))
        tokens = list(seq.tokens) if (seq is not None and partial) else []
        n_gen = 0 if seq is None else len(seq.tokens)
        self._responses[req.request_id] = Response(
            request_id=req.request_id, status="timeout",
            error=(f"deadline of {req.deadline_ms:.0f} ms exceeded at "
                   f"stage '{stage}' after {n_gen} tokens"),
            tokens=tokens,
            prompt_len=int(req.prompt.size), submit_time=req.submit_time,
            first_token_time=getattr(req, "_first_token_time", None),
            done_time=time.time(),
        )
        dispatch._emit("serve", site="engine", phase="expire",
                       rid=req.request_id, stage=stage, tokens=n_gen,
                       priority=req.priority)

    def _error(self, req: Request, why: str, seq: Optional[Sequence] = None):
        from ..core import dispatch

        self._n_errors += 1
        self._responses[req.request_id] = Response(
            request_id=req.request_id, status="error", error=why,
            tokens=list(seq.tokens) if seq is not None else [],
            prompt_len=int(req.prompt.size), submit_time=req.submit_time,
            done_time=time.time(),
        )
        dispatch._emit("serve", site="engine", phase="error",
                       rid=req.request_id, why=why[:120])

    def _complete(self, seq: Sequence):
        from ..core import dispatch

        self._release(seq)
        dispatch._counters["serve_requests_completed"] += 1
        dispatch._emit("serve", site="engine", phase="complete",
                       rid=seq.req.request_id, tokens=len(seq.tokens))
        self._n_completed += 1
        self._responses[seq.req.request_id] = Response(
            request_id=seq.req.request_id, status="ok",
            tokens=list(seq.tokens), prompt_len=int(seq.req.prompt.size),
            submit_time=seq.req.submit_time,
            first_token_time=getattr(seq.req, "_first_token_time", None),
            done_time=time.time(),
            logits=list(seq.logits) if self._keep_logits else None,
        )

    def _requeue_seq(self, seq: Sequence, err: BaseException,
                     count_retry: bool = True):
        """Tear one sequence down and re-run it from its prompt (greedy
        decode is deterministic — the re-run reproduces the same tokens).
        Past the retry budget, the request gets an error response.
        ``count_retry=False`` is the supervisor-restart path: the engine
        wedged, not the request, so innocent in-flight work must not burn
        its FLAGS_serving_request_retries budget — the restart budget
        (FLAGS_serving_max_engine_restarts → fail_clean) is the bound
        there."""
        from ..core import dispatch

        self._release(seq)
        req = seq.req
        if count_retry:
            req.retries += 1
            if req.retries > int(flags.flag("serving_request_retries")):
                self._error(
                    req,
                    f"failed after {req.retries - 1} retries: {err}", seq)
                return
        dispatch._counters["serve_request_requeues"] += 1
        dispatch._emit("serve", site="engine", phase="requeue",
                       rid=req.request_id, retries=req.retries,
                       error=type(err).__name__)
        self._queue.push_front(req)

    def _recover_pools(self, err: _PoolsConsumed):
        """A real fault escaped the donated rung: the pool buffers may be
        consumed. Rebuild the storage and restart every in-flight
        sequence."""
        self._pool.reset_storage()
        for seq in list(self._active):
            self._requeue_seq(seq, err.cause)
        self._mark_degraded(f"pool rebuilt after {type(err.cause).__name__}")

    def _mark_degraded(self, why: str):
        if self._health in ("draining", "dead"):
            return  # terminal-ish states outrank degraded
        self._degraded_until = self._tick_no + _DEGRADED_COOLDOWN_TICKS
        self._set_health("degraded", why)

    def _admit(self):
        from ..models.gpt import CacheOverflow

        while True:
            # pop-first, not peek-then-pop: a signal-handler submit landing
            # between the two could change which request pop() returns
            # (interactive jumps the batch head), so the engine always
            # operates on the request it actually popped and push_front
            # restores it on backpressure
            req = self._queue.pop()
            if req is None:
                return
            # last call before the expensive part: a request that expired
            # between the tick-start queue scan and this pop must not
            # burn a prefill (or the blocks behind it)
            if req.expired(self._now()):
                self._expire(req, stage="prefill")
                continue
            n_blk = self._buckets.ctx_blocks(
                int(req.prompt.size), req.max_new_tokens)
            try:
                blocks = self._pool.alloc(n_blk)
            except CacheOverflow as e:
                from ..core import dispatch

                dispatch._counters["serve_admission_refusals"] += 1
                self._reject(req, str(e))
                continue
            if blocks is None:
                # backpressure: wait for a completion to free blocks
                self._queue.push_front(req)
                return
            wait_ms = (self._now() - req.submit_time) * 1000.0
            self._admission.note_queue_wait(wait_ms)
            from ..profiler import sentinel as _sentinel

            _sentinel.observe(f"serve_queue_wait[{self._uid}]", wait_ms)
            seq = Sequence(req, blocks, n_blk)
            try:
                self._prefill(seq)
            except _PoolsConsumed as e:
                self._active.append(seq)  # so recovery requeues it too
                self._recover_pools(e)
                return
            except Exception as e:  # tiers exhausted — requeue just this one
                self._requeue_seq(seq, e)
                return

    def _prefill(self, seq: Sequence):
        from ..core import dispatch

        req = seq.req
        plen = int(req.prompt.size)
        padded = self._buckets.pad_prompt(req.prompt)
        P = int(padded.shape[-1])
        args = (
            tuple(self._pool.k), tuple(self._pool.v),
            jnp.asarray(np.asarray([seq.table_row()], np.int32)),
            jnp.asarray(padded[None, :].astype(np.int64)),
            jnp.asarray(np.asarray([plen], np.int32)),
        )
        key = ("prefill", self._uid, P, seq.n_blk)
        t0 = time.perf_counter()
        k_pools, v_pools, row, nxt = self._run_tiered(
            "prefill", key, self._prefill_fn, args)
        self._pool.k, self._pool.v = list(k_pools), list(v_pools)
        tok = int(np.asarray(jax.device_get(nxt))[0])
        dispatch._counters["serve_prefills"] += 1
        prefill_ms = (time.perf_counter() - t0) * 1000.0
        self._token_lat.observe(prefill_ms)
        self._admission.note_prefill(P, prefill_ms)
        dispatch._emit("serve", site="engine", phase="prefill",
                       rid=req.request_id, bucket=P, blocks=seq.n_blk,
                       ms=round(prefill_ms, 3))
        seq.length = plen
        seq.tokens.append(tok)
        seq.last_token = tok
        req._first_token_time = time.time()
        if self._keep_logits:
            seq.logits.append(np.asarray(jax.device_get(row))[0])
        self._active.append(seq)
        if seq.done:
            self._complete(seq)

    def _decode_batch(self, seqs: List[Sequence], n_blk: int) -> bool:
        """One decode step for one batch. Returns False only when a real
        fault forced a pool rebuild (the caller must abort its group
        snapshot for this tick)."""
        from ..core import dispatch
        from ..models.gpt import CacheOverflow

        # sequences at context capacity can't take another token — finish
        # them with what they have rather than corrupting a neighbor block
        ready = []
        for s in seqs:
            if s.length + 1 > s.n_blk * self._block_size:
                self._release(s)
                self._error(
                    s.req,
                    str(CacheOverflow(s.length + 1,
                                      s.n_blk * self._block_size)),
                    s,
                )
            else:
                ready.append(s)
        if not ready:
            return True
        B = self._buckets.batch_bucket(len(ready))
        rows = [s.table_row() for s in ready]
        lens = [s.length for s in ready]
        toks = [s.last_token for s in ready]
        for slot in range(len(ready), B):  # pad rows → per-slot scratch block
            rows.append([slot] * n_blk)
            lens.append(0)
            toks.append(0)
        args = (
            tuple(self._pool.k), tuple(self._pool.v),
            jnp.asarray(np.asarray(rows, np.int32)),
            jnp.asarray(np.asarray(lens, np.int32)),
            jnp.asarray(np.asarray(toks, np.int32)),
        )
        key = ("decode", self._uid, B, n_blk)
        t0 = time.perf_counter()
        try:
            k_pools, v_pools, row, nxt = self._run_tiered(
                "decode", key, self._decode_fn, args)
        except _PoolsConsumed as e:
            self._recover_pools(e)
            return False
        except Exception as e:  # every tier failed — requeue this batch only
            for s in ready:
                self._requeue_seq(s, e)
            return True
        self._pool.k, self._pool.v = list(k_pools), list(v_pools)
        out = np.asarray(jax.device_get(nxt))
        row_np = (
            np.asarray(jax.device_get(row)) if self._keep_logits else None)
        step_ms = (time.perf_counter() - t0) * 1000.0
        dispatch._counters["serve_decode_steps"] += 1
        dispatch._emit("serve", site="engine", phase="decode",
                       rids=tuple(s.req.request_id for s in ready),
                       batch=B, blocks=n_blk, ms=round(step_ms, 3))
        self._decode_rows += len(ready)
        self._admission.note_decode(step_ms, len(ready))
        # per-(decode-signature) regression baseline: one key per captured
        # bucket program, so only a genuinely slower replay drifts
        from ..profiler import sentinel as _sentinel

        _sentinel.observe(f"serve_decode[{self._uid}:{B}x{n_blk}]", step_ms)
        now = self._now()
        for i, s in enumerate(ready):
            tok = int(out[i])
            s.length += 1
            s.tokens.append(tok)
            s.last_token = tok
            if row_np is not None:
                s.logits.append(row_np[i])
            self._token_lat.observe(step_ms)
            if s.done:
                self._complete(s)
            elif s.req.expired(now):
                # mid-decode expiry: this row leaves the group here (the
                # group list is rebuilt every tick, so no other row moves)
                # and answers 'timeout' with its partial output
                self._release(s)
                self._expire(s.req, stage="decode", seq=s)
        return True

    def _run_tiered(self, kind: str, key, fn, args):
        """captured (donated) → lazy (same program, no donation) → per-op."""
        from ..core import dispatch
        from ..core import lazy as _lazy
        from ..resilience import faults as _faults
        from ..resilience import runtime as _rt

        if not flags.flag("serving_capture"):
            return _rt.execute(kind, lambda: fn(*args))
        donate = bool(flags.flag("serving_capture_donate"))
        prog = _lazy.serve_program(key, fn, donate_argnums=(0, 1))
        if donate and _rt.captured_tier_ok(key):
            from ..analysis import ProgramVerificationError

            try:
                return _rt.execute(
                    kind, lambda: prog.run(args, donate=True),
                    fresh=not prog.built(True), ladder_key=key,
                    retry_unsafe=True,
                )
            except ProgramVerificationError:
                # the donated rung failed its equivalence certificate
                # against the plain rung (FLAGS_check_programs=2). The
                # check runs at trace time, BEFORE the donated program
                # executes, so the pools are intact: take the retry-safe
                # rung with the same buffers
                dispatch._counters["serve_capture_fallbacks"] += 1
            except Exception as e:
                dispatch._counters["serve_capture_fallbacks"] += 1
                if not isinstance(e, _faults.InjectedFault):
                    # the donated program may have consumed the pool before
                    # failing — never reuse those buffers
                    raise _PoolsConsumed(e)
                # injected faults raise BEFORE the program runs: inputs are
                # intact, take the retry-safe rung with the same buffers
        try:
            return _rt.execute(
                kind, lambda: prog.run(args, donate=False),
                fresh=not prog.built(False), ladder_key=key,
            )
        except Exception:
            # the non-donated rung never consumed its inputs, so the floor
            # is safe for injected AND real faults alike (a fused-program-
            # only flake completes per-op; a deterministic bug fails again
            # below and propagates to the requeue/error path)
            dispatch._counters["serve_capture_fallbacks"] += 1
        # ladder floor: plain eager — every op is its own resilience site
        return fn(*args)
