"""Sharding specs + the compiled sharded train step (GSPMD path).

This replaces, in one mechanism, four reference subsystems (SURVEY.md §2.D):
  - DP grad allreduce (imperative/reducer.cc bucketed NCCL allreduce) —
    XLA inserts the gradient all-reduce when the batch is sharded on `dp`;
  - ZeRO stages 1-3 (meta_parallel/sharding/group_sharded_stage{2,3}.py,
    meta_optimizers/sharding_optimizer.py:45) — optimizer state (stage 1/2)
    and parameters (stage 3) carry a `sharding`-axis spec; XLA materializes
    reduce-scatter + all-gather exactly where the hand-written stages put
    them;
  - TP (meta_parallel/parallel_layers/mp_layers.py) — weight specs partition
    on `mp`, activations get sharding constraints;
  - the 143 collective ops (operators/collective/) — GSPMD emits the HLO
    collectives with replica_groups derived from the mesh.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.dispatch import no_grad
from ..core.tensor import Tensor
from .topology import get_mesh

ShardingSpec = P


def param_spec(p: Tensor, zero_stage: int = 0, mesh: Optional[Mesh] = None) -> P:
    """Sharding spec for one parameter: explicit layer-assigned spec first
    (TP layers set `dist_spec`), else ZeRO-3 shards the first divisible dim
    over `sharding`, else replicated."""
    mesh = mesh or get_mesh()
    if getattr(p, "fuse_replicated", False):
        # pinned by the fuse_all_reduce pass: too small to be worth
        # sharding — ride the fused replicated all-reduce
        return P(*([None] * p.ndim))
    spec = getattr(p, "dist_spec", None)
    if spec is not None:
        spec = P(*spec) if not isinstance(spec, P) else spec
    else:
        spec = P(*([None] * p.ndim))
    if zero_stage >= 3 and mesh is not None:
        n_shard = dict(zip(mesh.axis_names, mesh.devices.shape)).get("sharding", 1)
        if n_shard > 1:
            entries = list(spec) + [None] * (p.ndim - len(list(spec)))
            for d in range(p.ndim):
                if entries[d] is None and p.shape[d] % n_shard == 0:
                    entries[d] = "sharding"
                    break
            spec = P(*entries)
    return spec


def _state_spec(pspec: P, shape, zero_stage: int, mesh: Mesh) -> P:
    """Optimizer-state spec: mirrors the param spec; ZeRO-1/2 additionally
    shards moments over `sharding` (the optimizer-state partitioning of
    group_sharded_optimizer_stage2.py:41)."""
    entries = list(pspec) + [None] * (len(shape) - len(list(pspec)))
    if zero_stage >= 1 and mesh is not None and len(shape) > 0:
        n_shard = dict(zip(mesh.axis_names, mesh.devices.shape)).get("sharding", 1)
        if n_shard > 1 and "sharding" not in entries:
            for d in range(len(shape)):
                if entries[d] is None and shape[d] % n_shard == 0:
                    entries[d] = "sharding"
                    break
    return P(*entries)


def shard_params(model, mesh: Optional[Mesh] = None, zero_stage: int = 0):
    """Device_put every parameter/buffer with its NamedSharding — after this
    the weights physically live distributed across the mesh."""
    mesh = mesh or get_mesh()
    if mesh is None:
        return model
    with no_grad():
        for p in model.parameters():
            s = NamedSharding(mesh, param_spec(p, zero_stage, mesh))
            p._value = jax.device_put(p._value, s)
        for b in model.buffers():
            b._value = jax.device_put(b._value, NamedSharding(mesh, P()))
    return model


def capture_step_shardings(params, states, mesh: Optional[Mesh] = None):
    """NamedShardings of the donated leaves of a mesh-aware captured step.

    The whole-step capture controller (core.lazy) jits its captured program
    with declared in/out shardings so the replay is the same one SPMD
    program `ShardedTrainStep` compiles, buffer placement included. Per
    parameter: the committed NamedSharding when the buffer already lives
    distributed (shard_params / an earlier donated replay), else the
    derived `param_spec`. Per optimizer-state leaf: the committed sharding,
    else replicated for scalars (step counts) and the param spec mirrored
    through `_state_spec` otherwise — exactly the layout
    `ShardedTrainStep._shardings` declares, so a capture at matched specs
    is bitwise-comparable. Returns ``(param_shardings, state_shardings)``
    aligned with ``params`` / ``states`` (each state entry a dict keyed
    like the optimizer accumulator dict)."""
    mesh = mesh or get_mesh()
    if mesh is None:
        raise ValueError("capture_step_shardings requires a mesh")

    def _committed(val):
        sh = getattr(val, "sharding", None)
        if isinstance(sh, NamedSharding) and sh.mesh.devices.size > 1:
            return sh
        return None

    p_sh: List[NamedSharding] = []
    st_sh: List[Dict[str, NamedSharding]] = []
    for p, st in zip(params, states):
        v = p._value if isinstance(p, Tensor) else p
        psh = _committed(v) or NamedSharding(mesh, param_spec(p, 0, mesh))
        p_sh.append(psh)
        d = {}
        for k in sorted(st):
            sv = st[k]
            csh = _committed(sv)
            if csh is not None:
                d[k] = csh
            elif getattr(sv, "ndim", 0) == 0:
                d[k] = NamedSharding(mesh, P())
            else:
                d[k] = NamedSharding(
                    mesh, _state_spec(psh.spec, sv.shape, 1, mesh))
        st_sh.append(d)
    return tuple(p_sh), tuple(st_sh)


import threading as _threading

_constraint_tls = _threading.local()


class suppress_sharding_constraints:
    """Scope that turns with_sharding_constraint into a no-op. Used by the
    pipeline schedule: inside the shard_map-manual-over-pp region, GSPMD
    constraints naming auto axes can crash XLA's partitioner (group-count
    check in spmd_partitioner_util.cc); weight shardings alone propagate the
    TP layout there."""

    def __enter__(self):
        self._prev = getattr(_constraint_tls, "off", False)
        _constraint_tls.off = True
        return self

    def __exit__(self, *exc):
        _constraint_tls.off = self._prev
        return False


def _sharding_constraint_op(x, *, mesh, spec):
    """Module-level op fn (stable per-op jit cache token): the GSPMD
    sharding annotation as a regular dispatched op, so an eager constraint
    joins the pending lazy segment instead of forcing a flush."""
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, P(*spec)))


def with_sharding_constraint(x, *spec):
    """Annotation helper usable inside layer forwards (no-op without a mesh).
    The TPU analogue of inserting a c_split/c_concat/c_identity op."""
    mesh = get_mesh()
    val = x._value if isinstance(x, Tensor) else x
    if mesh is None or isinstance(val, np.ndarray):
        return x
    if getattr(_constraint_tls, "off", False):
        return x
    from ..core.flags import flag as _flag

    if isinstance(x, Tensor) and not isinstance(val, jax.core.Tracer) \
            and bool(_flag("eager_lazy_dispatch")):
        # lazy-eager path: dispatch as a regular lazy op. The constraint
        # stays inside the pending segment (one fused program, whole-step
        # capture keeps its 3-program shape) and GSPMD resolves it at
        # flush — the old jitted-identity eager lowering instead flushed
        # HERE, and refused single-device committed inputs (a pallas
        # kernel's eager flush output) against a mesh-spanning
        # out_sharding. Per-op eager mode (lazy dispatch off) keeps the
        # skip-on-conflict lowering below: its tensors are committed to
        # one device, and force-resharding just this value would feed
        # mixed placements to the next multi-arg op.
        from ..core import dispatch

        try:
            return dispatch.apply(
                _sharding_constraint_op, x, mesh=mesh, spec=tuple(spec),
                op_name="sharding_constraint",
            )
        except Exception:
            # repair committed-placement mismatches instead of skipping:
            # device_put reshards a concrete value from ANY placement
            try:
                from ..core.lazy import materialize as _mat

                out = jax.device_put(
                    _mat(val), NamedSharding(mesh, P(*spec)))
            except (ValueError, TypeError):
                return x
            t = Tensor(out, stop_gradient=x.stop_gradient)
            t._grad_node = x._grad_node
            t._out_index = x._out_index
            return t
    try:
        out = jax.lax.with_sharding_constraint(val, NamedSharding(mesh, P(*spec)))
    except (ValueError, TypeError):
        return x
    if isinstance(x, Tensor):
        t = Tensor(out, stop_gradient=x.stop_gradient)
        t._grad_node = x._grad_node
        t._out_index = x._out_index
        return t
    return out


class ShardedTrainStep:
    """Compiled hybrid-parallel train step over the global mesh.

    The single entry point that turns (model, loss, optimizer, strategy)
    into one SPMD XLA program: batch sharded over (dp, sharding), params per
    their specs (TP/ZeRO-3), optimizer state ZeRO-sharded, buffers
    replicated. Donation keeps params/opt-state in place in HBM.
    Reference counterpart: the whole
    fleet.distributed_model + HybridParallelOptimizer + reducer pipeline
    (fleet/meta_parallel/*).
    """

    def __init__(self, model, loss_fn, optimizer, mesh=None, zero_stage=0,
                 batch_axes=("dp", "sharding"), forward_ctx=None,
                 accumulate_steps=1, loss_scale=1.0, grad_input_idx=()):
        # batch positions to ALSO differentiate — their grads return to the
        # caller (the PS sparse path: pulled rows in, row grads out, pushed
        # to the host table; reference: distributed_push_sparse)
        self.grad_input_idx = tuple(int(i) for i in grad_input_idx)
        if self.grad_input_idx and int(accumulate_steps) > 1:
            raise ValueError(
                "grad_input_idx is not supported with compiled gradient "
                "merge (the per-microbatch input grads would need their "
                "own accumulation contract)"
            )
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        # zero-arg context-manager factory wrapped around the traced forward
        # (fleet wires strategy.amp through here as an auto_cast factory)
        self.forward_ctx = forward_ctx
        # >1 = compiled gradient merge: the leading batch dim must divide
        # into accumulate_steps microbatches (strategy.gradient_merge)
        self.accumulate_steps = int(accumulate_steps)
        if self.accumulate_steps < 1:
            raise ValueError("accumulate_steps must be >= 1")
        # static loss scaling for pure-fp16 compute (1.0 = off); grads are
        # unscaled before clipping/update inside the compiled step
        self.loss_scale = float(loss_scale)
        self.mesh = mesh or get_mesh()
        self.zero_stage = zero_stage
        self.batch_axes = tuple(
            a for a in batch_axes if a in (self.mesh.axis_names if self.mesh else ())
        )
        self._params = [p for p in model.parameters() if not p.stop_gradient]
        self._buffers = [b for _, b in model.named_buffers()]
        self._hyper = optimizer._hyper()
        self._step = None
        self._opt_state = None

    def _init_state(self):
        states = []
        for p in self._params:
            st = self.optimizer._accumulators.get(id(p))
            if st is None:
                st = self.optimizer._create_state(p)
                self.optimizer._accumulators[id(p)] = st
            states.append(st)
        return states

    def _shardings(self, opt_state=None):
        mesh = self.mesh
        states = opt_state if opt_state is not None else self._opt_state
        p_specs = [param_spec(p, self.zero_stage, mesh) for p in self._params]
        p_sh = tuple(NamedSharding(mesh, s) for s in p_specs)
        st_sh = []
        for p, spec, st in zip(self._params, p_specs, states):
            st_sh.append(
                {
                    k: NamedSharding(
                        mesh,
                        _state_spec(spec, v.shape, max(self.zero_stage, 1), mesh)
                        if v.ndim > 0
                        else P(),
                    )
                    for k, v in st.items()
                }
            )
        b_sh = tuple(NamedSharding(mesh, P()) for _ in self._buffers)
        batch_spec = P(self.batch_axes if self.batch_axes else None)
        return p_sh, tuple(st_sh), b_sh, NamedSharding(mesh, batch_spec)

    def _step_parts(self, n_batch_args, opt_state=None):
        """(step_fn, in_shardings, out_shardings) — the traced function and
        its declared shardings, pre-jit. The sharding analyzer
        (analysis.sharding.check_sharded_step) traces step_fn at per-shard
        shapes without paying the XLA compile; _build wraps the same triple
        in jax.jit."""
        from ..jit import _bind_values
        from ..core import random as _random

        model, loss_fn, opt = self.model, self.loss_fn, self.optimizer
        params, buffers = self._params, self._buffers
        hyper = self._hyper
        per_hyper = [dict(hyper, **opt._per_param_hyper(p)) for p in params]
        rule = type(opt)._update
        grad_clip = opt._grad_clip

        import contextlib

        fwd_ctx = self.forward_ctx or contextlib.nullcontext

        accum_k = self.accumulate_steps
        loss_scale = self.loss_scale
        # hybrid dp×sharding + ZeRO: GSPMD cannot partition the weight-grad
        # dots when the grad's zero-spec (sharded over 'sharding', replicated
        # over 'dp') propagates into batch-sharded activations that span
        # BOTH axes — it falls back to 'Involuntary full rematerialization'
        # (replicate-then-repartition) of every such activation. Pinning the
        # grads to their TP spec (no zero dim) right after the backward
        # keeps the grad dot local (partial sums + one all-reduce over the
        # batch group); the reshard onto the zero spec then happens at the
        # optimizer update, where it is a local slice.
        axes = dict(zip(self.mesh.axis_names, self.mesh.devices.shape)) \
            if self.mesh else {}
        # stage 3's sharded PARAMS hit the same trap from the other side:
        # the zero spec propagates backwards through the weight-grad dot
        # onto forward activations (r5: the ernie-ctr dryrun showed the
        # remat on a gelu output under dp2×sharding4 stage3), so all three
        # stages pin when both axes are real
        hybrid_zero = (self.zero_stage in (1, 2, 3) and axes.get("dp", 1) > 1
                       and axes.get("sharding", 1) > 1)
        if hybrid_zero:
            grad_pin = [
                NamedSharding(self.mesh, param_spec(p, 0, self.mesh))
                for p in params
            ]

        gidx = self.grad_input_idx

        def step_fn(p_vals, opt_states, b_vals, key, lr, *batch_vals):
            def loss_of(p_vals, b_vals, key, batch_vals, diff_vals=()):
                batch_vals = list(batch_vals)
                for i, v in zip(gidx, diff_vals):
                    batch_vals[i] = v
                ins = [Tensor(v, stop_gradient=True) for v in batch_vals]
                with _bind_values(params + buffers, list(p_vals) + list(b_vals)), \
                        no_grad(), _random.rng_scope(key), fwd_ctx():
                    out = model(*ins[:-1]) if len(ins) > 1 else model(ins[0])
                    loss = loss_fn(out, ins[-1]) if loss_fn is not None else out
                    new_b = tuple(b._value for b in buffers)
                lv = loss._value if isinstance(loss, Tensor) else loss
                if loss_scale != 1.0:
                    lv = lv * loss_scale
                return lv, new_b

            if accum_k > 1:
                # compiled gradient merge (reference: GradientMergeOptimizer
                # program rewrite): split the global batch into k chunks and
                # lax.scan value_and_grad over them, accumulating fp32 grads
                # — peak activation memory is one microbatch's, the update
                # applies ONCE on the averaged gradient
                chunks = tuple(
                    v.reshape((accum_k, v.shape[0] // accum_k) + v.shape[1:])
                    for v in batch_vals
                )
                keys = jax.random.split(key, accum_k)

                def scan_body(carry, xs):
                    g_acc, b_cur = carry
                    k_i, chunk = xs[0], xs[1:]
                    (lv, new_b), gs = jax.value_and_grad(
                        loss_of, has_aux=True)(tuple(p_vals), b_cur, k_i, chunk)
                    g_acc = tuple(
                        a + g.astype(jnp.float32) for a, g in zip(g_acc, gs)
                    )
                    return (g_acc, new_b), lv

                g0 = tuple(
                    jnp.zeros(p.shape, jnp.float32) for p in p_vals
                )
                (g_acc, new_b), losses = jax.lax.scan(
                    scan_body, (g0, tuple(b_vals)), (keys,) + chunks
                )
                grads = tuple(
                    (g / accum_k).astype(p.dtype)
                    for g, p in zip(g_acc, p_vals)
                )
                loss = jnp.mean(losses)
                in_grads = ()  # gidx is rejected with gradient merge
            elif gidx:
                (loss, new_b), (grads, in_grads) = jax.value_and_grad(
                    loss_of, argnums=(0, 4), has_aux=True
                )(tuple(p_vals), tuple(b_vals), key, tuple(batch_vals),
                  tuple(batch_vals[i] for i in gidx))
            else:
                in_grads = ()
                (loss, new_b), grads = jax.value_and_grad(
                    loss_of, has_aux=True
                )(tuple(p_vals), tuple(b_vals), key, tuple(batch_vals))
            if hybrid_zero:
                grads = tuple(
                    jax.lax.with_sharding_constraint(g, s)
                    for g, s in zip(grads, grad_pin)
                )
            if loss_scale != 1.0:
                loss = loss / loss_scale
                grads = tuple(
                    (g.astype(jnp.float32) / loss_scale).astype(g.dtype)
                    for g in grads
                )
                # input grads ship to the caller (PS push): they must be
                # unscaled exactly like the param grads
                in_grads = tuple(
                    (g.astype(jnp.float32) / loss_scale).astype(g.dtype)
                    for g in in_grads
                )
            if grad_clip is not None:
                pairs = grad_clip(
                    [
                        (Tensor(pv, stop_gradient=True), Tensor(gv, stop_gradient=True))
                        for pv, gv in zip(p_vals, grads)
                    ]
                )
                grads = [g._value for _, g in pairs]
            new_p, new_s = [], []
            for pv, gv, st, h in zip(p_vals, grads, opt_states, per_hyper):
                if gv.dtype != pv.dtype:
                    gv = gv.astype(pv.dtype)
                np_, ns_ = rule(opt, pv, gv, lr, st, **h)
                new_p.append(np_)
                new_s.append(ns_)
            return loss, tuple(in_grads), tuple(new_p), tuple(new_s), new_b

        p_sh, st_sh, b_sh, batch_sh = self._shardings(opt_state)
        repl = NamedSharding(self.mesh, P())
        in_sh = (p_sh, st_sh, b_sh, repl, repl) + (batch_sh,) * n_batch_args
        out_sh = (repl, (batch_sh,) * len(gidx), p_sh, st_sh, b_sh)
        return step_fn, in_sh, out_sh

    def _build(self, n_batch_args):
        step_fn, in_sh, out_sh = self._step_parts(n_batch_args)
        return jax.jit(
            step_fn, in_shardings=in_sh, out_shardings=out_sh,
            donate_argnums=(0, 1),
        )

    def _check_programs(self, batch):
        """FLAGS_check_programs gate: run the per-shard analysis suite over
        the traced step before the first compile. Same enforcement point as
        Executor.run (1 = warn, 2 = raise on errors); the trace itself must
        never block training, so its failures are swallowed."""
        from ..core.flags import flag as _flag

        if not int(_flag("check_programs")):
            return
        try:
            from ..analysis import enforce
            from ..analysis.sharding import check_sharded_step

            specs = [
                jax.ShapeDtypeStruct(
                    tuple((b._value if isinstance(b, Tensor)
                           else np.asarray(b)).shape),
                    (b._value if isinstance(b, Tensor)
                     else np.asarray(b)).dtype,
                )
                for b in batch
            ]
            diags = check_sharded_step(self, specs, source="sharded-step")
        except Exception:
            return
        enforce(diags, "sharded_train_step")

    @no_grad()
    def __call__(self, *batch) -> Tensor:
        if self.accumulate_steps > 1:
            for b in batch:
                n0 = (b._value if isinstance(b, Tensor) else np.asarray(b)).shape[0]
                if n0 % self.accumulate_steps:
                    raise ValueError(
                        f"global batch {n0} is not divisible by gradient-"
                        f"merge accumulate_steps={self.accumulate_steps}"
                    )
        if self._opt_state is None:
            # (re)initialize + physically place optimizer state per its
            # (ZeRO) spec — jit donation requires argument shardings to
            # match declarations. Separate from the compile so a tuner can
            # reset state on an already-compiled winner (trial steps
            # mutate it) without paying the XLA compile twice.
            self._opt_state = self._init_state()
            _, st_sh, _, _ = self._shardings()
            self._opt_state = [
                {k: jax.device_put(v, sh[k]) for k, v in st.items()}
                for st, sh in zip(self._opt_state, st_sh)
            ]
        if self._step is None:
            self._check_programs(batch)
            self._step = self._build(len(batch))
        _, _, _, batch_sh = self._shardings()
        batch_vals = [
            jax.device_put(
                b._value if isinstance(b, Tensor) else jnp.asarray(b), batch_sh
            )
            for b in batch
        ]
        p_vals = tuple(p._value for p in self._params)
        b_vals = tuple(b._value for b in self._buffers)
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        key = _next_key()
        loss, in_grads, new_p, new_s, new_b = self._step(
            p_vals, tuple(self._opt_state), b_vals, key, lr, *batch_vals
        )
        for p, v in zip(self._params, new_p):
            p._value = v
        for b, v in zip(self._buffers, new_b):
            b._value = v
        self._opt_state = list(new_s)
        for p, st in zip(self._params, self._opt_state):
            self.optimizer._accumulators[id(p)] = st
        self.optimizer._step_count += 1
        loss_t = Tensor(loss, stop_gradient=True)
        if self.grad_input_idx:
            return loss_t, [Tensor(g, stop_gradient=True) for g in in_grads]
        return loss_t


def _next_key():
    from ..core import random as _random

    return _random.next_key()


def sharded_train_step(model, loss_fn, optimizer, mesh=None, zero_stage=0,
                       batch_axes=("dp", "sharding"), forward_ctx=None,
                       accumulate_steps=1, loss_scale=1.0, grad_input_idx=()):
    return ShardedTrainStep(model, loss_fn, optimizer, mesh, zero_stage,
                            batch_axes, forward_ctx, accumulate_steps,
                            loss_scale, grad_input_idx)
