"""paddle_tpu.parallel — the mesh/SPMD engine under paddle.distributed.

This package is the TPU-native machinery that replaces the reference's
NCCL-ring world (SURVEY.md §2.C/D): a global `jax.sharding.Mesh` built from
the HybridCommunicateGroup topology, sharding specs for every parallelism
strategy (dp / sharding-ZeRO / mp-TP / pp / sep / ep), and the compiled
sharded train step (GSPMD inserts the collectives that the reference's 143
c_* ops insert by hand).
"""
from .topology import (  # noqa: F401
    CommunicateTopology,
    HybridCommunicateGroup,
    get_mesh,
    global_mesh,
    init_mesh,
)
from .sharding import (  # noqa: F401
    ShardingSpec,
    param_spec,
    shard_params,
    sharded_train_step,
)
