"""Device-mesh topology.

Reference analogue: fleet/base/topology.py (CommunicateTopology:52,
HybridCommunicateGroup:133 — the 4-D dp×mp×pp×sharding process topology that
builds NCCL comm groups per axis). TPU-native: the topology IS a
`jax.sharding.Mesh` with named axes; "comm groups" become mesh axis names
consumed by collectives/`PartitionSpec`s, and XLA lays the collectives onto
ICI rings. Axes extend the reference's four with `sep` (sequence/context
parallel — absent upstream, SURVEY.md §5) and `ep` (expert parallel is
folded over dp×sharding like the reference's MoE).
"""
from __future__ import annotations

import collections
from typing import Dict, List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

# canonical axis order: outermost (slowest-varying, cross-slice OK) first.
# pp communicates least → outermost; mp communicates most → innermost so its
# collectives ride the fastest ICI loops (scaling-book layout discipline).
AXIS_ORDER = ("pp", "dp", "sharding", "sep", "mp")

_global = {"hcg": None, "mesh": None}


class CommunicateTopology:
    """reference: fleet/base/topology.py:52 — named hybrid dims + rank math."""

    def __init__(self, hybrid_group_names=("data", "pipe", "sharding", "model"),
                 dims=(1, 1, 1, 1)):
        self._parallel_names = list(hybrid_group_names)
        self._dims = list(dims)
        self.coordinate = collections.namedtuple("Coordinate", self._parallel_names)
        ranges = [range(d) for d in self._dims]
        import itertools

        self._coord2rank = {}
        self._rank2coord = {}
        for rank, coord in enumerate(itertools.product(*ranges)):
            c = self.coordinate(*coord)
            self._coord2rank[c] = rank
            self._rank2coord[rank] = c

    def get_hybrid_group_names(self):
        return self._parallel_names

    def get_dim(self, axis_name):
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self):
        return int(np.prod(self._dims))

    def get_rank(self, **kwargs):
        return self._coord2rank[self.coordinate(**kwargs)]

    def get_coord(self, rank):
        return self._rank2coord[rank]

    def get_axis_list(self, axis_name, index):
        """All ranks whose coordinate on `axis_name` equals index."""
        axis = self._parallel_names.index(axis_name)
        return sorted(
            r for c, r in self._coord2rank.items() if c[axis] == index
        )

    def get_comm_list(self, axis_name):
        """Rank groups that vary only along `axis_name` — these are the
        reference's NCCL comm rings and our HLO replica_groups."""
        axis = self._parallel_names.index(axis_name)
        groups = collections.defaultdict(list)
        for c, r in sorted(self._coord2rank.items(), key=lambda kv: kv[1]):
            key = tuple(v for i, v in enumerate(c) if i != axis)
            groups[key].append(r)
        return [sorted(v) for _, v in sorted(groups.items())]


class HybridCommunicateGroup:
    """reference: fleet/base/topology.py:133 — per-axis group handles.

    On TPU the "group" for an axis is the mesh axis name itself; rank/world
    queries map to mesh coordinates of the current process's first device
    (single-controller) or of jax.process_index() (multi-host).
    """

    def __init__(self, topology: CommunicateTopology):
        self._topo = topology
        self.nranks = topology.world_size()
        self.global_rank = 0
        names = topology.get_hybrid_group_names()
        dim = topology.get_dim
        self._dp_degree = dim("data") if "data" in names else 1
        self._mp_degree = dim("model") if "model" in names else 1
        self._pp_degree = dim("pipe") if "pipe" in names else 1
        self._sharding_degree = dim("sharding") if "sharding" in names else 1
        self._sep_degree = dim("sep") if "sep" in names else 1

    # degrees (reference: topology.py:139-142)
    def get_data_parallel_world_size(self):
        return self._dp_degree

    def get_model_parallel_world_size(self):
        return self._mp_degree

    def get_pipe_parallel_world_size(self):
        return self._pp_degree

    def get_sharding_parallel_world_size(self):
        return self._sharding_degree

    def get_sep_parallel_world_size(self):
        return self._sep_degree

    def _coord(self):
        return self._topo.get_coord(self.global_rank)

    def get_data_parallel_rank(self):
        return self._coord().data if "data" in self._topo.get_hybrid_group_names() else 0

    def get_model_parallel_rank(self):
        return self._coord().model if "model" in self._topo.get_hybrid_group_names() else 0

    def get_stage_id(self):
        return self._coord().pipe if "pipe" in self._topo.get_hybrid_group_names() else 0

    def get_sharding_parallel_rank(self):
        return (
            self._coord().sharding
            if "sharding" in self._topo.get_hybrid_group_names()
            else 0
        )

    # group handles — on TPU these carry the mesh axis name
    def _group(self, axis):
        from ..distributed.collective import Group

        mesh_axis = {"data": "dp", "model": "mp", "pipe": "pp",
                     "sharding": "sharding", "sep": "sep"}[axis]
        names = self._topo.get_hybrid_group_names()
        if axis not in names:
            return Group(ranks=[0], axis_name=mesh_axis)
        comm = self._topo.get_comm_list(axis)
        mine = next(g for g in comm if self.global_rank in g)
        return Group(ranks=mine, axis_name=mesh_axis)

    def get_data_parallel_group(self):
        return self._group("data")

    def get_model_parallel_group(self):
        return self._group("model")

    def get_pipe_parallel_group(self):
        return self._group("pipe")

    def get_sharding_parallel_group(self):
        return self._group("sharding")

    def get_check_parallel_group(self):
        from ..distributed.collective import Group

        return Group(ranks=list(range(self.nranks)), axis_name=None)

    def get_data_parallel_group_src_rank(self):
        return self._group("data").ranks[0]

    def get_model_parallel_group_src_rank(self):
        return self._group("model").ranks[0]

    def topology(self):
        return self._topo

    # mesh view ---------------------------------------------------------
    def mesh_shape(self) -> Dict[str, int]:
        return {
            "pp": self._pp_degree,
            "dp": self._dp_degree,
            "sharding": self._sharding_degree,
            "sep": self._sep_degree,
            "mp": self._mp_degree,
        }


def _build_mesh(shape: Dict[str, int], devices=None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    axes = [a for a in AXIS_ORDER if shape.get(a, 1) >= 1]
    dims = [shape.get(a, 1) for a in axes]
    n = int(np.prod(dims))
    if n > len(devices):
        raise ValueError(
            f"mesh {dict(zip(axes, dims))} needs {n} devices, "
            f"only {len(devices)} visible"
        )
    dev = np.asarray(devices[:n]).reshape(dims)
    return Mesh(dev, tuple(axes))


def init_mesh(dp=1, mp=1, pp=1, sharding=1, sep=1, devices=None) -> Mesh:
    """Create and install the global mesh (+ HCG view of it)."""
    topo = CommunicateTopology(
        ["pipe", "data", "sharding", "sep", "model"], [pp, dp, sharding, sep, mp]
    )
    hcg = HybridCommunicateGroup(topo)
    mesh = _build_mesh(hcg.mesh_shape(), devices)
    _global["hcg"] = hcg
    _global["mesh"] = mesh
    return mesh


def get_mesh() -> Optional[Mesh]:
    return _global["mesh"]


def axis_size(name: str, mesh: Optional[Mesh] = None) -> int:
    """Size of a named mesh axis (1 when absent or no mesh installed)."""
    mesh = mesh or _global["mesh"]
    if mesh is None:
        return 1
    return dict(zip(mesh.axis_names, mesh.devices.shape)).get(name, 1)


def set_mesh(mesh: Optional[Mesh], hcg=None) -> None:
    """Install a mesh (and matching hcg view, or clear it) atomically —
    keeps get_mesh()/get_hcg() consistent when a non-topology mesh (e.g. an
    auto-parallel ProcessMesh) takes over."""
    _global["mesh"] = mesh
    _global["hcg"] = hcg


class use_mesh:
    """Temporarily install `mesh` as the global mesh (hcg cleared), restoring
    the previous mesh+hcg on exit."""

    def __init__(self, mesh: Optional[Mesh], hcg=None):
        self._mesh = mesh
        self._hcg = hcg

    def __enter__(self):
        self._prev = (_global["mesh"], _global["hcg"])
        set_mesh(self._mesh, self._hcg)
        return self._mesh

    def __exit__(self, *exc):
        _global["mesh"], _global["hcg"] = self._prev
        return False


def get_hcg() -> Optional[HybridCommunicateGroup]:
    return _global["hcg"]


def _set_hcg(hcg):
    _global["hcg"] = hcg


def global_mesh() -> Mesh:
    m = _global["mesh"]
    if m is None:
        m = init_mesh(dp=len(jax.devices()))
    return m
