"""Pipeline parallelism: the GPipe/1F1B schedule over the `pp` mesh axis.

Reference analogue:
  - fleet/meta_parallel/pipeline_parallel.py:80 `forward_backward_pipeline`
    (1F1B over batched NCCL p2p: warmup recv/forward/send, steady 1F1B,
    cooldown) and pp_layers.py:132 `PipelineLayer` segmentation;
  - fleet_executor/carrier.h:49 actor runtime for cross-host pipelines.

TPU-native design (NOT a port): there is no NCCL p2p on TPU — stage-to-stage
transfer is an XLA CollectivePermute riding ICI, and the whole schedule lives
*inside one compiled SPMD program*:

  - stage weights are STACKED: every per-block parameter of the homogeneous
    middle run is stacked to a leading [num_layers, ...] dim and sharded
    P("pp", ...) so each pp rank physically holds only its stage's slice
    (the memory property that makes PP worth it);
  - the program is `shard_map`-manual over `pp` only; dp/sharding/mp/sep stay
    in GSPMD "auto" mode, so TP layers/ZeRO specs compose unchanged inside a
    stage;
  - a `lax.scan` over ticks implements the schedule: at tick t, stage s
    processes microbatch t-s; outputs rotate one stage forward via
    `ppermute` [(i, i+1)] (parity with p2p_communication.py's
    send_forward/recv_forward, but compiler-scheduled);
  - backward is jax.grad through the scan: XLA reverses the schedule into
    the backward pipeline automatically, with per-tick rematerialization
    (jax.checkpoint) bounding activation memory the way the reference pairs
    PP with recompute.

The embedding + head (pre/post stages) are small and run replicated on every
pp rank; only the selected rank's contribution carries gradient (where-mask +
psum), so the math matches the reference's first/last-stage placement while
keeping the program SPMD.
"""
from __future__ import annotations

from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .._jax_compat import shard_map

from ..core.dispatch import no_grad
from ..core.tensor import Tensor
from .topology import axis_size as _mesh_axis_size, get_mesh

__all__ = ["gpipe_loss", "PipelinedTrainStep", "pipelined_train_step"]


def _axis_size(mesh: Mesh, name: str) -> int:
    return _mesh_axis_size(name, mesh)


def gpipe_loss(
    stage_fn: Callable,
    inject_fn: Callable,
    head_loss_fn: Callable,
    stacked_local,
    x_mb,
    y_mb,
    *,
    num_stages: int,
    num_micro: int,
    axis: str = "pp",
    remat: bool = True,
):
    """GPipe forward inside a shard_map-manual-over-`axis` region → mean loss.

    stage_fn(stacked_local, h) -> h          one stage's block stack
    inject_fn(x_microbatch) -> h0            embedding (stage-0 injection)
    head_loss_fn(h, y_microbatch) -> scalar  final-ln + head + criterion
    x_mb/y_mb: [num_micro, mb, ...] microbatched inputs, replicated over pp.

    Returns the scalar loss, identical on every pp rank (psum of the
    last-stage contribution). Differentiable; grads of replicated params are
    psum'd by the shard_map transpose.
    """
    S, M = num_stages, num_micro
    s_idx = jax.lax.axis_index(axis)
    apply_stage = jax.checkpoint(stage_fn) if remat else stage_fn

    # activation shape probe (no FLOPs at runtime: dead-code eliminated
    # unless needed): stage I/O shape == embedding output shape
    h0_shape = jax.eval_shape(inject_fn, jax.eval_shape(lambda: x_mb[0]))
    zeros_h = jnp.zeros(h0_shape.shape, h0_shape.dtype)

    def tick(carry, t):
        state, outputs = carry
        # stage 0 injects microbatch t (clamped in cooldown; results unused)
        xt = jax.lax.dynamic_index_in_dim(
            x_mb, jnp.clip(t, 0, M - 1), axis=0, keepdims=False
        )
        h_in = jnp.where(s_idx == 0, inject_fn(xt), state)
        y = apply_stage(stacked_local, h_in)
        # last stage's tick t output is microbatch t-(S-1); warmup garbage
        # lands on slot 0 and is overwritten at t = S-1
        out_idx = jnp.clip(t - (S - 1), 0, M - 1)
        outputs = jax.lax.dynamic_update_index_in_dim(outputs, y, out_idx, axis=0)
        # rotate activations one stage forward (reference: p2p send_forward /
        # recv_forward pairs); edge ranks receive zeros
        state = jax.lax.ppermute(y, axis, [(i, i + 1) for i in range(S - 1)])
        return (state, outputs), None

    outputs0 = jnp.zeros((M,) + h0_shape.shape, h0_shape.dtype)
    (_, outputs), _ = jax.lax.scan(
        tick, (zeros_h, outputs0), jnp.arange(M + S - 1)
    )

    # head + loss per microbatch, scanned to keep one microbatch of logits
    # live at a time; only the last pp rank's value is real
    def head_tick(acc, my):
        h, y = my
        return acc + head_loss_fn(h, y).astype(acc.dtype), None

    loss_sum, _ = jax.lax.scan(head_tick, jnp.zeros((), jnp.float32), (outputs, y_mb))
    loss_local = loss_sum / M
    return jax.lax.psum(jnp.where(s_idx == S - 1, loss_local, 0.0), axis)


def _collect_blocks(model):
    """Resolve the pipeline partition protocol on `model`:
    (pre_fn, blocks, post_fn). Models expose pp_embed/pp_blocks/pp_head
    (GPTForPretraining); PipelineLayer gets the homogeneous-middle adapter."""
    if hasattr(model, "pp_blocks"):
        blocks = list(model.pp_blocks)
        return model.pp_embed, blocks, model.pp_head
    raise TypeError(
        f"{type(model).__name__} is not pipeline-partitionable: expose "
        "pp_embed(x)/pp_blocks/pp_head(h) or use fleet.PipelineLayer"
    )


def _named_params(layer) -> List[Tensor]:
    return [p for _, p in sorted(layer.named_parameters(), key=lambda kv: kv[0])]


class PipelinedTrainStep:
    """Compiled pipeline-parallel train step (composes with dp/mp/sharding).

    One XLA program: stacked block params (pp-sharded dim 0), replicated
    embed/head params (mp/ZeRO specs honored in GSPMD auto mode), GPipe scan,
    loss, grads, optimizer update — with buffer donation.
    Reference counterpart: PipelineParallel.train_batch →
    forward_backward_pipeline (pipeline_parallel.py:80) + optimizer step.
    """

    def __init__(self, model, loss_fn, optimizer, mesh: Optional[Mesh] = None,
                 num_micro: int = 4, zero_stage: int = 0, remat: bool = True,
                 forward_ctx=None):
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        # zero-arg context-manager factory around every traced forward
        # region (fleet wires strategy.amp through here)
        import contextlib

        self.forward_ctx = forward_ctx or contextlib.nullcontext
        self.mesh = mesh or get_mesh()
        if self.mesh is None:
            raise RuntimeError("pipeline parallelism requires an initialized mesh")
        self.S = _axis_size(self.mesh, "pp")
        self.M = num_micro
        self.zero_stage = zero_stage
        self.remat = remat

        pre_fn, blocks, post_fn = _collect_blocks(model)
        if len(blocks) % max(self.S, 1) != 0:
            raise ValueError(
                f"num blocks {len(blocks)} not divisible by pp={self.S}"
            )
        self.pre_fn, self.blocks, self.post_fn = pre_fn, blocks, post_fn
        self.template = blocks[0]
        self.block_param_objs = [_named_params(b) for b in blocks]
        t_shapes = [tuple(p.shape) for p in self.block_param_objs[0]]
        for ps in self.block_param_objs[1:]:
            if [tuple(p.shape) for p in ps] != t_shapes:
                raise ValueError("pipeline middle blocks are not homogeneous")
        # params outside the blocks (embedding, final ln, head) stay unstacked
        block_ids = {id(p) for ps in self.block_param_objs for p in ps}
        self._repl_params = [
            p for p in model.parameters()
            if id(p) not in block_ids and not p.stop_gradient
        ]
        self._buffers = [b for _, b in model.named_buffers()]
        if self._buffers:
            # buffer mutation (BatchNorm running stats) inside the scanned
            # schedule cannot escape the scan trace; ShardedTrainStep threads
            # buffers out, this step cannot yet
            names = [n for n, _ in model.named_buffers()]
            raise ValueError(
                "pipelined training does not support layers with buffers "
                f"(running statistics) yet: {names[:5]} — use LayerNorm/"
                "GroupNorm in the pipelined middle or pp_degree=1"
            )
        self._hyper = optimizer._hyper()
        self._step = None
        self._loss_program = None  # forward GPipe loss (for the analyzer)
        self._stacked = None      # list of [L, ...] arrays, one per block param
        self._stacked_state = None
        self._repl_state = None

    # ---- sharding specs ---------------------------------------------------
    def _stacked_spec(self, p: Tensor) -> P:
        """P('pp', <dist_spec of the block param>); ZeRO additionally shards
        a free dim over 'sharding' (stage-local ZeRO, like the reference's
        pp+sharding hybrid)."""
        base = list(getattr(p, "dist_spec", None) or [None] * p.ndim)
        base += [None] * (p.ndim - len(base))
        if self.zero_stage >= 3:
            n_shard = _axis_size(self.mesh, "sharding")
            if n_shard > 1:
                for d in range(p.ndim):
                    if base[d] is None and p.shape[d] % n_shard == 0:
                        base[d] = "sharding"
                        break
        return P("pp", *base)

    def _repl_spec(self, p: Tensor) -> P:
        from .sharding import param_spec

        return param_spec(p, self.zero_stage, self.mesh)

    def _state_specs(self, spec: P, shape) -> P:
        # optimizer state mirrors its param's spec (incl. the pp dim)
        entries = list(spec) + [None] * (len(shape) - len(list(spec)))
        return P(*entries) if len(shape) > 0 else P()

    # ---- state ------------------------------------------------------------
    def _init_stacked(self):
        vals = []
        for j in range(len(self.block_param_objs[0])):
            vals.append(
                jnp.stack([ps[j]._value for ps in self.block_param_objs])
            )
        return vals

    def _make_state(self, val) -> dict:
        t = Tensor(val, stop_gradient=True)
        return self.optimizer._create_state(t)

    def _init_stacked_state(self):
        """Stacked optimizer moments; honors state restored by
        set_state_dict (checkpoint resume) when every block has it."""
        acc = self.optimizer._accumulators
        out = []
        for j, stacked in enumerate(self._stacked):
            per_layer = [acc.get(id(ps[j])) for ps in self.block_param_objs]
            if all(st is not None for st in per_layer):
                # scalar states (beta-pow step counters) are shared across
                # layers, tensor states stack along the layer dim
                out.append(
                    {
                        k: (
                            per_layer[0][k]
                            if jnp.ndim(per_layer[0][k]) == 0
                            else jnp.stack([st[k] for st in per_layer])
                        )
                        for k in per_layer[0].keys()
                    }
                )
            else:
                out.append(self._make_state(stacked))
        return out

    def _init_repl_state(self):
        acc = self.optimizer._accumulators
        out = []
        for p in self._repl_params:
            st = acc.get(id(p))
            out.append(dict(st) if st is not None else self._make_state(p._value))
        return out

    # ---- lazy write-back (state_dict / checkpoint paths) -------------------
    def sync_params(self):
        """Materialize the authoritative stacked weights back into the live
        per-layer param Tensors (invoked lazily from Layer.state_dict)."""
        if self._stacked is None:
            return
        with no_grad():
            for li, ps in enumerate(self.block_param_objs):
                for j, p in enumerate(ps):
                    p._value = self._stacked[j][li]

    def sync_opt_state(self):
        """Write stacked/replicated moments back into optimizer._accumulators
        (invoked lazily from Optimizer.state_dict)."""
        if self._stacked_state is None:
            return
        acc = self.optimizer._accumulators
        for j, st in enumerate(self._stacked_state):
            for li, ps in enumerate(self.block_param_objs):
                cur = acc.setdefault(id(ps[j]), {})
                for k, v in st.items():
                    cur[k] = v if jnp.ndim(v) == 0 else v[li]
        for p, st in zip(self._repl_params, self._repl_state):
            acc[id(p)] = dict(st)

    # ---- build ------------------------------------------------------------
    def _step_parts(self):
        """(step_fn, in_shardings, out_shardings) pre-jit — the sharding
        analyzer traces step_fn at per-shard shapes without compiling;
        _build wraps the same triple in jax.jit."""
        from ..jit import _bind_values
        from ..core import random as _random

        mesh, S, M = self.mesh, self.S, self.M
        model, loss_fn, opt = self.model, self.loss_fn, self.optimizer
        template_params = self.block_param_objs[0]
        t_objs = _named_params(self.template)
        repl_params, buffers = self._repl_params, self._buffers
        pre_fn, post_fn = self.pre_fn, self.post_fn
        L_per = len(self.blocks) // S
        hyper = self._hyper
        per_hyper_stack = [
            dict(hyper, **opt._per_param_hyper(p)) for p in template_params
        ]
        per_hyper_repl = [dict(hyper, **opt._per_param_hyper(p)) for p in repl_params]
        rule = type(opt)._update
        grad_clip = opt._grad_clip
        remat = self.remat

        stacked_specs = [self._stacked_spec(p) for p in template_params]
        repl_specs = [self._repl_spec(p) for p in repl_params]

        from .sharding import suppress_sharding_constraints

        def body(repl_vals, stacked_locals, b_vals, key, x_mb, y_mb):
            """Runs per-(pp, dp, sharding)-rank; mp stays GSPMD-auto so TP
            weight shardings propagate inside the stage. Making the batch
            axes MANUAL pins every activation's dp sharding — GSPMD-auto dp
            used to replicate-then-repartition activations between the scan
            carries and the in-stage program ('Involuntary full
            rematerialization' churn)."""
            with _random.rng_scope(key), suppress_sharding_constraints():
                fwd_ctx = self.forward_ctx

                def stage_fn(locals_, h):
                    for i in range(L_per):
                        slice_vals = [v[i] for v in locals_]
                        with _bind_values(t_objs, slice_vals), no_grad(), \
                                fwd_ctx():
                            h = self.template(
                                Tensor(h, stop_gradient=True)
                            )._value
                    return h

                def inject_fn(xt):
                    with _bind_values(repl_params + buffers,
                                      list(repl_vals) + list(b_vals)), \
                            no_grad(), fwd_ctx():
                        return pre_fn(Tensor(xt, stop_gradient=True))._value

                def head_loss_fn(h, y):
                    with _bind_values(repl_params + buffers,
                                      list(repl_vals) + list(b_vals)), \
                            no_grad(), fwd_ctx():
                        out = post_fn(Tensor(h, stop_gradient=True))
                        loss = (
                            loss_fn(out, Tensor(y, stop_gradient=True))
                            if loss_fn is not None else out
                        )
                    lv = loss._value if isinstance(loss, Tensor) else loss
                    if lv.ndim > 0:  # parity with the pp==1 path's loss.mean()
                        lv = lv.mean()
                    return lv.astype(jnp.float32)

                loss = gpipe_loss(
                    stage_fn, inject_fn, head_loss_fn, stacked_locals,
                    x_mb, y_mb, num_stages=S, num_micro=M, remat=remat,
                )
                # local-batch mean → global-batch mean (dp ranks hold
                # disjoint microbatch slices under the manual batch axis;
                # the 'sharding' slice of the batch stays GSPMD-auto because
                # ZeRO-3 shards stage weights over it in-stage)
                return jax.lax.pmean(loss, "dp")

        smapped = shard_map(
            body, mesh=mesh,
            in_specs=(P(), P("pp"), P(), P(),
                      P(None, "dp"), P(None, "dp")),
            out_specs=P(),
            axis_names={"pp", "dp"}, check_vma=False,
        )

        def loss_program(repl_vals, stacked_vals, b_vals, key, x, y):
            # forward GPipe loss only — the static analyzer traces this when
            # jax<0.5 cannot differentiate through shard_map (same schedule,
            # same ppermute/psum collectives, no optimizer tail)
            x_mb = x.reshape((M, x.shape[0] // M) + x.shape[1:])
            y_mb = y.reshape((M, y.shape[0] // M) + y.shape[1:])
            return smapped(tuple(repl_vals), tuple(stacked_vals),
                           tuple(b_vals), key, x_mb, y_mb)

        self._loss_program = loss_program

        def step_fn(repl_vals, stacked_vals, repl_states, stacked_states,
                    b_vals, key, lr, x, y):
            # microbatch: [B, ...] -> [M, B//M, ...]
            x_mb = x.reshape((M, x.shape[0] // M) + x.shape[1:])
            y_mb = y.reshape((M, y.shape[0] // M) + y.shape[1:])
            loss, (g_repl, g_stacked) = jax.value_and_grad(
                smapped, argnums=(0, 1)
            )(tuple(repl_vals), tuple(stacked_vals), tuple(b_vals), key, x_mb, y_mb)

            if grad_clip is not None:
                # one global clip over replicated + stacked grads (the
                # stacked arrays already hold all layers, so the global norm
                # matches the unstacked model's)
                n_r = len(repl_vals)
                pairs = grad_clip(
                    [
                        (Tensor(pv, stop_gradient=True), Tensor(gv, stop_gradient=True))
                        for pv, gv in zip(
                            list(repl_vals) + list(stacked_vals),
                            list(g_repl) + list(g_stacked),
                        )
                    ]
                )
                clipped = [g._value for _, g in pairs]
                g_repl, g_stacked = clipped[:n_r], clipped[n_r:]

            new_repl, new_rs = [], []
            for pv, gv, st, h in zip(repl_vals, g_repl, repl_states, per_hyper_repl):
                if gv.dtype != pv.dtype:
                    gv = gv.astype(pv.dtype)
                np_, ns_ = rule(opt, pv, gv, lr, st, **h)
                new_repl.append(np_)
                new_rs.append(ns_)
            new_stacked, new_ss = [], []
            for pv, gv, st, h in zip(stacked_vals, g_stacked, stacked_states,
                                     per_hyper_stack):
                if gv.dtype != pv.dtype:
                    gv = gv.astype(pv.dtype)
                np_, ns_ = rule(opt, pv, gv, lr, st, **h)
                new_stacked.append(np_)
                new_ss.append(ns_)
            return loss, tuple(new_repl), tuple(new_stacked), tuple(new_rs), tuple(new_ss)

        repl_sh = tuple(NamedSharding(mesh, s) for s in repl_specs)
        stacked_sh = tuple(NamedSharding(mesh, s) for s in stacked_specs)
        rs_sh = tuple(
            {k: NamedSharding(mesh, self._state_specs(spec, v.shape))
             for k, v in st.items()}
            for spec, st in zip(repl_specs, self._repl_state)
        )
        ss_sh = tuple(
            {k: NamedSharding(mesh, self._state_specs(spec, v.shape))
             for k, v in st.items()}
            for spec, st in zip(stacked_specs, self._stacked_state)
        )
        repl = NamedSharding(mesh, P())
        batch_sh = NamedSharding(mesh, P(("dp", "sharding")))
        in_sh = (repl_sh, stacked_sh, rs_sh, ss_sh,
                 tuple(repl for _ in self._buffers), repl, repl,
                 batch_sh, batch_sh)
        out_sh = (repl, repl_sh, stacked_sh, rs_sh, ss_sh)
        return step_fn, in_sh, out_sh

    def _build(self):
        step_fn, in_sh, out_sh = self._step_parts()
        return jax.jit(
            step_fn, in_shardings=in_sh, out_shardings=out_sh,
            donate_argnums=(0, 1, 2, 3),
        )

    def _check_programs(self, batch):
        """FLAGS_check_programs gate before the first compile — the same
        per-shard analysis suite ShardedTrainStep runs (1 = warn,
        2 = raise on errors); trace failures never block training."""
        from ..core.flags import flag as _flag

        if not int(_flag("check_programs")):
            return
        try:
            from ..analysis import enforce
            from ..analysis.sharding import check_sharded_step

            specs = [
                jax.ShapeDtypeStruct(
                    tuple((b._value if isinstance(b, Tensor)
                           else np.asarray(b)).shape),
                    (b._value if isinstance(b, Tensor)
                     else np.asarray(b)).dtype,
                )
                for b in batch
            ]
            diags = check_sharded_step(self, specs, source="pipelined-step")
        except Exception:
            return
        enforce(diags, "pipelined_train_step")

    # ---- call -------------------------------------------------------------
    @no_grad()
    def __call__(self, x, y) -> Tensor:
        from ..core import random as _random

        if self._step is None:
            self._stacked = self._init_stacked()
            self._stacked_state = self._init_stacked_state()
            self._repl_state = self._init_repl_state()
            self._check_programs((x, y))
            self._step = self._build()
            # lazy write-back hooks: state_dict() on the model/optimizer
            # pulls the authoritative stacked values without paying the
            # per-step gather cost
            self.model._lazy_param_sync = self.sync_params
            self.optimizer._lazy_state_sync = self.sync_opt_state
            # physically place stacked params/state so donation matches
            for j, v in enumerate(self._stacked):
                sh = NamedSharding(self.mesh, self._stacked_spec(
                    self.block_param_objs[0][j]))
                self._stacked[j] = jax.device_put(v, sh)
                self._stacked_state[j] = {
                    k: jax.device_put(sv, NamedSharding(
                        self.mesh, self._state_specs(
                            self._stacked_spec(self.block_param_objs[0][j]),
                            sv.shape)))
                    for k, sv in self._stacked_state[j].items()
                }
        batch_sh = NamedSharding(self.mesh, P(("dp", "sharding")))
        xv = x._value if isinstance(x, Tensor) else jnp.asarray(x)
        yv = y._value if isinstance(y, Tensor) else jnp.asarray(y)
        if xv.shape[0] % self.M != 0:
            raise ValueError(
                f"batch size {xv.shape[0]} not divisible by "
                f"accumulate_steps/num_micro={self.M}"
            )
        xv = jax.device_put(xv, batch_sh)
        yv = jax.device_put(yv, batch_sh)
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        key = _random.next_key()
        repl_vals = tuple(p._value for p in self._repl_params)
        b_vals = tuple(b._value for b in self._buffers)
        loss, new_repl, new_stacked, new_rs, new_ss = self._step(
            repl_vals, tuple(self._stacked), tuple(self._repl_state),
            tuple(self._stacked_state), b_vals, key, lr, xv, yv,
        )
        for p, v in zip(self._repl_params, new_repl):
            p._value = v
        self._stacked = list(new_stacked)
        self._repl_state = list(new_rs)
        self._stacked_state = list(new_ss)
        # live block params are synced lazily (sync_params via state_dict);
        # repl params were rebound above and accumulators for them flow
        # through sync_opt_state
        self.optimizer._step_count += 1
        return Tensor(loss, stop_gradient=True)


def pipelined_train_step(model, loss_fn, optimizer, mesh=None, num_micro=4,
                         zero_stage=0, remat=True, forward_ctx=None):
    return PipelinedTrainStep(
        model, loss_fn, optimizer, mesh, num_micro, zero_stage, remat,
        forward_ctx
    )
