"""True int8 execution path.

Reference analogue: the slim stack's quantized inference ops
(quantize/dequantize + int8 conv/mul kernels dispatched by the analysis
passes). TPU-native: `lax.dot_general` on int8 operands with an int32
accumulator — exactly the MXU's 8-bit mode (the chip's int8 throughput is
~2x its bf16 FLOPs; PROFILE_RESNET.md measured 161 TOP/s) — then a float
dequant fused in by XLA.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply
from ..core.tensor import Tensor
from ..nn.layer_base import Layer

__all__ = ["quantize_weight_int8", "int8_matmul", "Int8Linear"]


def quantize_weight_int8(w: np.ndarray, axis: int = -1):
    """Per-channel symmetric int8 weights (reference
    channel_wise_abs_max): returns (int8 array, float32 per-channel
    scales broadcastable along `axis`)."""
    w = np.asarray(w, np.float32)
    red = tuple(i for i in range(w.ndim) if i != (axis % w.ndim))
    scale = np.maximum(np.max(np.abs(w), axis=red, keepdims=True), 1e-8)
    q = np.clip(np.round(w / scale * 127.0), -127, 127).astype(np.int8)
    return q, scale.astype(np.float32)


def _int8_dot(xq, wq):
    """int8 x int8 -> int32 dot (the MXU 8-bit path)."""
    return jax.lax.dot_general(
        xq, wq, (((xq.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )


def int8_matmul(x, w_int8, w_scale, act_scale):
    """Quantize x to int8 with `act_scale`, run the int8 dot, dequantize.

    out = (xq @ wq) * (act_scale/127) * (w_scale/127) — all the float work
    is elementwise on the int32 accumulator, which XLA fuses.
    """
    def fn(xv, wq, wscale, ascale):
        xq = jnp.clip(jnp.round(xv / ascale * 127.0), -127, 127).astype(
            jnp.int8
        )
        acc = _int8_dot(xq, wq)
        return acc.astype(jnp.float32) * (ascale / 127.0) * (
            wscale.reshape(1, -1) / 127.0
        )

    return apply(fn, x, w_int8, w_scale, act_scale, op_name="int8_matmul",
                 differentiable=False)


class Int8Linear(Layer):
    """Inference-only Linear with int8-stored weights and the int8 MXU dot
    (what ConvertToInt8Pass lowers a calibrated QuantedLinear to). Weight
    memory is 4x smaller than f32; the matmul runs on the 8-bit path."""

    def __init__(self, w_int8: np.ndarray, w_scale: np.ndarray,
                 bias, act_scale: float):
        super().__init__()
        self.register_buffer("weight_int8",
                             Tensor(jnp.asarray(w_int8, jnp.int8)))
        self.register_buffer("weight_scale",
                             Tensor(jnp.asarray(w_scale, jnp.float32)))
        self.register_buffer(
            "act_scale", Tensor(jnp.asarray(float(act_scale), jnp.float32))
        )
        self.bias = bias

    @classmethod
    def from_quanted(cls, qlinear) -> "Int8Linear":
        w = np.asarray(qlinear._linear.weight._value)
        wq, ws = quantize_weight_int8(w, axis=-1)
        act_scale = float(np.asarray(qlinear.fq_act.scale._value))
        if act_scale <= 0:
            raise ValueError(
                "QuantedLinear has no calibrated activation scale — run "
                "calibration (PTQ) or training (QAT) first"
            )
        return cls(wq, ws.reshape(-1), qlinear._linear.bias, act_scale)

    def forward(self, x):
        shape = list(x.shape)
        x2 = x.reshape([-1, shape[-1]]) if x.ndim > 2 else x
        out = int8_matmul(x2, self.weight_int8, self.weight_scale,
                          self.act_scale)
        if self.bias is not None:
            out = out + self.bias
        if len(shape) > 2:
            out = out.reshape(shape[:-1] + [out.shape[-1]])
        return out
