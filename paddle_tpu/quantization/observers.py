"""Activation/weight observers for calibration.

Reference analogue: slim/quantization's calibration machinery —
post_training_quantization.py collects abs_max / histogram ranges
(algo="abs_max" | "KL" | "hist" | "mse" | "avg") per tensor before
computing the frozen quantization scales. Each observer here consumes
calibration batches via `collect(x)` and yields a scalar (or per-channel)
`scale()`.
"""
from __future__ import annotations

import numpy as np

__all__ = ["AbsMaxObserver", "EMAAbsMaxObserver", "HistObserver",
           "MSEObserver", "make_observer"]


class AbsMaxObserver:
    """Running max of |x| (reference algo='abs_max').

    scale() is 0.0 until data arrives — an uncalibrated layer must be
    DISTINGUISHABLE (FreezeScalesPass skips it loudly) rather than get a
    degenerate epsilon scale that crushes its outputs."""

    def __init__(self, bits: int = 8):
        self.bits = bits
        self._max = 0.0
        self._seen = False

    def collect(self, x: np.ndarray):
        self._max = max(self._max, float(np.max(np.abs(x))))
        self._seen = True

    def scale(self) -> float:
        return max(self._max, 1e-8) if self._seen else 0.0


class EMAAbsMaxObserver:
    """Exponential moving average of per-batch abs-max (reference
    algo='avg' family / moving_average_abs_max)."""

    def __init__(self, bits: int = 8, rate: float = 0.9):
        self.bits = bits
        self.rate = rate
        self._state = None

    def collect(self, x: np.ndarray):
        cur = float(np.max(np.abs(x)))
        self._state = cur if self._state is None else (
            self.rate * self._state + (1 - self.rate) * cur
        )

    def scale(self) -> float:
        if self._state is None:
            return 0.0
        return max(self._state, 1e-8)


class HistObserver:
    """Percentile-of-histogram range (reference algo='hist'): clips the
    long activation tail that abs-max would waste quantization bins on."""

    def __init__(self, bits: int = 8, bins: int = 2048,
                 percentile: float = 0.9999):
        self.bits = bits
        self.bins = bins
        self.percentile = percentile
        self._hist = np.zeros(bins, np.float64)
        self._max = 0.0

    def collect(self, x: np.ndarray):
        a = np.abs(np.asarray(x, np.float32)).reshape(-1)
        m = float(a.max()) if a.size else 0.0
        if m == 0.0:
            return
        if m > self._max:
            # remap the existing histogram onto the wider range: old bin i
            # (center (i+0.5)*old_max/bins) lands in new bin
            # floor((i+0.5)*old_max/new_max)
            if self._max > 0.0:
                ratio = self._max / m
                old = self._hist
                self._hist = np.zeros(self.bins, np.float64)
                idx = np.clip(
                    ((np.arange(self.bins) + 0.5) * ratio).astype(np.int64),
                    0, self.bins - 1,
                )
                np.add.at(self._hist, idx, old)
            self._max = m
        h, _ = np.histogram(a, bins=self.bins, range=(0.0, self._max))
        self._hist += h

    def scale(self) -> float:
        total = self._hist.sum()
        if total <= 0:
            return 0.0  # uncalibrated — see AbsMaxObserver
        cdf = np.cumsum(self._hist) / total
        idx = int(np.searchsorted(cdf, self.percentile))
        return max((idx + 1) / self.bins * self._max, 1e-8)


class MSEObserver:
    """Scale minimizing quantization MSE over a retained sample
    (reference algo='mse': grid-search candidate clips)."""

    def __init__(self, bits: int = 8, sample: int = 65536, steps: int = 40,
                 seed: int = 0):
        self.bits = bits
        self.sample = sample
        self.steps = steps
        self._data = None
        self._max = 0.0
        self._rng = np.random.default_rng(seed)

    def collect(self, x: np.ndarray):
        a = np.asarray(x, np.float32).reshape(-1)
        self._max = max(self._max, float(np.max(np.abs(a))) if a.size else 0.0)
        if a.size > self.sample:
            stride = a.size // self.sample
            a = a[::stride][: self.sample]
        if self._data is None:
            self._data = a
        else:
            # random down-sample of the POOLED data — keeping only the
            # last batch (a sliding window) would fit the clip to the
            # final batch's distribution alone
            pool = np.concatenate([self._data, a])
            if pool.size > self.sample:
                idx = self._rng.choice(pool.size, self.sample, replace=False)
                pool = pool[idx]
            self._data = pool

    def scale(self) -> float:
        if self._data is None or self._max == 0.0:
            return 0.0  # uncalibrated — see AbsMaxObserver
        qmax = 2 ** (self.bits - 1) - 1
        best, best_err = self._max, np.inf
        for k in range(1, self.steps + 1):
            s = self._max * k / self.steps
            q = np.clip(np.round(self._data / s * qmax), -qmax, qmax) \
                / qmax * s
            err = float(np.mean((q - self._data) ** 2))
            if err < best_err:
                best, best_err = s, err
        return max(best, 1e-8)


_OBSERVERS = {
    "abs_max": AbsMaxObserver,
    "avg": EMAAbsMaxObserver,
    "hist": HistObserver,
    "mse": MSEObserver,
}


def make_observer(algo: str, bits: int = 8):
    if algo not in _OBSERVERS:
        raise ValueError(f"algo must be one of {sorted(_OBSERVERS)}")
    return _OBSERVERS[algo](bits=bits)
