"""Pass-driven post-training quantization.

Reference analogue: slim/quantization/quantization_pass.py — the static
PTQ pipeline is a sequence of program passes (QuantizationTransformPass
inserts quant/dequant + observer ops, the calibration run fills ranges,
QuantizationFreezePass folds scales in, and the int8 conversion pass
lowers to quantized kernels). The TPU build's "program" is the layer
graph; each pass below rewrites it with the same division of labor:

    InsertObserversPass  -> hook an observer on every quantizable layer
    CalibratePass        -> stream calibration batches through the model
    FreezeScalesPass     -> swap layers for fake-quant wrappers with the
                            calibrated scales frozen in
    ConvertToInt8Pass    -> (optional, inference) lower calibrated Linears
                            to Int8Linear running the int8 MXU dot

`QuantPassManager.run()` applies them in order; every pass reports what it
touched so nothing happens silently.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ..core.dispatch import no_grad
from ..core.tensor import Tensor
from ..nn.layer_base import Layer
from .observers import make_observer

__all__ = ["QuantConfig", "InsertObserversPass", "CalibratePass",
           "FreezeScalesPass", "ConvertToInt8Pass", "QuantPassManager"]


class QuantConfig:
    def __init__(self, quantizable_layer_type=("Conv2D", "Linear",
                                               "Embedding",
                                               "ColumnParallelLinear",
                                               "RowParallelLinear"),
                 weight_bits: int = 8, activation_bits: int = 8,
                 algo: str = "abs_max"):
        self.types = tuple(quantizable_layer_type)
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.algo = algo


class _PassState:
    """What flows between passes: per-layer observers and frozen scales."""

    def __init__(self, model: Layer, config: QuantConfig):
        self.model = model
        self.config = config
        self.observers: Dict[str, object] = {}
        self.scales: Dict[str, float] = {}
        self._handles: List = []
        self.report: Dict[str, object] = {}


class InsertObserversPass:
    """Attach an activation observer ahead of every quantizable layer
    (reference: QuantizationTransformPass's observer insertion)."""

    name = "insert_observers"

    def apply(self, st: _PassState):
        cfg = st.config
        n = 0
        for name, layer in st.model.named_sublayers():
            if type(layer).__name__ not in cfg.types:
                continue
            obs = make_observer(cfg.algo, bits=cfg.activation_bits)
            st.observers[name] = obs

            def hook(lyr, inputs, _obs=obs):
                x = inputs[0] if isinstance(inputs, (tuple, list)) else inputs
                _obs.collect(np.asarray(x._value))

            st._handles.append(layer.register_forward_pre_hook(hook))
            n += 1
        st.report[self.name] = n
        if n == 0:
            raise ValueError(
                f"no quantizable layers of types {cfg.types} found"
            )


class CalibratePass:
    """Stream calibration batches through the float model."""

    name = "calibrate"

    def __init__(self, data_loader, batch_nums: Optional[int] = None):
        self.loader = data_loader
        self.batch_nums = batch_nums

    def apply(self, st: _PassState):
        import jax.numpy as jnp

        st.model.eval()
        seen = 0
        try:
            with no_grad():
                for i, batch in enumerate(self.loader):
                    if self.batch_nums is not None and i >= self.batch_nums:
                        break
                    x = batch[0] if isinstance(batch, (tuple, list)) else batch
                    if not isinstance(x, Tensor):
                        x = Tensor(jnp.asarray(np.asarray(x)))
                    st.model(x)
                    seen += 1
        finally:
            # a failing calibration batch must not leak observer hooks
            # onto the float model
            for h in st._handles:
                h.remove()
            st._handles.clear()
        for name, obs in st.observers.items():
            st.scales[name] = float(obs.scale())
        st.report[self.name] = seen
        if seen == 0:
            raise ValueError("calibration loader yielded no batches")


class FreezeScalesPass:
    """Swap quantizable layers for fake-quant wrappers carrying the frozen
    calibrated scales (reference: QuantizationFreezePass)."""

    name = "freeze_scales"

    def apply(self, st: _PassState):
        import jax.numpy as jnp

        from . import _QUANT_MAP

        import warnings

        cfg = st.config
        n = 0
        skipped = []
        was_training = st.model.training
        names = {id(l): nm for nm, l in st.model.named_sublayers()}
        for parent in st.model.sublayers(include_self=True):
            for cname, child in list(parent._sub_layers.items()):
                tname = type(child).__name__
                if tname not in cfg.types or tname not in _QUANT_MAP:
                    continue
                full = names.get(id(child), "")
                scale = st.scales.get(full, 0.0)
                if full in st.scales and scale <= 0.0:
                    # the calibration data never reached this layer — a
                    # 0-scale wrapper would silently crush its outputs
                    warnings.warn(
                        f"layer {full!r} received no calibration data; "
                        "left unquantized"
                    )
                    skipped.append(full)
                    continue
                wrapped = _QUANT_MAP[tname](
                    child, cfg.weight_bits, cfg.activation_bits,
                )
                if scale > 0 and hasattr(wrapped, "fq_act"):
                    with no_grad():
                        wrapped.fq_act.scale._value = jnp.asarray(
                            scale, jnp.float32
                        )
                # wrappers are born training=True; match the model (PTQ
                # returns an inference-ready model — a training-mode
                # fq_act would overwrite the frozen scale on first use)
                if not was_training:
                    wrapped.eval()
                setattr(parent, cname, wrapped)
                n += 1
        st.report[self.name] = n
        if skipped:
            st.report[self.name + "_skipped_uncalibrated"] = skipped


class ConvertToInt8Pass:
    """Lower calibrated QuantedLinear layers to Int8Linear — int8-stored
    weights + the int8 MXU dot (inference only)."""

    name = "convert_int8"

    def apply(self, st: _PassState):
        from . import QuantedLinear
        from .int8 import Int8Linear

        n = 0
        for parent in st.model.sublayers(include_self=True):
            for cname, child in list(parent._sub_layers.items()):
                if isinstance(child, QuantedLinear):
                    setattr(parent, cname, Int8Linear.from_quanted(child))
                    n += 1
        st.report[self.name] = n


class QuantPassManager:
    """Apply quantization passes in order (reference: the pass pipeline in
    post_training_quantization.py quantize())."""

    def __init__(self, passes: List):
        self.passes = list(passes)

    def run(self, model: Layer, config: QuantConfig) -> "_PassState":
        st = _PassState(model, config)
        for p in self.passes:
            p.apply(st)
        return st
