"""paddle.quantization — QAT fake-quant + post-training calibration.

Reference analogue: python/paddle/fluid/contrib/slim/quantization/
(ImperativeQuantAware in imperative/qat.py — replaces Conv2D/Linear with
QuantizedConv2D/QuantizedLinear carrying fake_quant ops; PostTraining
Quantization collects activation ranges over calibration data; fake-quant
kernels fake_quantize_op.cc: abs_max, channel_wise_abs_max,
moving_average_abs_max).

TPU-native design: fake-quant is pure jnp math recorded on the tape with a
straight-through estimator (x + stop_gradient(quant(x) - x)) — no
registered STE grad kernels needed. Scales live in layer buffers so
state_dict round-trips them and `save_quantized_model` bakes them into the
StableHLO artifact. Int8 *execution* maps to XLA int8 dots when the
deployment runtime chooses; the artifact carries exact scale metadata.
"""
from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply, no_grad
from ..core.tensor import Tensor
from ..nn.layer_base import Layer
from .. import nn

__all__ = [
    "ImperativeQuantAware",
    "PostTrainingQuantization",
    "QuantedLinear",
    "QuantedConv2D",
    "QuantedEmbedding",
    "fake_quant_abs_max",
    "fake_quant_channel_wise_abs_max",
    "observers",
    "passes",
    "int8",
]


# ---------------------------------------------------------------------------
# fake-quant ops (reference: operators/fake_quantize_op.cc kernels)
# ---------------------------------------------------------------------------
def _ste(x, q):
    """Straight-through estimator: forward q, backward identity."""
    return x + jax.lax.stop_gradient(q - x)


def _fq_abs_max(x, *, bits=8):
    qmax = float(2 ** (bits - 1) - 1)
    scale = jnp.maximum(jnp.max(jnp.abs(x)), 1e-8)
    q = jnp.round(x / scale * qmax) / qmax * scale
    return _ste(x, q), scale


def _fq_channel_abs_max(w, *, bits=8, axis=-1):
    """Per-output-channel abs-max (reference: channel_wise_abs_max for
    weights; paddle Linear weight is [in, out] so channels are axis -1)."""
    qmax = float(2 ** (bits - 1) - 1)
    red = tuple(i for i in range(w.ndim) if i != (axis % w.ndim))
    scale = jnp.maximum(jnp.max(jnp.abs(w), axis=red, keepdims=True), 1e-8)
    q = jnp.round(w / scale * qmax) / qmax * scale
    return _ste(w, q), scale.reshape(-1)


def _fq_moving_avg(x, state, *, bits=8, rate=0.9):
    """moving_average_abs_max: running activation scale (training); the
    accumulated scale is what inference uses."""
    qmax = float(2 ** (bits - 1) - 1)
    cur = jnp.max(jnp.abs(x))
    new_state = jnp.where(state > 0, rate * state + (1 - rate) * cur, cur)
    scale = jnp.maximum(new_state, 1e-8)
    q = jnp.clip(jnp.round(x / scale * qmax), -qmax, qmax) / qmax * scale
    return _ste(x, q), new_state


def fake_quant_abs_max(x, bits=8):
    out = apply(lambda v, bits: _fq_abs_max(v, bits=bits)[0], x, bits=bits,
                op_name="fake_quantize_abs_max")
    return out


def fake_quant_channel_wise_abs_max(w, bits=8, axis=-1):
    return apply(
        lambda v, bits, axis: _fq_channel_abs_max(v, bits=bits, axis=axis)[0],
        w, bits=bits, axis=axis, op_name="fake_channel_wise_quantize_abs_max",
    )


# ---------------------------------------------------------------------------
# quantized layer wrappers (reference: slim/quantization/imperative/qat.py
# QuantizedConv2D / QuantizedLinear)
# ---------------------------------------------------------------------------
class _FakeQuantAct(Layer):
    """moving_average_abs_max activation fake-quant with a persistent scale."""

    def __init__(self, bits=8, moving_rate=0.9):
        super().__init__()
        self.bits = bits
        self.rate = moving_rate
        self.register_buffer("scale", Tensor(np.zeros((), np.float32)))

    def forward(self, x):
        if self.training:
            out, new_state = apply(
                lambda v, s, bits, rate: _fq_moving_avg(v, s, bits=bits, rate=rate),
                x, self.scale, bits=self.bits, rate=self.rate,
                op_name="fake_quantize_moving_average_abs_max",
            )
            with no_grad():
                self.scale._value = jax.lax.stop_gradient(new_state._value)
            return out
        qmax = float(2 ** (self.bits - 1) - 1)

        def eval_q(v, s, qmax):
            # scale is a traced input so jit.save can bake the buffer value
            scale = jnp.maximum(s, 1e-8)
            return jnp.clip(jnp.round(v / scale * qmax), -qmax, qmax) / qmax * scale

        return apply(eval_q, x, self.scale, qmax=qmax, op_name="quantize_dequantize")


class QuantedLinear(Layer):
    def __init__(self, layer: "nn.Linear", weight_bits=8, activation_bits=8,
                 moving_rate=0.9, weight_quantize_type="channel_wise_abs_max"):
        super().__init__()
        self._linear = layer
        self.weight_bits = weight_bits
        self.weight_quantize_type = weight_quantize_type
        self.fq_act = _FakeQuantAct(activation_bits, moving_rate)

    def _quant_weight(self, w):
        if self.weight_quantize_type == "channel_wise_abs_max":
            return fake_quant_channel_wise_abs_max(w, self.weight_bits, axis=-1)
        return fake_quant_abs_max(w, self.weight_bits)

    def forward(self, x):
        xq = self.fq_act(x)
        wq = self._quant_weight(self._linear.weight)
        return nn.functional.linear(xq, wq, self._linear.bias)


class QuantedConv2D(Layer):
    def __init__(self, layer: "nn.Conv2D", weight_bits=8, activation_bits=8,
                 moving_rate=0.9, weight_quantize_type="channel_wise_abs_max"):
        super().__init__()
        self._conv = layer
        self.weight_bits = weight_bits
        self.weight_quantize_type = weight_quantize_type
        self.fq_act = _FakeQuantAct(activation_bits, moving_rate)

    def _quant_weight(self, w):
        # conv weight [out_c, in_c/g, kh, kw] — channel axis 0
        if self.weight_quantize_type == "channel_wise_abs_max":
            return fake_quant_channel_wise_abs_max(w, self.weight_bits, axis=0)
        return fake_quant_abs_max(w, self.weight_bits)

    def forward(self, x):
        xq = self.fq_act(x)
        wq = self._quant_weight(self._conv.weight)
        c = self._conv
        return nn.functional.conv2d(
            xq, wq, c.bias, stride=c._stride, padding=c._padding,
            dilation=c._dilation, groups=c._groups, data_format=c._data_format,
        )


class QuantedEmbedding(Layer):
    """Weight-only fake-quant embedding (reference: qat.py
    QuantizedEmbedding — ids carry no activation scale; grads flow to the
    float weight through the STE)."""

    def __init__(self, layer, weight_bits=8, activation_bits=8,
                 moving_rate=0.9, weight_quantize_type="abs_max"):
        super().__init__()
        self._embedding = layer
        self.weight_bits = weight_bits

    def forward(self, ids):
        wq = fake_quant_abs_max(self._embedding.weight, self.weight_bits)
        return nn.functional.embedding(
            ids, wq, padding_idx=getattr(self._embedding, "_padding_idx", None)
        )


class _QuantedParallelEmbedding(Layer):
    """PTQ wrapper for VocabParallelEmbedding: runs the ORIGINAL forward
    (keeping its sharding constraint — a plain embedding lookup would let
    XLA replicate the vocab-sharded table) with the fake-quant weight
    bound in. Inference/PTQ only, like _QuantedParallelLinear."""

    def __init__(self, layer, weight_bits=8, activation_bits=8,
                 moving_rate=0.9, weight_quantize_type="abs_max"):
        super().__init__()
        self._inner = layer
        self.weight_bits = weight_bits

    def forward(self, ids):
        if self.training:
            raise RuntimeError(
                "QAT training through VocabParallelEmbedding is not "
                "supported (the quantized-weight bind bypasses the tape); "
                "use PTQ (model.eval()) or quantize before distributing"
            )
        from ..jit import _bind_values

        wq = fake_quant_abs_max(self._inner.weight, self.weight_bits)
        with _bind_values([self._inner.weight], [wq._value]):
            return self._inner(ids)


class _QuantedParallelLinear(Layer):
    """PTQ wrapper for TP linears: fake-quants the input/weight, then runs
    the ORIGINAL layer's forward (with its sharding constraints and
    collectives) on the quantized weight via a temporary value bind.
    Inference/PTQ only — the bind bypasses the tape, so QAT training
    through this wrapper is refused rather than silently unquantized."""

    def __init__(self, layer, weight_bits=8, activation_bits=8,
                 moving_rate=0.9, weight_quantize_type="channel_wise_abs_max"):
        super().__init__()
        self._inner = layer
        self.weight_bits = weight_bits
        self.fq_act = _FakeQuantAct(activation_bits, moving_rate)

    def forward(self, x):
        if self.training:
            raise RuntimeError(
                "QAT training through a tensor-parallel linear is not "
                "supported (the quantized-weight bind bypasses the tape); "
                "use PTQ (model.eval()) or quantize before distributing"
            )
        from ..jit import _bind_values

        xq = self.fq_act(x)
        wq = fake_quant_channel_wise_abs_max(
            self._inner.weight, self.weight_bits, axis=-1
        )
        with _bind_values([self._inner.weight], [wq._value]):
            return self._inner(xq)


_QUANT_MAP = {
    "Linear": QuantedLinear,
    "Conv2D": QuantedConv2D,
    "Embedding": QuantedEmbedding,
    "VocabParallelEmbedding": _QuantedParallelEmbedding,
    "ColumnParallelLinear": _QuantedParallelLinear,
    "RowParallelLinear": _QuantedParallelLinear,
}


class ImperativeQuantAware:
    """QAT driver (reference: imperative/qat.py ImperativeQuantAware).

    quantize(model) swaps each quantizable sublayer IN PLACE for its
    fake-quant wrapper; train as usual; save_quantized_model exports the
    scale-baked inference artifact.
    """

    def __init__(self, quantizable_layer_type=("Conv2D", "Linear"),
                 weight_quantize_type="channel_wise_abs_max",
                 activation_quantize_type="moving_average_abs_max",
                 weight_bits=8, activation_bits=8, moving_rate=0.9, **kw):
        self.types = tuple(quantizable_layer_type)
        self.weight_quantize_type = weight_quantize_type
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.moving_rate = moving_rate

    def quantize(self, model: Layer) -> Layer:
        for parent in model.sublayers(include_self=True):
            for name, child in list(parent._sub_layers.items()):
                cls_name = type(child).__name__
                if cls_name in self.types and cls_name in _QUANT_MAP:
                    wrapped = _QUANT_MAP[cls_name](
                        child, self.weight_bits, self.activation_bits,
                        self.moving_rate, self.weight_quantize_type,
                    )
                    setattr(parent, name, wrapped)
        return model

    def save_quantized_model(self, model: Layer, path: str, input_spec=None, **config):
        from .. import jit

        model.eval()
        jit.save(model, path, input_spec=input_spec)


class PostTrainingQuantization:
    """Pass-driven PTQ (reference: post_training_quantization.py over the
    quantization_pass.py pipeline): InsertObservers → Calibrate →
    FreezeScales (→ ConvertToInt8 when int8_inference=True). `algo` picks
    the activation observer: abs_max | avg | hist | mse."""

    def __init__(self, model: Layer,
                 quantizable_layer_type=("Conv2D", "Linear"),
                 weight_bits=8, activation_bits=8, algo: str = "abs_max"):
        from .passes import QuantConfig

        self.model = model
        self.config = QuantConfig(
            quantizable_layer_type=quantizable_layer_type,
            weight_bits=weight_bits, activation_bits=activation_bits,
            algo=algo,
        )
        self._report = {}
        self._scales = {}

    def quantize(self, data_loader, batch_nums: Optional[int] = None,
                 int8_inference: bool = False) -> Layer:
        from .passes import (
            CalibratePass,
            ConvertToInt8Pass,
            FreezeScalesPass,
            InsertObserversPass,
            QuantPassManager,
        )

        passes = [
            InsertObserversPass(),
            CalibratePass(data_loader, batch_nums),
            FreezeScalesPass(),
        ]
        if int8_inference:
            passes.append(ConvertToInt8Pass())
        st = QuantPassManager(passes).run(self.model, self.config)
        self._report = st.report
        self._scales = dict(st.scales)
        return self.model

    @property
    def activation_ranges(self):
        return dict(self._scales)

    @property
    def pass_report(self):
        return dict(self._report)


from . import int8, observers, passes  # noqa: E402,F401
from .int8 import Int8Linear, int8_matmul, quantize_weight_int8  # noqa: E402,F401
