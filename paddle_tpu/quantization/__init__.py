"""paddle.quantization — QAT fake-quant + post-training calibration.

Reference analogue: python/paddle/fluid/contrib/slim/quantization/
(ImperativeQuantAware in imperative/qat.py — replaces Conv2D/Linear with
QuantizedConv2D/QuantizedLinear carrying fake_quant ops; PostTraining
Quantization collects activation ranges over calibration data; fake-quant
kernels fake_quantize_op.cc: abs_max, channel_wise_abs_max,
moving_average_abs_max).

TPU-native design: fake-quant is pure jnp math recorded on the tape with a
straight-through estimator (x + stop_gradient(quant(x) - x)) — no
registered STE grad kernels needed. Scales live in layer buffers so
state_dict round-trips them and `save_quantized_model` bakes them into the
StableHLO artifact. Int8 *execution* maps to XLA int8 dots when the
deployment runtime chooses; the artifact carries exact scale metadata.
"""
from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply, no_grad
from ..core.tensor import Tensor
from ..nn.layer_base import Layer
from .. import nn

__all__ = [
    "ImperativeQuantAware",
    "PostTrainingQuantization",
    "QuantedLinear",
    "QuantedConv2D",
    "fake_quant_abs_max",
    "fake_quant_channel_wise_abs_max",
]


# ---------------------------------------------------------------------------
# fake-quant ops (reference: operators/fake_quantize_op.cc kernels)
# ---------------------------------------------------------------------------
def _ste(x, q):
    """Straight-through estimator: forward q, backward identity."""
    return x + jax.lax.stop_gradient(q - x)


def _fq_abs_max(x, *, bits=8):
    qmax = float(2 ** (bits - 1) - 1)
    scale = jnp.maximum(jnp.max(jnp.abs(x)), 1e-8)
    q = jnp.round(x / scale * qmax) / qmax * scale
    return _ste(x, q), scale


def _fq_channel_abs_max(w, *, bits=8, axis=-1):
    """Per-output-channel abs-max (reference: channel_wise_abs_max for
    weights; paddle Linear weight is [in, out] so channels are axis -1)."""
    qmax = float(2 ** (bits - 1) - 1)
    red = tuple(i for i in range(w.ndim) if i != (axis % w.ndim))
    scale = jnp.maximum(jnp.max(jnp.abs(w), axis=red, keepdims=True), 1e-8)
    q = jnp.round(w / scale * qmax) / qmax * scale
    return _ste(w, q), scale.reshape(-1)


def _fq_moving_avg(x, state, *, bits=8, rate=0.9):
    """moving_average_abs_max: running activation scale (training); the
    accumulated scale is what inference uses."""
    qmax = float(2 ** (bits - 1) - 1)
    cur = jnp.max(jnp.abs(x))
    new_state = jnp.where(state > 0, rate * state + (1 - rate) * cur, cur)
    scale = jnp.maximum(new_state, 1e-8)
    q = jnp.clip(jnp.round(x / scale * qmax), -qmax, qmax) / qmax * scale
    return _ste(x, q), new_state


def fake_quant_abs_max(x, bits=8):
    out = apply(lambda v, bits: _fq_abs_max(v, bits=bits)[0], x, bits=bits,
                op_name="fake_quantize_abs_max")
    return out


def fake_quant_channel_wise_abs_max(w, bits=8, axis=-1):
    return apply(
        lambda v, bits, axis: _fq_channel_abs_max(v, bits=bits, axis=axis)[0],
        w, bits=bits, axis=axis, op_name="fake_channel_wise_quantize_abs_max",
    )


# ---------------------------------------------------------------------------
# quantized layer wrappers (reference: slim/quantization/imperative/qat.py
# QuantizedConv2D / QuantizedLinear)
# ---------------------------------------------------------------------------
class _FakeQuantAct(Layer):
    """moving_average_abs_max activation fake-quant with a persistent scale."""

    def __init__(self, bits=8, moving_rate=0.9):
        super().__init__()
        self.bits = bits
        self.rate = moving_rate
        self.register_buffer("scale", Tensor(np.zeros((), np.float32)))

    def forward(self, x):
        if self.training:
            out, new_state = apply(
                lambda v, s, bits, rate: _fq_moving_avg(v, s, bits=bits, rate=rate),
                x, self.scale, bits=self.bits, rate=self.rate,
                op_name="fake_quantize_moving_average_abs_max",
            )
            with no_grad():
                self.scale._value = jax.lax.stop_gradient(new_state._value)
            return out
        qmax = float(2 ** (self.bits - 1) - 1)

        def eval_q(v, s, qmax):
            # scale is a traced input so jit.save can bake the buffer value
            scale = jnp.maximum(s, 1e-8)
            return jnp.clip(jnp.round(v / scale * qmax), -qmax, qmax) / qmax * scale

        return apply(eval_q, x, self.scale, qmax=qmax, op_name="quantize_dequantize")


class QuantedLinear(Layer):
    def __init__(self, layer: "nn.Linear", weight_bits=8, activation_bits=8,
                 moving_rate=0.9, weight_quantize_type="channel_wise_abs_max"):
        super().__init__()
        self._linear = layer
        self.weight_bits = weight_bits
        self.weight_quantize_type = weight_quantize_type
        self.fq_act = _FakeQuantAct(activation_bits, moving_rate)

    def _quant_weight(self, w):
        if self.weight_quantize_type == "channel_wise_abs_max":
            return fake_quant_channel_wise_abs_max(w, self.weight_bits, axis=-1)
        return fake_quant_abs_max(w, self.weight_bits)

    def forward(self, x):
        xq = self.fq_act(x)
        wq = self._quant_weight(self._linear.weight)
        return nn.functional.linear(xq, wq, self._linear.bias)


class QuantedConv2D(Layer):
    def __init__(self, layer: "nn.Conv2D", weight_bits=8, activation_bits=8,
                 moving_rate=0.9, weight_quantize_type="channel_wise_abs_max"):
        super().__init__()
        self._conv = layer
        self.weight_bits = weight_bits
        self.weight_quantize_type = weight_quantize_type
        self.fq_act = _FakeQuantAct(activation_bits, moving_rate)

    def _quant_weight(self, w):
        # conv weight [out_c, in_c/g, kh, kw] — channel axis 0
        if self.weight_quantize_type == "channel_wise_abs_max":
            return fake_quant_channel_wise_abs_max(w, self.weight_bits, axis=0)
        return fake_quant_abs_max(w, self.weight_bits)

    def forward(self, x):
        xq = self.fq_act(x)
        wq = self._quant_weight(self._conv.weight)
        c = self._conv
        return nn.functional.conv2d(
            xq, wq, c.bias, stride=c._stride, padding=c._padding,
            dilation=c._dilation, groups=c._groups, data_format=c._data_format,
        )


_QUANT_MAP = {"Linear": QuantedLinear, "Conv2D": QuantedConv2D}


class ImperativeQuantAware:
    """QAT driver (reference: imperative/qat.py ImperativeQuantAware).

    quantize(model) swaps each quantizable sublayer IN PLACE for its
    fake-quant wrapper; train as usual; save_quantized_model exports the
    scale-baked inference artifact.
    """

    def __init__(self, quantizable_layer_type=("Conv2D", "Linear"),
                 weight_quantize_type="channel_wise_abs_max",
                 activation_quantize_type="moving_average_abs_max",
                 weight_bits=8, activation_bits=8, moving_rate=0.9, **kw):
        self.types = tuple(quantizable_layer_type)
        self.weight_quantize_type = weight_quantize_type
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.moving_rate = moving_rate

    def quantize(self, model: Layer) -> Layer:
        for parent in model.sublayers(include_self=True):
            for name, child in list(parent._sub_layers.items()):
                cls_name = type(child).__name__
                if cls_name in self.types and cls_name in _QUANT_MAP:
                    wrapped = _QUANT_MAP[cls_name](
                        child, self.weight_bits, self.activation_bits,
                        self.moving_rate, self.weight_quantize_type,
                    )
                    setattr(parent, name, wrapped)
        return model

    def save_quantized_model(self, model: Layer, path: str, input_spec=None, **config):
        from .. import jit

        model.eval()
        jit.save(model, path, input_spec=input_spec)


class PostTrainingQuantization:
    """PTQ (reference: post_training_quantization.py): run calibration data
    through the float model, record per-activation abs-max ranges, attach
    frozen scales."""

    def __init__(self, model: Layer, quantizable_layer_type=("Conv2D", "Linear"),
                 weight_bits=8, activation_bits=8):
        self.model = model
        self.types = tuple(quantizable_layer_type)
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self._ranges = {}

    def quantize(self, data_loader, batch_nums: Optional[int] = None) -> Layer:
        # hooks record input abs-max per quantizable layer
        handles = []
        names = {}
        for name, layer in self.model.named_sublayers():
            if type(layer).__name__ in self.types:
                names[id(layer)] = name

                def hook(lyr, inputs, _name=name):
                    x = inputs[0] if isinstance(inputs, (tuple, list)) else inputs
                    m = float(jnp.max(jnp.abs(x._value)))
                    self._ranges[_name] = max(self._ranges.get(_name, 0.0), m)

                handles.append(layer.register_forward_pre_hook(hook))
        self.model.eval()
        with no_grad():
            for i, batch in enumerate(data_loader):
                if batch_nums is not None and i >= batch_nums:
                    break
                x = batch[0] if isinstance(batch, (tuple, list)) else batch
                self.model(x if isinstance(x, Tensor) else Tensor(jnp.asarray(np.asarray(x))))
        for h in handles:
            h.remove()
        # freeze: swap in wrappers with calibrated (non-moving) scales
        q = ImperativeQuantAware(
            quantizable_layer_type=self.types,
            weight_bits=self.weight_bits, activation_bits=self.activation_bits,
        )
        q.quantize(self.model)
        for name, layer in self.model.named_sublayers():
            if isinstance(layer, (QuantedLinear, QuantedConv2D)):
                base = name
                scale = self._ranges.get(base, 0.0)
                if scale > 0:
                    with no_grad():
                        layer.fq_act.scale._value = jnp.asarray(scale, jnp.float32)
        return self.model

    @property
    def activation_ranges(self):
        return dict(self._ranges)
