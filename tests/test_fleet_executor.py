"""FleetExecutor C++ actor runtime — carrier/interceptor scheduling.

Reference analogue: fleet_executor tests (carrier_test.cc,
interceptor_pipeline_test.cc) — ordering + completion of a microbatch
pipeline over the actor DAG.
"""
import threading
import time

import numpy as np
import pytest

from paddle_tpu.distributed.fleet_executor import FleetExecutor, TaskNode


def test_linear_pipeline_ordering():
    log = []
    lock = threading.Lock()

    def stage(k):
        def fn(scope):
            with lock:
                log.append((k, scope))
        return fn

    num_micro, n_stages = 5, 3
    FleetExecutor.pipeline([stage(k) for k in range(n_stages)], num_micro).run()

    assert len(log) == num_micro * n_stages
    pos = {(k, s): i for i, (k, s) in enumerate(log)}
    # dependency order: stage k microbatch s after stage k-1 microbatch s
    for s in range(num_micro):
        for k in range(1, n_stages):
            assert pos[(k, s)] > pos[(k - 1, s)]


def test_pipeline_overlap():
    """Stages overlap in wall-clock (actors run concurrently)."""
    active = {"now": 0, "max": 0}
    lock = threading.Lock()

    def fn(scope):
        with lock:
            active["now"] += 1
            active["max"] = max(active["max"], active["now"])
        time.sleep(0.02)
        with lock:
            active["now"] -= 1

    FleetExecutor.pipeline([fn, fn, fn, fn], num_micro=8).run()
    assert active["max"] >= 2  # pipelining really happened


def test_diamond_dag():
    log = []
    lock = threading.Lock()

    def mk(name):
        def fn(scope):
            with lock:
                log.append((name, scope))
        return fn

    a = TaskNode(0, mk("a"), max_run_times=3)
    b = TaskNode(1, mk("b"), max_run_times=3)
    c = TaskNode(2, mk("c"), max_run_times=3)
    d = TaskNode(3, mk("d"), max_run_times=3)
    a.add_downstream_task(1).add_downstream_task(2)
    b.add_upstream_task(0).add_downstream_task(3)
    c.add_upstream_task(0).add_downstream_task(3)
    d.add_upstream_task(1).add_upstream_task(2)
    FleetExecutor([a, b, c, d]).run()

    pos = {(n, s): i for i, (n, s) in enumerate(log)}
    for s in range(3):
        assert pos[("d", s)] > pos[("b", s)] and pos[("d", s)] > pos[("c", s)]
        assert pos[("b", s)] > pos[("a", s)] and pos[("c", s)] > pos[("a", s)]


def test_task_exception_propagates():
    def bad(scope):
        if scope == 1:
            raise ValueError("boom at microbatch 1")

    with pytest.raises(ValueError, match="boom"):
        FleetExecutor.pipeline([bad, lambda s: None], num_micro=3).run()


def test_host_pipeline_drives_jax_stages():
    """The intended use: each stage is a jitted XLA program; the actor
    runtime overlaps stages across microbatches."""
    import jax
    import jax.numpy as jnp

    f1 = jax.jit(lambda x: x * 2.0)
    f2 = jax.jit(lambda x: x + 1.0)
    buf = {}
    out = {}

    def s1(scope):
        buf[scope] = f1(jnp.ones((4,)) * scope)

    def s2(scope):
        out[scope] = np.asarray(f2(buf[scope]))

    FleetExecutor.pipeline([s1, s2], num_micro=4).run()
    for s in range(4):
        np.testing.assert_allclose(out[s], 2.0 * s + 1.0)


def test_bad_dag_rejected():
    n = TaskNode(0).add_upstream_task(7)
    with pytest.raises(ValueError, match="unknown"):
        FleetExecutor([n])


def test_host_pipeline_trainer_matches_single_device():
    """Actor-driven multi-program pipeline == plain single-program training."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.distributed.fleet_executor.pipeline_trainer import (
        HostPipelineTrainer,
    )

    key = jax.random.key(0)
    k1, k2, k3 = jax.random.split(key, 3)
    p1 = {"w": jax.random.normal(k1, (8, 16)) * 0.1}
    p2 = {"w": jax.random.normal(k2, (16, 16)) * 0.1}
    p3 = {"w": jax.random.normal(k3, (16, 4)) * 0.1}

    def s1(p, x):
        return jnp.tanh(x @ p["w"])

    def s2(p, x):
        return jnp.tanh(x @ p["w"])

    def s3(p, x):
        return x @ p["w"]

    def loss_fn(y, lbl):
        return ((y - lbl) ** 2).mean()

    rng = np.random.default_rng(0)
    xs = [jnp.asarray(rng.standard_normal((4, 8)), jnp.float32) for _ in range(4)]
    ys = [jnp.asarray(rng.standard_normal((4, 4)), jnp.float32) for _ in range(4)]

    lr = 0.1
    trainer = HostPipelineTrainer([s1, s2, s3], [p1, p2, p3], loss_fn,
                                  learning_rate=lr, devices=jax.devices()[:3])

    # single-device reference: same params, microbatch-mean grads, SGD
    ref = [dict(p1), dict(p2), dict(p3)]

    def full_loss(ps, x, lbl):
        return loss_fn(s3(ps[2], s2(ps[1], s1(ps[0], x))), lbl)

    pipe_losses = []
    for step in range(3):
        pipe_losses.append(trainer.train_batch(xs, ys))
        gsum = None
        ref_loss = 0.0
        for x, lbl in zip(xs, ys):
            l, g = jax.value_and_grad(full_loss)(ref, x, lbl)
            ref_loss += float(l)
            gsum = g if gsum is None else jax.tree_util.tree_map(jnp.add, gsum, g)
        gmean = jax.tree_util.tree_map(lambda v: v / len(xs), gsum)
        ref = jax.tree_util.tree_map(lambda pv, gv: pv - lr * gv, ref, gmean)
        np.testing.assert_allclose(pipe_losses[-1], ref_loss / len(xs), rtol=1e-5)

    # trained params identical stage by stage
    for k in range(3):
        np.testing.assert_allclose(
            np.asarray(trainer.params[k]["w"]), np.asarray(ref[k]["w"]), rtol=1e-5
        )
    assert pipe_losses[-1] < pipe_losses[0]


def test_host_pipeline_1f1b_window_and_parity():
    """1F1B caps in-flight microbatches at n_stages; numerics identical to
    GPipe (reference: pipeline_parallel.py:80 forward_backward_pipeline)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from paddle_tpu.distributed.fleet_executor.pipeline_trainer import (
        HostPipelineTrainer,
    )

    rng = np.random.default_rng(0)
    w1 = jnp.asarray(rng.standard_normal((4, 8)).astype(np.float32) * 0.3)
    w2 = jnp.asarray(rng.standard_normal((8, 2)).astype(np.float32) * 0.3)
    micro_xs = [jnp.asarray(rng.standard_normal((4, 4)).astype(np.float32))
                for _ in range(8)]
    micro_ys = [jnp.asarray(rng.standard_normal((4, 2)).astype(np.float32))
                for _ in range(8)]

    def make():
        return HostPipelineTrainer(
            stage_fns=[
                lambda p, x: jnp.tanh(x @ p),
                lambda p, x: x @ p,
            ],
            params=[w1, w2],
            loss_fn=lambda y, lbl: jnp.mean((y - lbl) ** 2),
            learning_rate=0.1,
            devices=[jax.devices()[0]] * 2,
        )

    t1 = make()
    loss_1f1b = t1.train_batch(micro_xs, micro_ys, schedule="1f1b")
    assert t1._peak_inflight <= 2, t1._peak_inflight
    t2 = make()
    loss_gpipe = t2.train_batch(micro_xs, micro_ys, schedule="gpipe")
    assert abs(loss_1f1b - loss_gpipe) < 1e-6
    for a, b in zip(jax.tree_util.tree_leaves(t1.params),
                    jax.tree_util.tree_leaves(t2.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)
    import pytest

    with pytest.raises(ValueError, match="1f1b"):
        make().train_batch(micro_xs, micro_ys, schedule="bogus")
