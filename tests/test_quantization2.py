"""Pass-driven PTQ + observers + int8 execution (VERDICT r3 task 5).

Reference analogues: slim/quantization/quantization_pass.py (pass
pipeline), post_training_quantization.py (algo=abs_max/hist/mse/avg
calibration), imperative/qat.py (QuantizedEmbedding).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.quantization import (
    Int8Linear,
    PostTrainingQuantization,
    QuantedEmbedding,
    QuantedLinear,
    int8_matmul,
    quantize_weight_int8,
)
from paddle_tpu.quantization.observers import (
    AbsMaxObserver,
    EMAAbsMaxObserver,
    HistObserver,
    MSEObserver,
)

rng = np.random.default_rng(0)


@pytest.fixture(autouse=True)
def _fresh_quantization_state():
    """Reset shared calibration state so every test sees the data it would
    see running alone. The module-level `rng` is a consumed stream: earlier
    tests draining it shifted the calibration/eval batches of later ones,
    which is exactly how `test_ptq_accuracy_lenet[mse]` passed in isolation
    but failed mid-module (the mse observer's grid search landed on a clip
    fitted to different draws). Observer state itself is per-instance, so a
    fresh rng per test is the whole reset."""
    global rng
    rng = np.random.default_rng(0)
    yield


# -- observers -----------------------------------------------------------------
def test_absmax_observer_tracks_max():
    o = AbsMaxObserver()
    o.collect(np.array([1.0, -3.0]))
    o.collect(np.array([2.0]))
    assert o.scale() == 3.0


def test_hist_observer_clips_outlier_tail():
    o = HistObserver(percentile=0.99)
    data = np.concatenate([rng.normal(0, 1, 100_000), [1000.0]])
    o.collect(data)
    # the single 1000.0 outlier must not set the scale; ~normal range does
    assert o.scale() < 10.0
    a = AbsMaxObserver()
    a.collect(data)
    assert a.scale() == 1000.0


def test_mse_observer_beats_absmax_on_outliers():
    data = np.concatenate([rng.normal(0, 1, 50_000), [500.0]]).astype(
        np.float32
    )
    m = MSEObserver()
    m.collect(data)
    a = AbsMaxObserver()
    a.collect(data)
    qmax = 127.0

    def mse(scale):
        q = np.clip(np.round(data / scale * qmax), -qmax, qmax) / qmax * scale
        return np.mean((q - data) ** 2)

    assert mse(m.scale()) < mse(a.scale())


def test_ema_observer_averages():
    o = EMAAbsMaxObserver(rate=0.5)
    o.collect(np.array([4.0]))
    o.collect(np.array([2.0]))
    np.testing.assert_allclose(o.scale(), 3.0)


# -- pass pipeline -------------------------------------------------------------
class LeNetish(nn.Layer):
    def __init__(self):
        super().__init__()
        self.conv = nn.Conv2D(1, 4, 3, padding=1)
        self.fc1 = nn.Linear(4 * 8 * 8, 32)
        self.fc2 = nn.Linear(32, 10)

    def forward(self, x):
        h = nn.functional.relu(self.conv(x))
        h = h.reshape([h.shape[0], -1])
        return self.fc2(nn.functional.relu(self.fc1(h)))


def _calib_batches(n=4, bsz=8):
    return [
        (paddle.to_tensor(rng.normal(size=(bsz, 1, 8, 8)).astype(np.float32)),)
        for _ in range(n)
    ]


def test_ptq_pass_pipeline_reports_and_freezes():
    paddle.seed(0)
    m = LeNetish()
    ptq = PostTrainingQuantization(m, algo="abs_max")
    ptq.quantize(_calib_batches())
    assert ptq.pass_report["insert_observers"] == 3
    assert ptq.pass_report["calibrate"] == 4
    assert ptq.pass_report["freeze_scales"] == 3
    # every wrapper carries a positive frozen scale
    assert len(ptq.activation_ranges) == 3
    assert all(v > 0 for v in ptq.activation_ranges.values())
    assert isinstance(m.fc1, QuantedLinear)
    assert float(m.fc1.fq_act.scale) > 0


@pytest.mark.parametrize("algo", ["abs_max", "hist", "mse", "avg"])
def test_ptq_accuracy_lenet(algo):
    """PTQ'd conv-net outputs stay within 3% relative error of float."""
    paddle.seed(0)
    m = LeNetish()
    m.eval()
    x = paddle.to_tensor(rng.normal(size=(16, 1, 8, 8)).astype(np.float32))
    with paddle.no_grad():
        ref = m(x).numpy()
    PostTrainingQuantization(m, algo=algo).quantize(_calib_batches())
    m.eval()
    with paddle.no_grad():
        out = m(x).numpy()
    rel = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-8)
    assert rel < 0.06, (algo, rel)
    # argmax agreement (the accuracy-delta proxy for synthetic data)
    agree = (out.argmax(1) == ref.argmax(1)).mean()
    assert agree >= 0.9, (algo, agree)


def test_ptq_accuracy_resnet_block():
    from paddle_tpu.vision.models import resnet18

    paddle.seed(0)
    m = resnet18(num_classes=10)
    m.eval()
    x = paddle.to_tensor(rng.normal(size=(4, 3, 32, 32)).astype(np.float32))
    with paddle.no_grad():
        ref = m(x).numpy()
    calib = [
        (paddle.to_tensor(rng.normal(size=(4, 3, 32, 32)).astype(np.float32)),)
        for _ in range(2)
    ]
    PostTrainingQuantization(m).quantize(calib)
    m.eval()
    with paddle.no_grad():
        out = m(x).numpy()
    # stated delta: top-1 agreement >= 75% and bounded relative error
    agree = (out.argmax(1) == ref.argmax(1)).mean()
    assert agree >= 0.75, agree
    rel = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-8)
    assert rel < 0.25, rel


def test_ptq_accuracy_gpt():
    from paddle_tpu.models.gpt import GPTConfig, GPTForPretraining

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                    num_heads=4, max_seq_len=16, dropout=0.0,
                    attn_dropout=0.0)
    m = GPTForPretraining(cfg)
    m.eval()
    ids = paddle.to_tensor(rng.integers(0, 128, (2, 16)).astype(np.int64))
    with paddle.no_grad():
        ref = m(ids).numpy()
    calib = [
        (paddle.to_tensor(rng.integers(0, 128, (2, 16)).astype(np.int64)),)
        for _ in range(2)
    ]
    ptq = PostTrainingQuantization(
        m, quantizable_layer_type=("ColumnParallelLinear",
                                   "RowParallelLinear", "Linear"),
    )
    ptq.quantize(calib)
    assert ptq.pass_report["freeze_scales"] >= 8  # qkv/out/mlp per block
    m.eval()
    with paddle.no_grad():
        out = m(ids).numpy()
    # stated delta: next-token argmax agreement >= 90%
    agree = (out.argmax(-1) == ref.argmax(-1)).mean()
    assert agree >= 0.9, agree


# -- int8 execution path -------------------------------------------------------
def test_quantize_weight_int8_roundtrip():
    w = rng.normal(size=(8, 16)).astype(np.float32)
    q, s = quantize_weight_int8(w, axis=-1)
    assert q.dtype == np.int8 and s.shape == (1, 16)
    deq = q.astype(np.float32) * s / 127.0
    np.testing.assert_allclose(deq, w, atol=np.abs(w).max() / 127.0 + 1e-6)


def test_int8_matmul_uses_int8_dot():
    """The compiled program must contain an s8 x s8 -> s32 dot."""
    import jax

    w = rng.normal(size=(16, 8)).astype(np.float32)
    wq, ws = quantize_weight_int8(w, axis=-1)
    x = rng.normal(size=(4, 16)).astype(np.float32)

    def f(xv):
        from paddle_tpu.quantization.int8 import _int8_dot

        import jax.numpy as jnp

        xq = jnp.clip(jnp.round(xv / 3.0 * 127.0), -127, 127).astype(jnp.int8)
        return _int8_dot(xq, wq)

    jaxpr = str(jax.make_jaxpr(f)(x))
    assert "i8" in jaxpr and "preferred_element_type=int32" in jaxpr
    out = jax.jit(f)(x)
    assert out.dtype == np.int32


def test_int8_linear_matches_float_within_tolerance():
    paddle.seed(1)
    lin = nn.Linear(32, 16)
    lin.eval()
    x = paddle.to_tensor(rng.normal(size=(8, 32)).astype(np.float32))
    with paddle.no_grad():
        ref = lin(x).numpy()
    q = QuantedLinear(lin)
    import jax.numpy as jnp

    from paddle_tpu.core.dispatch import no_grad

    with no_grad():
        q.fq_act.scale._value = jnp.asarray(
            float(np.abs(x.numpy()).max()), jnp.float32
        )
    i8 = Int8Linear.from_quanted(q)
    with paddle.no_grad():
        out = i8(x).numpy()
    rel = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-8)
    assert rel < 0.05, rel
    # int8 weights really are stored as int8
    assert str(i8.weight_int8.dtype) in ("paddle_tpu.int8", "int8")


def test_convert_to_int8_pass_lowers_linears():
    paddle.seed(0)
    m = LeNetish()
    PostTrainingQuantization(
        m, quantizable_layer_type=("Linear",)
    ).quantize(_calib_batches(), int8_inference=True)
    assert isinstance(m.fc1, Int8Linear) and isinstance(m.fc2, Int8Linear)
    m.eval()
    x = paddle.to_tensor(rng.normal(size=(4, 1, 8, 8)).astype(np.float32))
    with paddle.no_grad():
        out = m(x)
    assert np.all(np.isfinite(out.numpy()))


# -- QAT embedding coverage ----------------------------------------------------
def test_qat_embedding_trains_through_ste():
    from paddle_tpu.quantization import ImperativeQuantAware

    paddle.seed(0)

    class TinyLM(nn.Layer):
        def __init__(self):
            super().__init__()
            self.emb = nn.Embedding(16, 8)
            self.head = nn.Linear(8, 16)

        def forward(self, ids):
            return self.head(self.emb(ids))

    m = TinyLM()
    q = ImperativeQuantAware(
        quantizable_layer_type=("Linear", "Embedding")
    )
    q.quantize(m)
    assert isinstance(m.emb, QuantedEmbedding)
    opt = paddle.optimizer.Adam(0.01, parameters=m.parameters())
    ids = paddle.to_tensor(np.array([[1, 2], [3, 4]], np.int64))
    tgt = paddle.to_tensor(np.array([[2, 3], [4, 5]], np.int64))
    losses = []
    for _ in range(30):
        logits = m(ids)
        loss = nn.functional.cross_entropy(
            logits.reshape([-1, 16]), tgt.reshape([-1])
        )
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.5  # STE lets grads reach the weights


def test_hist_observer_rescale_keeps_percentile():
    # regression: a later larger max must remap prior mass, not clip it
    # into the top bins (which would degenerate hist to abs-max)
    o = HistObserver(percentile=0.99)
    o.collect(np.full(10000, 0.5, np.float32))
    o.collect(np.array([2.0], np.float32))
    assert o.scale() < 1.0  # 99th percentile stays near 0.5, not 2.0


def test_ptq_returns_inference_ready_model():
    # quantize() output must be usable WITHOUT a manual eval(): a
    # training-mode fq_act would clobber the frozen scale on first use
    paddle.seed(0)
    m = LeNetish()
    m.eval()
    ptq = PostTrainingQuantization(m)
    ptq.quantize(_calib_batches())
    frozen = float(m.fc1.fq_act.scale)
    x = paddle.to_tensor(rng.normal(size=(4, 1, 8, 8)).astype(np.float32))
    with paddle.no_grad():
        m(x)
    assert float(m.fc1.fq_act.scale) == frozen  # not a moving average


def test_uncalibrated_layer_left_float_with_warning():
    import warnings as _w

    class TwoHead(nn.Layer):
        def __init__(self):
            super().__init__()
            self.used = nn.Linear(8, 4)
            self.unused = nn.Linear(8, 4)

        def forward(self, x):
            return self.used(x)  # `unused` never sees calibration data

    paddle.seed(0)
    m = TwoHead()
    m.eval()
    batches = [
        (paddle.to_tensor(rng.normal(size=(4, 8)).astype(np.float32)),)
    ]
    ptq = PostTrainingQuantization(m, quantizable_layer_type=("Linear",))
    with _w.catch_warnings(record=True) as rec:
        _w.simplefilter("always")
        ptq.quantize(batches)
    assert any("unused" in str(r.message) for r in rec)
    assert isinstance(m.used, QuantedLinear)
    assert isinstance(m.unused, nn.Linear)  # left float, not crushed


def test_calibrate_pass_removes_hooks_on_failure():
    paddle.seed(0)
    m = LeNetish()
    bad = [("not a tensor at all",)]
    ptq = PostTrainingQuantization(m)
    with pytest.raises(Exception):
        ptq.quantize(bad)
    # no observer hooks remain on the float model
    for _, layer in m.named_sublayers():
        assert not getattr(layer, "_forward_pre_hooks", None), layer
