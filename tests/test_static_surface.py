"""Tests for the paddle.static surface completion (reference:
python/paddle/static/__init__.py, static/nn/, static/sparsity)."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import static

sn = static.nn
rng = np.random.default_rng(5)


class TestStaticNN:
    def test_fc_program_build_once(self):
        prog = static.Program()
        with static.program_guard(prog):
            static.data("x", [None, 8])

        def build(feed):
            h = sn.fc(feed["x"], 16, activation="relu", name="fc1")
            return sn.fc(h, 2, name="fc2")

        prog.set_builder(build)
        exe = static.Executor()
        x = np.ones((4, 8), np.float32)
        with static.program_guard(prog):
            out1 = exe.run(prog, feed={"x": x})
            out2 = exe.run(prog, feed={"x": x})
        assert out1[0].shape == (4, 2)
        np.testing.assert_allclose(out1[0], out2[0])  # params built once

    def test_conv_and_norm_fns(self):
        prog = static.Program()
        with static.program_guard(prog):
            x = paddle.to_tensor(
                rng.standard_normal((2, 3, 8, 8)).astype(np.float32)
            )
            out = sn.conv2d(x, 4, 3, padding=1, act="relu", name="c1")
            assert out.shape == [2, 4, 8, 8]
            out = sn.batch_norm(out, name="bn1")
            out = sn.group_norm(out, groups=2, name="gn1")
            flat = out.flatten(1)
            out = sn.layer_norm(flat, name="ln1")
            assert np.isfinite(out.numpy()).all()

    def test_sequence_ops(self):
        xs = paddle.to_tensor(np.array(
            [[[1.0, 1.0], [2.0, 2.0], [3.0, 3.0]],
             [[4.0, 4.0], [5.0, 5.0], [6.0, 6.0]]], np.float32))
        lens = paddle.to_tensor(np.array([2, 3]))
        np.testing.assert_allclose(
            sn.sequence_pool(xs, "average", length=lens).numpy()[0],
            [1.5, 1.5],
        )
        np.testing.assert_allclose(
            sn.sequence_last_step(xs, length=lens).numpy()[0], [2.0, 2.0]
        )
        np.testing.assert_allclose(
            sn.sequence_first_step(xs).numpy()[1], [4.0, 4.0]
        )
        sm = sn.sequence_softmax(
            paddle.to_tensor(np.zeros((1, 4, 1), np.float32)),
            length=paddle.to_tensor(np.array([2])),
        ).numpy()
        np.testing.assert_allclose(sm[0, :, 0], [0.5, 0.5, 0, 0])
        rev = sn.sequence_reverse(xs, length=lens).numpy()
        np.testing.assert_allclose(rev[0, 0], [2.0, 2.0])
        np.testing.assert_allclose(rev[0, 2], [3.0, 3.0])  # pad untouched
        un = sn.sequence_unpad(xs, lens).numpy()
        assert (un[0, 2] == 0).all()
        enum = sn.sequence_enumerate(
            paddle.to_tensor(np.array([[1, 2, 3]])), win_size=2
        ).numpy()
        np.testing.assert_array_equal(enum[0], [[1, 2], [2, 3], [3, 0]])

    def test_control_flow(self):
        out = sn.while_loop(
            lambda i: int(i) < 5, lambda i: i + 2, [paddle.to_tensor(0)]
        )
        assert int(out[0]) == 6
        assert sn.switch_case(1, {0: lambda: 10, 1: lambda: 20}) == 20
        assert sn.case([(paddle.to_tensor(False), lambda: 1),
                        (paddle.to_tensor(True), lambda: 2)]) == 2
        assert sn.cond(paddle.to_tensor(True), lambda: "a", lambda: "b") == "a"

    def test_nce_crf_rowconv(self):
        emb = paddle.to_tensor(rng.standard_normal((4, 8)).astype(np.float32))
        lab = paddle.to_tensor(np.array([1, 2, 0, 3]))
        with static.program_guard(static.Program()):
            loss = sn.nce(emb, lab, 10, num_neg_samples=3)
            assert loss.shape == [4, 1] and np.isfinite(loss.numpy()).all()
            seq = paddle.to_tensor(
                rng.standard_normal((2, 5, 6)).astype(np.float32)
            )
            assert sn.crf_decoding(seq).shape[0] == 2
            assert sn.row_conv(seq, 2).shape == [2, 5, 6]

    def test_multi_box_head(self):
        with static.program_guard(static.Program()):
            img = paddle.to_tensor(np.zeros((1, 3, 64, 64), np.float32))
            f1 = paddle.to_tensor(rng.standard_normal((1, 8, 8, 8)).astype(np.float32))
            f2 = paddle.to_tensor(rng.standard_normal((1, 8, 4, 4)).astype(np.float32))
            locs, confs, box, var = sn.multi_box_head(
                [f1, f2], img, base_size=64, num_classes=3,
                aspect_ratios=[[2.0], [2.0]], min_ratio=20, max_ratio=90,
            )
            n_priors = box.shape[0]
            assert locs.shape == [1, n_priors, 4]
            assert confs.shape == [1, n_priors, 3]
            assert var.shape == [n_priors, 4]


class TestStaticMisc:
    def test_scope_and_global_var(self):
        v = static.create_global_var([2], 2.5, "float32", persistable=True,
                                     name="scope_var")
        assert static.global_scope().find_var("scope_var") is v
        fresh = static.Scope()
        with static.scope_guard(fresh):
            assert static.global_scope() is fresh
        assert static.global_scope() is not fresh

    def test_save_load_roundtrip(self, tmp_path):
        v = static.create_global_var([3], 1.25, "float32", persistable=True,
                                     name="persist_me")
        prog = static.Program()
        static.save(prog, str(tmp_path / "model"))
        v.set_value(np.zeros(3, np.float32))
        static.load(prog, str(tmp_path / "model"))
        np.testing.assert_allclose(v.numpy(), 1.25)
        state = static.load_program_state(str(tmp_path / "model"))
        assert "persist_me" in state
        v.set_value(np.zeros(3, np.float32))
        static.set_program_state(prog, state)
        np.testing.assert_allclose(v.numpy(), 1.25)

    def test_serialize_roundtrip(self):
        data = static.serialize_program(
            [static.Variable("x", [None, 4], "float32")], []
        )
        p2 = static.deserialize_program(data)
        assert "x" in p2.feed_vars
        blob = static.serialize_persistables([], [])
        static.deserialize_persistables(p2, blob)

    def test_metric_ops(self):
        logits = paddle.to_tensor(np.array([[0.1, 0.9], [0.8, 0.2]], np.float32))
        lab = paddle.to_tensor(np.array([[1], [0]]))
        assert float(static.accuracy(logits, lab)) == 1.0
        probs = paddle.nn.functional.softmax(logits, -1)
        assert 0.9 <= float(static.auc(probs, lab)) <= 1.0001

    def test_ema(self):
        net = paddle.nn.Linear(2, 2)
        ema = static.ExponentialMovingAverage(0.9).register(net.parameters())
        w0 = net.weight.numpy().copy()
        net.weight.set_value(w0 + 1.0)
        ema.update()
        with ema.apply():
            assert not np.allclose(net.weight.numpy(), w0 + 1.0)
        np.testing.assert_allclose(net.weight.numpy(), w0 + 1.0)

    def test_places_and_strategies(self):
        assert len(static.cpu_places(2)) == 2
        assert static.cuda_places([0])[0].device_type == "tpu"
        bs = static.BuildStrategy()
        bs.fuse_bn_act_ops = True
        es = static.ExecutionStrategy()
        es.num_threads = 4
        with static.device_guard("gpu:0"):
            pass
        p = static.Print(paddle.to_tensor(np.arange(3)), message="dbg")
        assert p is not None

    def test_py_func(self):
        x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        out = static.py_func(lambda t: t * 2, x, None)
        np.testing.assert_allclose(out.numpy(), [2.0, 4.0])


class TestStaticSparsity:
    def test_prune_and_density(self):
        net = paddle.nn.Linear(8, 8)
        net.weight.set_value(
            rng.standard_normal((8, 8)).astype(np.float32) + 0.1
        )
        assert static.sparsity.calculate_density(net.weight) == 1.0
        static.sparsity.prune_model(net)
        d = static.sparsity.calculate_density(net.weight)
        assert abs(d - 0.5) < 1e-6  # 2:4
        # excluded layers are skipped
        net2 = paddle.nn.Linear(8, 8)
        net2.weight.set_value(
            rng.standard_normal((8, 8)).astype(np.float32) + 0.1
        )
        static.sparsity.set_excluded_layers(param_names=[""])
        try:
            static.sparsity.prune_model(net2)
            assert static.sparsity.calculate_density(net2.weight) == 1.0
        finally:
            static.sparsity.reset_excluded_layers()


class TestReviewFixes:
    def test_static_nn_params_persist(self, tmp_path):
        paddle.enable_static()
        try:
            prog = static.Program()
            with static.program_guard(prog):
                static.data("x", [None, 4])
            prog.set_builder(lambda f: sn.fc(f["x"], 2, name="persist_fc"))
            exe = static.Executor()
            with static.program_guard(prog):
                o1 = exe.run(prog, feed={"x": np.ones((2, 4), np.float32)})
            static.save(prog, str(tmp_path / "m"))
            import pickle

            state = pickle.load(open(tmp_path / "m.pdparams", "rb"))
            assert any("persist_fc" in k for k in state)
            p = prog.all_parameters()[0]
            p.set_value(np.zeros_like(p.numpy()))
            static.load(prog, str(tmp_path / "m"))
            prog._compiled_cache.clear()
            with static.program_guard(prog):
                o2 = exe.run(prog, feed={"x": np.ones((2, 4), np.float32)})
            np.testing.assert_allclose(o1[0], o2[0])
        finally:
            paddle.disable_static()

    def test_builder_side_effects_once_per_run(self):
        paddle.enable_static()
        try:
            calls = []
            prog = static.Program()
            with static.program_guard(prog):
                static.data("x", [None, 2])

            def build(feed):
                calls.append(1)
                return feed["x"] * 2

            prog.set_builder(build)
            exe = static.Executor()
            with static.program_guard(prog):
                exe.run(prog, feed={"x": np.ones((1, 2), np.float32)})
            assert len(calls) == 1
        finally:
            paddle.disable_static()

    def test_ema_fixed_decay_without_thres(self):
        net = paddle.nn.Linear(2, 2)
        ema = static.ExponentialMovingAverage(0.999).register(net.parameters())
        w0 = net.weight.numpy().copy()
        net.weight.set_value(w0 + 1.0)
        ema.update()
        np.testing.assert_allclose(
            ema._shadow[0], 0.999 * w0 + 0.001 * (w0 + 1.0), rtol=1e-6
        )

    def test_print_summarize_all(self, capsys):
        static.Print(paddle.to_tensor(np.arange(5)), summarize=-1)
        assert "4" in capsys.readouterr().out

    def test_scope_var_slot(self):
        sc = static.Scope()
        v = sc.var("x")
        v.set(np.ones(2, np.float32))
        assert sc.find_var("x").get_tensor().shape == [2]

    def test_sparse_conv_grads_flow(self):
        import paddle_tpu.sparse as S

        dense = np.zeros((1, 4, 4, 4, 2), np.float32)
        dense[0, 1, 1, 1] = [1.0, -1.0]
        idx = np.stack(np.nonzero(np.abs(dense).sum(-1) > 0))
        sp = S.sparse_coo_tensor(
            paddle.to_tensor(idx), paddle.to_tensor(dense[tuple(idx)]),
            shape=[1, 4, 4, 4, 2],
        )
        conv = S.Conv3D(2, 4, 3, padding=1)
        conv(sp).values.sum().backward()
        assert conv.weight.grad is not None
        assert np.isfinite(conv.weight.grad.numpy()).all()


def test_program_ops_introspection():
    """reference: Program.global_block().ops — op-level views of the
    traced program (read-only here; jaxpr is the IR)."""
    import paddle_tpu as paddle
    import paddle_tpu.static as static

    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [4, 8], "float32")

        def build(feed):
            h = static.nn.fc(feed["x"], size=16)
            return paddle.tanh(h)

        prog.set_builder(build)
    ops = prog.ops
    types = [o.type for o in ops]
    assert any("dot" in t or "matmul" in t for t in types), types
    assert "tanh" in types, types
    matmuls = [o for o in ops if "dot" in o.type]
    assert matmuls[0].output_shapes()[0] == (4, 16)
    assert "op " in repr(ops[0]) and "Program(" in repr(prog)
    # cached: second access returns without retracing
    assert len(prog.ops) == len(ops)
    # introspection must NOT poison later executions (leaked-tracer guard)
    import numpy as np
    exe = static.Executor()
    out = exe.run(prog, feed={"x": np.ones((4, 8), np.float32)},
                  fetch_list=None)
    assert np.all(np.isfinite(np.asarray(out[0])))
    out2 = exe.run(prog, feed={"x": np.ones((4, 8), np.float32)},
                   fetch_list=None)
    np.testing.assert_allclose(np.asarray(out[0]), np.asarray(out2[0]))
    # the default program was not polluted with this program's layers
    assert not getattr(static.default_main_program(), "_static_layers", {})
    # no builder -> clear error
    import pytest as _pytest

    with _pytest.raises(RuntimeError, match="no builder"):
        static.Program().ops
