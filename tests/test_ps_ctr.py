"""CTR accessor semantics on the sparse table (VERDICT r3 task 6).

Reference analogues: ps/table/ctr_accessor.h CtrCommonAccessor (show/click
counters, time decay, ShowClickScore-based eviction) and
ps/table/sparse_sgd_rule.h (pluggable naive/adagrad/adam rules) — here the
accessor lives inside the C++ sharded table (csrc/ps_sparse_table.h) and is
exercised both in-process and over the framed-TCP wire.
"""
import numpy as np
import pytest

from paddle_tpu.distributed.ps import CtrAccessorConfig, MemorySparseTable


def _table(**kw):
    cfg = dict(emb_dim=4, optimizer="sgd", learning_rate=0.1, init_range=0.0,
               ctr=CtrAccessorConfig(show_coeff=0.25, click_coeff=1.0,
                                     decay_rate=0.98, delete_threshold=0.8,
                                     delete_after_unseen_days=30))
    cfg.update(kw)
    return MemorySparseTable(**cfg)


def test_push_ctr_accumulates_show_click():
    t = _table()
    keys = np.array([7, 8], np.int64)
    g = np.zeros((2, 4), np.float32)
    t.push_ctr(keys, shows=[1.0, 1.0], clicks=[1.0, 0.0], grads=g)
    t.push_ctr(np.array([7], np.int64), shows=[1.0], clicks=[1.0],
               grads=np.zeros((1, 4), np.float32))
    show, click, unseen, score = t.ctr_stats(7)
    assert (show, click, unseen) == (2.0, 2.0, 0.0)
    # score = 0.25*(show-click) + 1.0*click
    np.testing.assert_allclose(score, 2.0)
    show8, click8, _, score8 = t.ctr_stats(8)
    assert (show8, click8) == (1.0, 0.0)
    np.testing.assert_allclose(score8, 0.25)
    assert t.ctr_stats(999) is None


def test_shrink_decays_and_evicts_low_score():
    t = _table()
    g1 = np.zeros((1, 4), np.float32)
    t.push_ctr(np.array([1], np.int64), [5.0], [5.0], g1)   # score 5
    t.push_ctr(np.array([2], np.int64), [1.0], [0.0], g1)   # score 0.25
    assert len(t) == 2
    evicted = t.shrink()
    # key 2: 0.25*0.98 = 0.245 < 0.8 -> evicted; key 1: 4.9 > 0.8 survives
    assert evicted == 1 and len(t) == 1
    show, click, unseen, _ = t.ctr_stats(1)
    np.testing.assert_allclose([show, click, unseen], [4.9, 4.9, 1.0],
                               rtol=1e-6)
    assert t.ctr_stats(2) is None


def test_shrink_evicts_long_unseen():
    t = _table(ctr=CtrAccessorConfig(delete_threshold=0.0,
                                     delete_after_unseen_days=3,
                                     decay_rate=1.0))
    t.push_ctr(np.array([5], np.int64), [100.0], [100.0],
               np.zeros((1, 4), np.float32))
    for day in range(3):
        assert t.shrink() == 0, day
    assert t.shrink() == 1  # unseen_days exceeds 3
    assert len(t) == 0


def test_decay_is_exact_geometric():
    t = _table(ctr=CtrAccessorConfig(decay_rate=0.5, delete_threshold=0.0,
                                     delete_after_unseen_days=100))
    t.push_ctr(np.array([3], np.int64), [8.0], [4.0],
               np.zeros((1, 4), np.float32))
    for _ in range(3):
        t.shrink()
    show, click, unseen, _ = t.ctr_stats(3)
    np.testing.assert_allclose([show, click, unseen], [1.0, 0.5, 3.0])


def test_push_ctr_resets_unseen_clock():
    t = _table()
    t.push_ctr(np.array([9], np.int64), [1.0], [1.0],
               np.zeros((1, 4), np.float32))
    t.shrink()
    assert t.ctr_stats(9)[2] == 1.0
    t.push_ctr(np.array([9], np.int64), [1.0], [1.0],
               np.zeros((1, 4), np.float32))
    assert t.ctr_stats(9)[2] == 0.0


# -- pluggable SGD rules -------------------------------------------------------
def test_adam_rule_matches_numpy():
    t = MemorySparseTable(emb_dim=4, optimizer="adam", learning_rate=0.01,
                          init_range=0.0)
    key = np.array([11], np.int64)
    g = np.full((1, 4), 0.5, np.float32)
    t.push(key, g)
    t.push(key, g)
    # manual adam, beta1=.9 beta2=.999 eps=1e-6, w0=0
    w = np.zeros(4)
    m = np.zeros(4)
    v = np.zeros(4)
    b1p = b2p = 1.0
    for _ in range(2):
        b1p *= 0.9
        b2p *= 0.999
        m = 0.9 * m + 0.1 * 0.5
        v = 0.999 * v + 0.001 * 0.25
        w -= 0.01 * (m / (1 - b1p)) / (np.sqrt(v / (1 - b2p)) + 1e-6)
    np.testing.assert_allclose(t.pull(key)[0], w, rtol=1e-5)


def test_sgd_rules_selectable():
    for opt in ("sgd", "adagrad", "adam"):
        t = MemorySparseTable(emb_dim=2, optimizer=opt, learning_rate=0.1,
                              init_range=0.0)
        k = np.array([1], np.int64)
        t.push(k, np.ones((1, 2), np.float32))
        assert np.all(t.pull(k) < 0)  # every rule moved against the grad


def test_ctr_save_load_roundtrip(tmp_path):
    t = _table(optimizer="adam")
    t.push_ctr(np.array([1, 2], np.int64), [3.0, 1.0], [2.0, 0.0],
               np.random.default_rng(0).standard_normal((2, 4)).astype(np.float32))
    t.shrink()
    path = str(tmp_path / "ctr.tbl")
    t.save(path)
    t2 = _table(optimizer="adam")
    t2.load(path)
    assert len(t2) == len(t)
    np.testing.assert_allclose(t2.ctr_stats(1), t.ctr_stats(1), rtol=1e-6)
    np.testing.assert_array_equal(
        t2.pull(np.array([1], np.int64)), t.pull(np.array([1], np.int64))
    )


# -- over the wire -------------------------------------------------------------
@pytest.mark.slow
def test_ctr_over_the_wire():
    from paddle_tpu.distributed.ps import (
        DistributedSparseTable, PsClient, PsServer,
    )

    s0 = PsServer(port=0, server_id=0, n_servers=2, n_trainers=1)
    s1 = PsServer(port=0, server_id=1, n_servers=2, n_trainers=1)
    eps = [f"127.0.0.1:{s0.port}", f"127.0.0.1:{s1.port}"]
    c = PsClient(eps, trainer_id=0)
    try:
        ctr = CtrAccessorConfig(show_coeff=0.25, click_coeff=1.0,
                                decay_rate=0.98, delete_threshold=0.8,
                                delete_after_unseen_days=30)
        t = DistributedSparseTable(c, 21, emb_dim=8, optimizer="adagrad",
                                   learning_rate=0.05, ctr=ctr)
        # keys spread over both servers by hash
        keys = np.arange(1, 41, dtype=np.int64)
        shows = np.ones(40, np.float32)
        clicks = (keys % 2 == 0).astype(np.float32) * 2.0
        grads = np.random.default_rng(1).standard_normal((40, 8)).astype(np.float32)
        t.pull(keys)
        t.push_ctr(keys, shows, clicks, grads)
        # wire stats match the accessor math
        show, click, unseen, score = t.ctr_stats(2)
        assert (show, click, unseen) == (1.0, 2.0, 0.0)
        np.testing.assert_allclose(score, 0.25 * (1.0 - 2.0) + 2.0)
        # odd keys score 0.25 -> evicted on shrink; even keys survive
        evicted = t.shrink()
        assert evicted == 20
        assert c.stat(21) == 20
        assert t.ctr_stats(3) is None and t.ctr_stats(4) is not None
    finally:
        c.stop_servers()


@pytest.mark.slow
def test_fused_dense_push_pull_matches_separate():
    from paddle_tpu.distributed.ps import PsClient, PsServer

    s0 = PsServer(port=0, server_id=0, n_servers=2, n_trainers=1)
    s1 = PsServer(port=0, server_id=1, n_servers=2, n_trainers=1)
    c = PsClient([f"127.0.0.1:{s0.port}", f"127.0.0.1:{s1.port}"],
                 trainer_id=0)
    try:
        rng = np.random.default_rng(0)
        n = 10_001  # odd length exercises the range split
        init = rng.normal(size=n).astype(np.float32)
        g1 = rng.normal(size=n).astype(np.float32)
        g2 = rng.normal(size=n).astype(np.float32)
        # table A: separate push + pull
        c.create_dense_table(1, n, "sgd", 0.1, init=init)
        c.push_dense(1, g1)
        sep = c.pull_dense(1, n)
        # table B: fused round trip from the same start
        c.create_dense_table(2, n, "sgd", 0.1, init=init)
        fused = c.push_pull_dense(2, g1)
        np.testing.assert_allclose(fused, sep, rtol=1e-6)
        # second step keeps them in lockstep
        c.push_dense(1, g2)
        np.testing.assert_allclose(
            c.push_pull_dense(2, g2), c.pull_dense(1, n), rtol=1e-6
        )
        # fused is one round trip: time both paths (informational; assert
        # only that fused is not SLOWER by more than noise)
        import time as _t

        t0 = _t.perf_counter()
        for _ in range(20):
            c.push_dense(1, g1)
            c.pull_dense(1, n)
        sep_dt = _t.perf_counter() - t0
        t0 = _t.perf_counter()
        for _ in range(20):
            c.push_pull_dense(2, g1)
        fused_dt = _t.perf_counter() - t0
        print(f"dense wire: separate {sep_dt * 50:.2f} ms/step, "
              f"fused {fused_dt * 50:.2f} ms/step")
        assert fused_dt < sep_dt * 1.2
    finally:
        c.stop_servers()
