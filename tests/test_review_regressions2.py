"""Regression tests for code-review findings (round 2: optimizer cache,
buffer threading under jit, GradScaler double-unscale, jit.save buffers,
compiled-step clip/decay parity, AMP grad dtype, to_static array args)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def test_optimizer_cache_respects_weight_decay():
    p1 = nn.Parameter(np.ones(3, np.float32))
    o1 = paddle.optimizer.SGD(learning_rate=0.0, parameters=[p1], weight_decay=0.0)
    p1.grad = paddle.zeros([3])
    o1.step()
    np.testing.assert_allclose(p1.numpy(), [1, 1, 1])

    p2 = nn.Parameter(np.ones(3, np.float32))
    o2 = paddle.optimizer.SGD(learning_rate=1.0, parameters=[p2], weight_decay=0.5)
    p2.grad = paddle.zeros([3])
    o2.step()
    # wd applied: p - lr*(g + wd*p) = 1 - 0.5 = 0.5
    np.testing.assert_allclose(p2.numpy(), [0.5, 0.5, 0.5])


def test_batchnorm_stats_update_under_to_static():
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(4, 8), nn.BatchNorm1D(8))
    bn = net[1]
    snet = paddle.jit.to_static(net)
    x = paddle.randn([16, 4]) * 3 + 1
    snet(x)
    assert not np.allclose(bn._mean.numpy(), np.zeros(8))
    assert not np.allclose(bn._variance.numpy(), np.ones(8))


def test_batchnorm_stats_update_in_compiled_train_step():
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(4, 8), nn.BatchNorm1D(8), nn.Linear(8, 1))
    bn = model[1]
    opt = paddle.optimizer.SGD(learning_rate=0.01, parameters=model.parameters())
    step = paddle.jit.compile_train_step(model, F.mse_loss, opt)
    x = paddle.randn([32, 4]) * 2 + 5
    y = paddle.randn([32, 1])
    step(x, y)
    assert not np.allclose(bn._mean.numpy(), np.zeros(8))


def test_grad_scaler_explicit_unscale_not_double():
    p = nn.Parameter(np.zeros(1, np.float32))
    o = paddle.optimizer.SGD(learning_rate=1.0, parameters=[p])
    scaler = paddle.amp.GradScaler(init_loss_scaling=128.0)
    loss = (p * 4.0).sum()
    scaler.scale(loss).backward()
    scaler.unscale_(o)
    np.testing.assert_allclose(p.grad.numpy(), [4.0])
    scaler.step(o)  # must not unscale again
    np.testing.assert_allclose(p.numpy(), [-4.0])


def test_jit_save_load_with_nonpersistable_buffer(tmp_path):
    class WithBuf(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 2)
            self.register_buffer("offset", paddle.ones([2]), persistable=False)

        def forward(self, x):
            return self.fc(x) + self.offset

    net = WithBuf()
    net.eval()
    x = paddle.randn([3, 4])
    expected = net(x).numpy()
    path = str(tmp_path / "m")
    paddle.jit.save(net, path, input_spec=[paddle.jit.InputSpec([3, 4], "float32")])
    loaded = paddle.jit.load(path)
    np.testing.assert_allclose(loaded(x).numpy(), expected, rtol=1e-5, atol=1e-6)


def test_compiled_step_applies_grad_clip():
    def run(clip):
        paddle.seed(1)
        m = nn.Linear(2, 1, bias_attr=False)
        m.weight.set_value(np.zeros((2, 1), np.float32))
        o = paddle.optimizer.SGD(learning_rate=1.0, parameters=m.parameters(), grad_clip=clip)
        step = paddle.jit.compile_train_step(m, F.mse_loss, o)
        x = paddle.to_tensor(np.ones((4, 2), np.float32) * 10)
        y = paddle.to_tensor(np.ones((4, 1), np.float32) * 100)
        step(x, y)
        return m.weight.numpy()

    unclipped = run(None)
    clipped = run(nn.ClipGradByGlobalNorm(0.1))
    assert np.abs(clipped).sum() < np.abs(unclipped).sum() * 0.01
    np.testing.assert_allclose(np.sqrt((clipped**2).sum()), 0.1, rtol=1e-3)


def test_compiled_step_adamw_skips_decay_for_excluded():
    paddle.seed(0)
    m = nn.Linear(4, 4)
    m.bias.name = "linear_bias"
    m.weight.name = "linear_weight"
    opt = paddle.optimizer.AdamW(
        learning_rate=0.0,  # isolate the decay term: lr=0 → only wd acts...
        weight_decay=0.5,
        parameters=m.parameters(),
        apply_decay_param_fun=lambda n: "bias" not in n,
    )
    # with lr=0 AdamW's decoupled decay p*(1-lr*wd) is also 0 — use lr>0 and
    # zero grads instead so only the decay term moves params
    opt2 = paddle.optimizer.AdamW(
        learning_rate=0.1,
        weight_decay=0.5,
        parameters=m.parameters(),
        apply_decay_param_fun=lambda n: "bias" not in n,
    )

    class ZeroLoss(nn.Layer):
        def __init__(self, inner):
            super().__init__()
            self.inner = inner

        def forward(self, x):
            return self.inner(x).sum() * 0.0

    m.bias.set_value(np.ones(4, np.float32))
    w_before = m.weight.numpy().copy()
    step = paddle.jit.compile_train_step(ZeroLoss(m), None, opt2)
    step(paddle.ones([2, 4]), paddle.zeros([1]))
    # weight decayed (×(1-0.05)), bias untouched by decay
    np.testing.assert_allclose(m.weight.numpy(), w_before * 0.95, rtol=1e-4)
    np.testing.assert_allclose(m.bias.numpy(), np.ones(4), rtol=1e-5)


def test_amp_o1_param_grads_fp32():
    m = nn.Linear(4, 4)
    x = paddle.randn([2, 4])
    with paddle.amp.auto_cast(level="O1", dtype="bfloat16"):
        out = m(x)
    out.sum().backward()
    assert m.weight.grad is not None
    assert m.weight.grad.dtype == paddle.float32  # cast back, not bf16


def test_to_static_numpy_array_arg_not_baked():
    @paddle.jit.to_static
    def fn(x, arr):
        return x + arr

    a1 = np.arange(2000, dtype=np.float32)
    a2 = -np.arange(2000, dtype=np.float32)
    x = paddle.zeros([2000])
    np.testing.assert_allclose(fn(x, a1).numpy(), a1)
    np.testing.assert_allclose(fn(x, a2).numpy(), a2)  # not the stale a1
