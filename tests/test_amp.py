"""AMP tests: O1 auto_cast lists, O2 decorate, GradScaler dynamics."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def test_o1_casts_matmul_to_bf16():
    x = paddle.randn([4, 8])
    w = paddle.randn([8, 4])
    with paddle.amp.auto_cast(level="O1", dtype="bfloat16"):
        out = paddle.matmul(x, w)
    assert out.dtype == paddle.bfloat16
    out2 = paddle.matmul(x, w)
    assert out2.dtype == paddle.float32  # outside context


def test_o1_blacklist_stays_fp32():
    x = paddle.randn([4, 8]).astype("bfloat16")
    with paddle.amp.auto_cast(level="O1"):
        out = F.softmax(x)
    assert out.dtype == paddle.float32


def test_o1_training_converges():
    paddle.seed(0)
    m = nn.Linear(4, 1)
    o = paddle.optimizer.SGD(learning_rate=0.1, parameters=m.parameters())
    x = paddle.randn([32, 4])
    y = x.sum(axis=1, keepdim=True)
    for _ in range(40):
        with paddle.amp.auto_cast(level="O1"):
            loss = F.mse_loss(m(x), y)
        loss.backward()
        o.step()
        o.clear_grad()
    assert float(loss) < 0.1
    assert m.weight.dtype == paddle.float32  # params stay fp32 in O1


def test_o2_decorate_casts_params():
    m = nn.Linear(4, 4)
    m2 = paddle.amp.decorate(m, level="O2", dtype="bfloat16")
    assert m2.weight.dtype == paddle.bfloat16
    out = m2(paddle.randn([2, 4]).astype("bfloat16"))
    assert out.dtype == paddle.bfloat16


def test_grad_scaler_scales_and_unscales():
    p = nn.Parameter(np.zeros(2, np.float32))
    o = paddle.optimizer.SGD(learning_rate=1.0, parameters=[p])
    scaler = paddle.amp.GradScaler(init_loss_scaling=128.0)
    loss = (p * paddle.to_tensor([1.0, 2.0])).sum()
    scaled = scaler.scale(loss)
    scaled.backward()
    np.testing.assert_allclose(p.grad.numpy(), [128.0, 256.0])
    scaler.step(o)
    scaler.update()
    np.testing.assert_allclose(p.numpy(), [-1.0, -2.0])  # unscaled applied


def test_grad_scaler_skips_inf_and_decays():
    p = nn.Parameter(np.zeros(1, np.float32))
    o = paddle.optimizer.SGD(learning_rate=1.0, parameters=[p])
    scaler = paddle.amp.GradScaler(
        init_loss_scaling=64.0, decr_every_n_nan_or_inf=1
    )
    p.grad = paddle.to_tensor([np.inf], dtype="float32")
    scaler.step(o)
    scaler.update()
    np.testing.assert_allclose(p.numpy(), [0.0])  # step skipped
    assert scaler.get_init_loss_scaling() == pytest.approx(32.0)  # decayed


def test_scaler_minimize():
    paddle.seed(0)
    m = nn.Linear(4, 1)
    o = paddle.optimizer.SGD(learning_rate=0.05, parameters=m.parameters())
    scaler = paddle.amp.GradScaler()
    x = paddle.randn([16, 4])
    y = x.sum(axis=1, keepdim=True)
    for _ in range(50):
        with paddle.amp.auto_cast(level="O1"):
            loss = F.mse_loss(m(x), y)
        scaler.minimize(o, scaler.scale(loss))
        o.clear_grad()
    assert float(loss) < 0.2
