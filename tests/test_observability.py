"""Runtime observability (ISSUE 9): flight recorder, unified metrics
registry, crash postmortems, and the merged chrome trace.

Acceptance slices covered here:
  - the flight-recorder ring is bounded under sustained load, ordered, and
    free on the off-mode fast path;
  - a forced unrecovered fault dumps a postmortem JSON (subprocess) whose
    event tail explains the fault (site, retries);
  - Prometheus text exposition round-trips against the snapshot API;
  - serving request lanes join into the merged chrome trace (b/n/e async
    events per request id);
  - dispatch_counters() is an immutable snapshot; capture fallback-reason
    events match the capture_fallback_reasons histogram;
  - the step-stall watchdog trips once per episode and re-arms.
"""
import json
import os
import subprocess
import sys
import tempfile
import time

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.profiler as prof
import paddle_tpu.resilience as res
from paddle_tpu.profiler import metrics, trace

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _obs_isolation():
    res.reset()
    prof.reset_dispatch_counters()
    trace.clear()
    yield
    paddle.set_flags({
        "FLAGS_trace_ring_size": 4096,
        "FLAGS_trace_stall_ms": 0.0,
        "FLAGS_postmortem_dir": "",
        "FLAGS_fault_inject": "",
        "FLAGS_eager_lazy_dispatch": False,
        "FLAGS_retry_backoff_ms": 5.0,
        "FLAGS_retry_max": 2,
    })
    res.reset()
    trace.clear()


# ---------------------------------------------------------------------------
# flight recorder: ring mechanics
# ---------------------------------------------------------------------------
def test_ring_bounded_under_sustained_load_and_ordered():
    paddle.set_flags({"FLAGS_trace_ring_size": 128})
    trace.clear()
    for i in range(5000):
        trace.emit("probe", site="test", step=0, i=i)
    evs = trace.events()
    assert len(evs) == 128  # bounded, not 5000
    # ordering: the ring keeps the newest events, oldest first
    idx = [e.attrs["i"] for e in evs]
    assert idx == list(range(5000 - 128, 5000))
    ts = [e.ts for e in evs]
    assert ts == sorted(ts)
    # tail selection
    assert [e.attrs["i"] for e in trace.events(last=3)] == [4997, 4998, 4999]


def test_ring_off_mode_fast_path_and_resize():
    paddle.set_flags({"FLAGS_trace_ring_size": 0})
    trace.clear()
    assert not trace.enabled()
    assert trace.emit("probe", site="x") is None
    assert trace.events() == []
    # off mode must be CHEAP: no event objects, no clock reads — bound the
    # per-call cost loosely (it's one dict read + a falsy test)
    n = 200_000
    t0 = time.perf_counter()
    for _ in range(n):
        trace.emit("probe")
    per_call_us = (time.perf_counter() - t0) / n * 1e6
    assert per_call_us < 5.0, f"off-mode emit costs {per_call_us:.2f}us"
    # re-enable: emission resumes with the new capacity
    paddle.set_flags({"FLAGS_trace_ring_size": 16})
    for i in range(40):
        trace.emit("probe", i=i)
    assert len(trace.events()) == 16


def test_events_auto_fill_step_from_fault_clock():
    paddle.set_flags({"FLAGS_trace_ring_size": 64})
    res.reset()
    from paddle_tpu.resilience import faults

    faults.advance_step()
    faults.advance_step()
    ev = trace.emit("probe", site="x")
    assert ev.step == 2
    assert trace.emit("probe", step=7).step == 7


# ---------------------------------------------------------------------------
# runtime events at the choke points
# ---------------------------------------------------------------------------
def _lenet_free_step():
    paddle.seed(0)
    w = paddle.to_tensor(np.random.randn(8, 4).astype(np.float32),
                         stop_gradient=False)
    x = paddle.to_tensor(np.random.randn(2, 8).astype(np.float32))
    loss = (x @ w).sum()
    loss.backward()
    return w


def test_flush_and_program_events_under_lazy_dispatch():
    paddle.set_flags({"FLAGS_eager_lazy_dispatch": True,
                      "FLAGS_trace_ring_size": 4096})
    trace.clear()
    _lenet_free_step()
    kinds = {(e.kind, e.site) for e in trace.events()}
    assert ("flush", "segment") in kinds
    assert ("program", "segment") in kinds
    assert ("program", "backward") in kinds
    flush = [e for e in trace.events() if e.kind == "flush"][0]
    assert flush.attrs["reason"] in ("backward", "sync")
    assert flush.attrs["cache"] in ("hit", "miss", "join")


def test_capture_fallback_reason_events_match_counters():
    """The fallback-reason event stream must agree with the
    capture_fallback_reasons histogram — the obs_probe gate's contract."""
    paddle.set_flags({"FLAGS_eager_lazy_dispatch": True,
                      "FLAGS_eager_step_capture": True,
                      "FLAGS_trace_ring_size": 4096})
    trace.clear()
    prof.reset_dispatch_counters()
    paddle.seed(0)
    w = paddle.to_tensor(np.random.randn(4, 4).astype(np.float32),
                         stop_gradient=False)
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=[w])
    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    for step in range(5):
        loss = (x @ w).sum()
        loss.backward()
        if step >= 3:
            # reading the grad between backward and step aborts a deferred
            # captured step — a counted, reasoned fallback
            _ = w.grad.numpy()
        opt.step()
        opt.clear_grad()
    reasons = dict(prof.dispatch_counters()["capture_fallback_reasons"])
    ev_reasons = {}
    for e in trace.events():
        if e.kind == "capture" and e.attrs and e.attrs.get("phase") == "fallback":
            r = e.attrs["reason"]
            ev_reasons[r] = ev_reasons.get(r, 0) + 1
    assert reasons, "expected at least one capture fallback"
    assert ev_reasons == reasons


def test_fault_retry_and_ladder_events():
    paddle.set_flags({"FLAGS_eager_lazy_dispatch": True,
                      "FLAGS_fault_inject": "execute:segment:p=1:x=1",
                      "FLAGS_retry_backoff_ms": 0.1,
                      "FLAGS_trace_ring_size": 4096})
    trace.clear()
    _lenet_free_step()
    paddle.set_flags({"FLAGS_fault_inject": ""})
    kinds = [e.kind for e in trace.events()]
    assert "fault" in kinds and "retry" in kinds
    fault = [e for e in trace.events() if e.kind == "fault"][0]
    assert fault.site == "segment"
    assert fault.attrs["injected"] and fault.attrs["transient"]
    retry = [e for e in trace.events() if e.kind == "retry"][0]
    assert retry.attrs["attempt"] == 1


def test_ckpt_events():
    from paddle_tpu.distributed.checkpoint import AsyncCheckpointer

    paddle.set_flags({"FLAGS_trace_ring_size": 4096})
    trace.clear()
    w = paddle.to_tensor(np.ones((2, 2), np.float32))
    with tempfile.TemporaryDirectory() as d:
        ck = AsyncCheckpointer(d, max_to_keep=1)
        ck.save(0, {"w": w})
        ck.wait()
    phases = {e.attrs["phase"] for e in trace.events() if e.kind == "ckpt"}
    assert "snapshot" in phases and "commit" in phases


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------
def test_registry_types_and_snapshot():
    reg = metrics.MetricsRegistry()
    c = reg.counter("events", doc="events seen")
    c.inc()
    c.inc(2)
    g = reg.gauge("depth")
    g.set(3)
    g.add(-1)
    h = reg.histogram("lat_ms")
    for v in (1.0, 2.0, 4.0, 1000.0):
        h.observe(v)
    assert reg.counter("events") is c  # get-or-create returns the SAME object
    with pytest.raises(TypeError):
        reg.gauge("events")  # type conflict fails loud
    assert reg.histogram("lat_ms") is h
    with pytest.raises(ValueError):
        # a DIFFERENT bucket geometry must not silently hand back the old
        # one (the caller would run with 3x the expected quantile error)
        reg.histogram("lat_ms", factor=1.05)
    with pytest.raises(ValueError):
        c.inc(-1)  # counters are monotonic
    snap = reg.snapshot(include_dispatch=False)
    assert snap["counters"]["events"] == 3
    assert snap["gauges"]["depth"] == 2
    hd = snap["histograms"]["lat_ms"]
    assert hd["count"] == 4 and hd["min"] == 1.0 and hd["max"] == 1000.0
    # mutating the snapshot never touches live state
    snap["counters"]["events"] = 0
    assert reg.snapshot(include_dispatch=False)["counters"]["events"] == 3


def test_histogram_quantiles_bounded_error():
    h = metrics.Histogram()
    rng = np.random.default_rng(0)
    samples = rng.lognormal(mean=1.0, sigma=1.0, size=20_000)
    for v in samples:
        h.observe(float(v))
    for q in (0.5, 0.9, 0.99):
        est = h.quantile(q)
        true = float(np.quantile(samples, q))
        assert abs(est - true) / true < 0.16, (q, est, true)
    assert h.quantile(0.0) == float(samples.min())
    assert h.quantile(1.0) == float(samples.max())
    h.reset()
    assert h.quantile(0.5) is None and h.count == 0


def test_prometheus_text_round_trip():
    reg = metrics.MetricsRegistry()
    reg.counter("requests", labels={"engine": "1"}).inc(5)
    reg.gauge("pool_occupancy").set(0.25)
    h = reg.histogram("lat_ms")
    for v in (1.0, 2.0, 300.0):
        h.observe(v)
    text = reg.prometheus_text(include_dispatch=False)
    parsed = metrics.parse_prometheus_text(text)
    snap = reg.snapshot(include_dispatch=False)
    assert parsed['paddle_requests{engine="1"}'] == snap["counters"][
        'requests{engine="1"}'] == 5
    assert parsed["paddle_pool_occupancy"] == 0.25
    assert parsed["paddle_lat_ms_count"] == 3
    assert abs(parsed["paddle_lat_ms_sum"] - 303.0) < 1e-6
    # cumulative buckets: the +Inf bucket equals the count
    inf_buckets = [v for k, v in parsed.items()
                   if k.startswith("paddle_lat_ms_bucket") and "+Inf" in k]
    assert inf_buckets and inf_buckets[-1] == 3
    # TYPE lines present for scrapers
    assert "# TYPE paddle_lat_ms histogram" in text
    assert "# TYPE paddle_requests counter" in text


def test_prometheus_label_escaping_round_trip():
    """Hostile label values (backslash, double-quote, newline — exactly
    what an error-string or request-id label carries) must neither corrupt
    the exposition nor break the parse round-trip (exposition format
    v0.0.4 escaping)."""
    reg = metrics.MetricsRegistry()
    hostile = {
        "err": 'Bad "quote" \\ backslash\nand a newline',
        "path": "C:\\tmp\\x",
    }
    reg.counter("errors", labels=hostile).inc(3)
    reg.gauge("plain").set(1)
    text = reg.prometheus_text(include_dispatch=False)
    # escaped single-line samples: no raw newline inside any sample line,
    # every non-comment line still parses as "<name{labels}> <value>"
    for line in text.splitlines():
        if line.startswith("#") or not line:
            continue
        name, _, value = line.rpartition(" ")
        float(value)  # would raise on a split/corrupted line
    assert '\\"quote\\"' in text and "\\\\ backslash" in text
    assert "\\n" in text and "backslash\nand" not in text
    parsed = metrics.parse_prometheus_text(text)
    snap = reg.snapshot(include_dispatch=False)
    (full_name,) = snap["counters"]
    assert parsed["paddle_" + full_name] == 3
    # the escaping is reversible
    assert metrics.unescape_label_value(
        metrics.escape_label_value(hostile["err"])) == hostile["err"]


def test_trace_events_kind_site_filters():
    paddle.set_flags({"FLAGS_trace_ring_size": 256})
    trace.clear()
    for i in range(10):
        trace.emit("alpha", site="s1", i=i)
        trace.emit("alpha", site="s2", i=i)
        trace.emit("beta", site="s1", i=i)
    assert len(trace.events(kind="alpha")) == 20
    assert len(trace.events(kind="beta")) == 10
    assert len(trace.events(site="s1")) == 20
    assert len(trace.events(kind="alpha", site="s2")) == 10
    assert trace.events(kind="nope") == []
    # `last` applies AFTER the filter (trailing N matching), oldest first
    tail = trace.events(kind="alpha", site="s1", last=3)
    assert [e.attrs["i"] for e in tail] == [7, 8, 9]
    ts = [e.ts for e in trace.events(kind="alpha")]
    assert ts == sorted(ts)


def test_concurrent_scrape_vs_reset_exposition():
    """Satellite of ISSUE 13: snapshot()/prometheus_text() hammered from a
    scraper thread while an off-thread writer bumps counters (incl. nested
    families) and reset_dispatch_counters() fires must never raise and
    never emit a torn/partial exposition."""
    import threading

    from paddle_tpu.core import dispatch

    stop = threading.Event()
    errors = []

    def writer():
        # the LEGITIMATE off-thread writer paths (the async-compile worker
        # and persist threads use these); an early writer death would
        # silently hollow the stress out, so its errors are recorded too
        try:
            i = 0
            while not stop.is_set():
                dispatch._counter_add("async_compile_ms", 0.5)
                dispatch._counter_add_labeled("flush_reasons", f"r{i % 7}")
                dispatch._counter_add("programs", 1)
                i += 1
        except Exception as e:  # pragma: no cover - the regression
            errors.append(e)

    def resetter():
        try:
            while not stop.is_set():
                prof.reset_dispatch_counters()
        except Exception as e:  # pragma: no cover - the regression
            errors.append(e)

    def scraper():
        while not stop.is_set():
            try:
                snap = metrics.snapshot(include_dispatch=True)
                assert "programs" in snap["counters"]
                text = metrics.prometheus_text(include_dispatch=True)
                parsed = metrics.parse_prometheus_text(text)
                # a torn family would show up as an unparseable line
                # (parse floats every value) or a missing core counter
                assert "paddle_programs" in parsed
            except Exception as e:  # pragma: no cover - the regression
                errors.append(e)
                return

    threads = [threading.Thread(target=f)
               for f in (writer, resetter, scraper, scraper)]
    for t in threads:
        t.start()
    time.sleep(0.4)  # plenty of interleavings; the race is per-call
    stop.set()
    for t in threads:
        t.join(timeout=5)
    assert not errors, errors[:1]


def test_dispatch_counters_adopted_by_registry():
    prof.reset_dispatch_counters()
    _ = paddle.to_tensor(np.ones((2, 2), np.float32)) + 1.0
    snap = metrics.snapshot()
    assert snap["counters"]["programs"] >= 1
    text = metrics.prometheus_text()
    parsed = metrics.parse_prometheus_text(text)
    assert parsed["paddle_programs"] >= 1
    # nested reason dicts become labeled counter families
    paddle.set_flags({"FLAGS_eager_lazy_dispatch": True})
    _lenet_free_step()
    paddle.set_flags({"FLAGS_eager_lazy_dispatch": False})
    parsed = metrics.parse_prometheus_text(metrics.prometheus_text())
    assert any(k.startswith("paddle_flush_reasons{reason=")
               for k in parsed)


def test_dispatch_counters_snapshot_is_immutable():
    c = prof.dispatch_counters()
    with pytest.raises(TypeError):
        c["programs"] = 0
    with pytest.raises(TypeError):
        c["flush_reasons"]["x"] = 1
    # measure_programs annotates a DEEP copy: nested reason maps are plain
    # dicts again, so the measurement is mutable and JSON-serializable
    out = prof.measure_programs(
        lambda: paddle.to_tensor(np.ones((2, 2), np.float32)) + 1.0)
    assert "_capture_state" in out and "_step_result" in out
    out["flush_reasons"]["x"] = 1  # mutable
    json.dumps({k: v for k, v in out.items() if not k.startswith("_")})


def test_counter_reset_race_free_helper():
    from paddle_tpu.core import dispatch

    prof.reset_dispatch_counters()
    dispatch._counter_add("async_compile_ms", 1.5)
    assert prof.dispatch_counters()["async_compile_ms"] == 1.5
    # after a reset, an off-thread add lands on the fresh dict (no KeyError)
    prof.reset_dispatch_counters()
    dispatch._counter_add("async_compile_ms", 2.0)
    assert prof.dispatch_counters()["async_compile_ms"] == 2.0


# ---------------------------------------------------------------------------
# serving: histogram-backed stats + request-span join in the chrome trace
# ---------------------------------------------------------------------------
def _tiny_engine():
    from paddle_tpu import serving
    from paddle_tpu.models import GPTConfig, GPTForPretraining

    paddle.seed(7)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                    num_heads=2, max_seq_len=32, dropout=0.0,
                    attn_dropout=0.0)
    m = GPTForPretraining(cfg)
    m.eval()
    return serving.Engine(m, serving.ServingConfig(
        block_size=8, prompt_buckets=[8], num_blocks=24))


def test_serving_stats_backed_by_histogram():
    eng = _tiny_engine()
    try:
        resp = eng.serve([[1, 2, 3], [4, 5]], max_new_tokens=4)
        assert all(r.status == "ok" for r in resp)
        st = eng.stats()
        assert st["token_lat_p50_ms"] is not None
        assert st["token_lat_p99_ms"] >= st["token_lat_p50_ms"]
        assert st["token_lat_count"] >= 8  # lifetime samples, no reservoir
        # the histogram is registered (prometheus sees per-engine latency)
        parsed = metrics.parse_prometheus_text(metrics.prometheus_text())
        assert any(k.startswith("paddle_serve_token_lat_ms_count")
                   for k in parsed)
        eng.reset_stats()
        assert eng.stats()["token_lat_p50_ms"] is None
    finally:
        eng.close()
    # close() unregisters the per-engine histogram
    assert not any(
        m.name == "serve_token_lat_ms"
        and m.labels.get("engine") == str(eng._uid)
        for m in metrics.default_registry().metrics()
    )


def test_serving_request_span_join_in_chrome_trace():
    paddle.set_flags({"FLAGS_trace_ring_size": 4096})
    trace.clear()
    eng = _tiny_engine()
    try:
        ids = [eng.submit([1, 2, 3], max_new_tokens=4),
               eng.submit([4, 5], max_new_tokens=4)]
        # rejected at submit (context beyond the model's positions): its
        # lane never began, so it must render as an instant — an unmatched
        # async-end would be dropped as malformed by perfetto
        rejected = eng.submit([1] * 8, max_new_tokens=1000)
        eng.run_until_idle()
    finally:
        eng.close()
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "trace.json")
        prof.Profiler(timer_only=True).export(path)
        doc = json.load(open(path))
    serve_evs = [e for e in doc["traceEvents"] if e.get("cat") == "serving"]
    for rid in ids:
        lane = [e for e in serve_evs if e.get("id") == str(rid)]
        phs = [e["ph"] for e in lane]
        # each request is one async lane: begin (admit) ... instants
        # (prefill/decode ticks) ... end (complete)
        assert phs[0] == "b" and phs[-1] == "e", phs
        assert "n" in phs
        phases = [e["args"]["phase"] for e in lane]
        assert "prefill" in phases and "decode" in phases
        # timestamps are ordered within a lane
        ts = [e["ts"] for e in lane]
        assert ts == sorted(ts)
    # the rejected request never began a lane: no async events carry its
    # id (a lone "e"/"n" would be dropped as malformed); it shows up as a
    # plain instant instead
    assert not any(e.get("id") == str(rejected) for e in serve_evs)
    rej_inst = [e for e in serve_evs
                if e["ph"] == "i" and e["args"].get("rid") == rejected]
    assert rej_inst and rej_inst[0]["name"] == "serve:reject"
    # flight instants share the timeline (flush/capture/program events)
    assert any(e.get("cat") == "flight" for e in doc["traceEvents"])


# ---------------------------------------------------------------------------
# postmortems
# ---------------------------------------------------------------------------
_PM_SCRIPT = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["FLAGS_postmortem_dir"] = sys.argv[1]
sys.path.insert(0, sys.argv[2])
import numpy as np
import paddle_tpu as paddle

# an injected fault that outlives the retry budget is unrecovered at the
# per-op floor: it must propagate AND dump a postmortem on the way out
paddle.set_flags({"FLAGS_fault_inject": "execute:op:p=1:x=99",
                  "FLAGS_retry_max": 1, "FLAGS_retry_backoff_ms": 0.1})
try:
    x = paddle.to_tensor(np.ones((2, 2), np.float32))
    (x + x).numpy()
    sys.exit(3)  # UNREACHABLE: the fault must fire
except Exception as e:
    print("fault:", type(e).__name__)
sys.exit(0)
"""


@pytest.mark.slow
def test_postmortem_on_injected_fatal_fault_subprocess():
    with tempfile.TemporaryDirectory() as d:
        script = os.path.join(d, "crash.py")
        with open(script, "w") as f:
            f.write(_PM_SCRIPT)
        out = subprocess.run(
            [sys.executable, script, d, REPO], capture_output=True,
            text=True, timeout=300,
        )
        assert out.returncode == 0, out.stdout + out.stderr
        pms = [f for f in os.listdir(d) if f.startswith("postmortem_")]
        assert pms, "no postmortem written"
        doc = json.load(open(os.path.join(d, sorted(pms)[0])))
    assert doc["reason"] == "unrecovered_fault"
    assert doc["attrs"]["site"] == "op"
    assert doc["attrs"]["retries"] == 1
    assert doc["exception"]["type"] == "InjectedExecuteError"
    # the event tail explains the fault: fault + retry events at the site
    kinds = [(e["kind"], e["site"]) for e in doc["events"]]
    assert ("fault", "op") in kinds and ("retry", "op") in kinds
    # metrics snapshot rode along (dispatch counters adopted)
    assert doc["metrics"]["counters"]["retry_exhausted"] >= 1
    assert doc["memory"] is None or "live_buffer_count" in doc["memory"]


def test_postmortem_disabled_by_default_and_inline_dump():
    assert paddle.get_flags("FLAGS_postmortem_dir")["FLAGS_postmortem_dir"] == ""
    assert trace.dump_postmortem("probe") is None  # no dir — no-op
    with tempfile.TemporaryDirectory() as d:
        paddle.set_flags({"FLAGS_postmortem_dir": d,
                          "FLAGS_postmortem_events": 5})
        for i in range(20):
            trace.emit("probe", i=i)
        path = trace.dump_postmortem("probe", extra="x")
        assert path and os.path.exists(path)
        doc = json.load(open(path))
        probes = [e for e in doc["events"] if e["kind"] == "probe"]
        assert len(probes) <= 5  # FLAGS_postmortem_events caps the tail
        assert doc["attrs"]["extra"] == "x"
        paddle.set_flags({"FLAGS_postmortem_dir": ""})


def test_preempted_postmortem():
    from paddle_tpu.resilience import Preempted, PreemptionGuard

    with tempfile.TemporaryDirectory() as d:
        paddle.set_flags({"FLAGS_postmortem_dir": d})
        guard = PreemptionGuard()
        guard.preempted = True
        guard.signum = 15
        with pytest.raises(Preempted):
            guard.step_boundary(4)
        pms = [f for f in os.listdir(d) if "preempted" in f]
        assert len(pms) == 1
        doc = json.load(open(os.path.join(d, pms[0])))
        assert doc["attrs"]["last_completed_step"] == 4
        paddle.set_flags({"FLAGS_postmortem_dir": ""})


def test_verification_error_postmortem():
    import jax.numpy as jnp
    from paddle_tpu import analysis

    with tempfile.TemporaryDirectory() as d:
        paddle.set_flags({"FLAGS_postmortem_dir": d})
        import jax

        # unguarded log: a numeric-hazard ERROR diagnostic at level 2
        jaxpr = jax.make_jaxpr(lambda x: jnp.log(x))(
            jax.ShapeDtypeStruct((4,), jnp.float32))
        diags = analysis.check(jaxpr, source="test")
        try:
            analysis.enforce(diags, where="test", level=2)
            raised = False
        except analysis.ProgramVerificationError:
            raised = True
        pms = [f for f in os.listdir(d) if "verification" in f]
        assert raised == bool(pms)  # dump iff the verdict raised
        if raised:
            doc = json.load(open(os.path.join(d, pms[0])))
            assert doc["exception"]["type"] == "ProgramVerificationError"
        paddle.set_flags({"FLAGS_postmortem_dir": ""})


# ---------------------------------------------------------------------------
# stall watchdog
# ---------------------------------------------------------------------------
def test_stall_watchdog_trips_once_per_episode():
    with tempfile.TemporaryDirectory() as d:
        paddle.set_flags({"FLAGS_trace_stall_ms": 60.0,
                          "FLAGS_postmortem_dir": d})
        before = trace.stall_count()
        trace.step_heartbeat()
        deadline = time.time() + 5.0
        while trace.stall_count() == before and time.time() < deadline:
            time.sleep(0.02)
        assert trace.stall_count() == before + 1
        # one trip per episode: no second dump while stalled
        time.sleep(0.2)
        assert trace.stall_count() == before + 1
        pms = [f for f in os.listdir(d) if "stall" in f]
        assert len(pms) == 1
        doc = json.load(open(os.path.join(d, pms[0])))
        assert doc["attrs"]["stalled_ms"] >= 60.0
        # a disarmed watchdog stays quiet: a finished training loop looks
        # exactly like a stall, so train_step_range disarms in its finally
        trace.step_heartbeat()
        trace.watchdog_disarm()
        time.sleep(0.25)
        assert trace.stall_count() == before + 1
        assert len([f for f in os.listdir(d) if "stall" in f]) == 1
        paddle.set_flags({"FLAGS_trace_stall_ms": 0.0,
                          "FLAGS_postmortem_dir": ""})


def test_heartbeat_sources_disarm_independently():
    # train and serve heartbeats are separate sources: an idle serving
    # engine standing down (Engine.run_until_idle / Supervisor) must not
    # erase the training loop's liveness signal in a combined process
    trace.watchdog_disarm()
    trace.step_heartbeat("train")
    trace.step_heartbeat("serve")
    assert trace.heartbeat_age_ms("train") is not None
    assert trace.heartbeat_age_ms("serve") is not None
    trace.watchdog_disarm("serve")
    assert trace.heartbeat_age_ms("serve") is None
    assert trace.heartbeat_age_ms("train") is not None
    assert trace.heartbeat_age_ms() is not None  # /healthz still sees train
    # the source-less age is the STALEST armed source (any wedged loop
    # must flip /healthz, not just the most recently beating one)
    time.sleep(0.02)
    trace.step_heartbeat("serve")
    assert (trace.heartbeat_age_ms()
            >= trace.heartbeat_age_ms("serve"))
    assert trace.heartbeat_age_ms() == pytest.approx(
        trace.heartbeat_age_ms("train"), rel=0.5)
    trace.watchdog_disarm()  # argless: every source stands down
    assert trace.heartbeat_age_ms() is None


# ---------------------------------------------------------------------------
# the obs probe CLI gate (subprocess — slow)
# ---------------------------------------------------------------------------
@pytest.mark.slow
def test_obs_probe_cli():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "obs_probe.py"),
         "--steps", "6", "--batch", "8"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=600,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    assert "ALL SCENARIOS PASSED" in out.stdout
