"""CI self-lint: tools/graph_lint.py over the example model builders.

The tier-1 gate from this PR's ISSUE: linting the shipped example models
(`examples/train_vision.py`, `examples/train_gpt.py`) must produce NO
error-severity diagnostics with FLAGS_check_programs=1 — a pass-suite or
model regression that introduces one fails here. Runs the CLI in-process
(same code path as `python tools/graph_lint.py ...`, minus the interpreter
spawn).
"""
import importlib.util
import json
import os
import sys

import pytest

import paddle_tpu as paddle

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cli():
    path = os.path.join(REPO, "tools", "graph_lint.py")
    spec = importlib.util.spec_from_file_location("graph_lint_cli", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture
def check_programs_on():
    paddle.set_flags({"FLAGS_check_programs": 1})
    try:
        yield
    finally:
        paddle.set_flags({"FLAGS_check_programs": 0})


@pytest.mark.parametrize("example", ["train_gpt.py", "train_vision.py"])
def test_example_models_lint_error_clean(example, check_programs_on, capsys):
    rc = _cli().main([os.path.join(REPO, "examples", example)])
    out = capsys.readouterr().out
    assert rc == 0, f"error-severity diagnostics in {example}:\n{out}"
    assert "error[" not in out
    # the CLI footer reports the analysis flags in effect for CI logs
    assert "FLAGS_check_programs=1" in out


@pytest.mark.parametrize("example", ["train_gpt.py", "train_vision.py"])
def test_example_models_stay_under_memory_budget(example, check_programs_on,
                                                 capsys):
    """CI memory gate: both shipped example models must keep their
    liveness-estimated peak HBM under a declared 64 MB budget (current
    estimates: vision ~6 MB, gpt ~15 MB — the budget flags a 4x+ memory
    regression while leaving room for model growth)."""
    rc = _cli().main([os.path.join(REPO, "examples", example),
                      "--memory-budget-mb", "64"])
    out = capsys.readouterr().out
    assert rc == 0, f"memory budget exceeded in {example}:\n{out}"
    assert "estimated peak HBM" in out  # the report diagnostic is emitted

    # and the gate actually bites: an absurdly small budget fails the lint
    rc = _cli().main([os.path.join(REPO, "examples", example),
                      "--memory-budget-mb", "0.001", "--json"])
    lines = [l for l in capsys.readouterr().out.splitlines() if l.strip()]
    assert rc == 1
    recs = [json.loads(l) for l in lines]
    over = [r for r in recs if r["severity"] == "error"
            and r["pass"] == "memory_budget"]
    assert over and over[0]["data"]["peak_bytes"] > 0


def test_lint_fails_on_injected_error(tmp_path, capsys):
    bad = tmp_path / "bad_model.py"
    bad.write_text(
        "import paddle_tpu as paddle\n"
        "def build_model():\n"
        "    fn = lambda x: paddle.log(x).sum()\n"
        "    return fn, [paddle.static.InputSpec([4], 'float32')]\n"
    )
    cli = _cli()
    rc = cli.main([str(bad)])
    out = capsys.readouterr().out
    assert rc == 1
    assert "unguarded log" in out

    # --fail-on warning catches warning-severity findings too
    warn = tmp_path / "warn_model.py"
    warn.write_text(
        "import paddle_tpu as paddle\n"
        "def build_model():\n"
        "    fn = lambda x: x * 1.0\n"
        "    return fn, [paddle.static.InputSpec([4], 'float32')]\n"
    )
    assert cli.main([str(warn)]) == 0
    assert cli.main([str(warn), "--fail-on", "warning"]) == 1


def test_lint_json_output_is_structured(tmp_path, capsys):
    mod = tmp_path / "json_model.py"
    mod.write_text(
        "import paddle_tpu as paddle\n"
        "def build_model():\n"
        "    fn = lambda x: paddle.log(x).sum()\n"
        "    return fn, [paddle.static.InputSpec([4], 'float32')]\n"
    )
    rc = _cli().main([str(mod), "--json"])
    lines = [l for l in capsys.readouterr().out.splitlines() if l.strip()]
    assert rc == 1
    recs = [json.loads(l) for l in lines]
    assert any(
        r["severity"] == "error" and r["pass"] == "numeric_hazards"
        for r in recs
    )
    assert all({"severity", "pass", "op", "message", "hint"} <= set(r)
               for r in recs)


def test_lint_input_spec_override_and_pass_subset(tmp_path, capsys):
    mod = tmp_path / "spec_model.py"
    mod.write_text(
        "import paddle_tpu as paddle\n"
        "def build_model():\n"
        "    return lambda x: paddle.log(x).sum()\n"  # no specs returned
    )
    cli = _cli()
    rc = cli.main([str(mod), "--input-spec", "2,3:float32",
                   "--passes", "dead_code"])
    assert rc == 0  # hazard pass not selected
    rc = cli.main([str(mod), "--input-spec", "2,3:float32"])
    assert rc == 1


@pytest.mark.parametrize("mesh,builder", [
    ("dp=2,mp=2", "build_model"),
    ("pp=2", "build_model_pp"),
])
def test_multichip_dryrun_mesh_lint_error_clean(mesh, builder,
                                                check_programs_on, capsys):
    """The multichip CI gate from ISSUE 17: per-shard linting of the
    hybrid-parallel dryrun GPT builders (GSPMD sharded step and the GPipe
    pipelined step) must be error-clean under FLAGS_check_programs=1.
    Runs the CLI in-process — the 8 simulated devices from conftest
    already cover every mesh here, so no subprocess spawn is needed."""
    rc = _cli().main([os.path.join(REPO, "examples", "multichip_dryrun.py"),
                      "--mesh", mesh, "--builder", builder])
    out = capsys.readouterr().out
    assert rc == 0, f"error-severity diagnostics under --mesh {mesh}:\n{out}"
    assert "error[" not in out
    # the per-shard passes actually ran: collective cost + per-device memory
    assert "collective_cost" in out
    assert "FLAGS_check_programs=1" in out


def test_registry_exposes_the_new_passes():
    from paddle_tpu import analysis as A

    names = A.pass_names()
    for p in ("determinism", "collective_schedule", "equivalence"):
        assert p in names, names


def _write_diff_builders(tmp_path):
    a = tmp_path / "model_a.py"
    a.write_text(
        "import paddle_tpu as paddle\n"
        "def build_model():\n"
        "    fn = lambda x: (x * 2.0 + 1.0).sum()\n"
        "    return fn, [paddle.static.InputSpec([4], 'float32')]\n"
    )
    b = tmp_path / "model_b.py"
    # only a renamed builder on purpose: exercises --builder-b resolution
    b.write_text(
        "import paddle_tpu as paddle\n"
        "def build_model_v2():\n"
        "    fn = lambda x: (1.0 + 2.0 * x).sum()\n"  # commuted: equivalent
        "    return fn, [paddle.static.InputSpec([4], 'float32')]\n"
    )
    c = tmp_path / "model_c.py"
    c.write_text(
        "import paddle_tpu as paddle\n"
        "def build_model():\n"
        "    fn = lambda x: (x * 3.0 + 1.0).sum()\n"  # rescaled: divergent
        "    return fn, [paddle.static.InputSpec([4], 'float32')]\n"
    )
    return a, b, c


def test_diff_mode_certifies_equivalent_builders(tmp_path, capsys):
    a, b, _c = _write_diff_builders(tmp_path)
    rc = _cli().main([str(a), "--diff", str(b), "--builder-b",
                      "build_model_v2"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "EQUIVALENT" in out


def test_diff_mode_flags_divergent_builders(tmp_path, capsys):
    a, _b, c = _write_diff_builders(tmp_path)
    cli = _cli()
    rc = cli.main([str(a), "--diff", str(c)])
    out = capsys.readouterr().out
    assert rc == 1, out
    assert "DIVERGENT" in out

    # --json carries the certificate + structural diff lines
    rc = cli.main([str(a), "--diff", str(c), "--json"])
    lines = [l for l in capsys.readouterr().out.splitlines() if l.strip()]
    assert rc == 1
    recs = [json.loads(l) for l in lines]
    diff_recs = [r for r in recs if r["pass"] == "equivalence"]
    assert diff_recs, recs
    data = diff_recs[0]["data"]
    assert data["certificate"]["equivalent"] is False
    assert data["diff"]


def test_mesh_lint_json_carries_collective_records(capsys):
    rc = _cli().main([os.path.join(REPO, "examples", "multichip_dryrun.py"),
                      "--mesh", "dp=2,mp=2", "--json"])
    lines = [l for l in capsys.readouterr().out.splitlines() if l.strip()]
    assert rc == 0
    recs = [json.loads(l) for l in lines]
    cost = [r for r in recs if r["pass"] == "collective_cost"]
    assert cost and cost[0]["data"]["comm_bytes"] > 0
    assert all({"kind", "axes", "wire_bytes"} <= set(c)
               for c in cost[0]["data"]["collectives"])
    mem = [r for r in recs if r["pass"] == "memory_budget"]
    assert mem and "per device" in mem[0]["message"]
