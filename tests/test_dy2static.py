"""Dy2static data-dependent control flow (VERDICT r2 item 4).

The AST pipeline (jit/dy2static.py, reference: dygraph_to_static/
loop_transformer.py:486 + ifelse_transformer.py) must convert Python
if/while/for-range over traced tensors into lax.cond/while_loop inside the
ONE compiled to_static program, with eager/static parity and working grads.
"""
import numpy as np
import pytest

import paddle_tpu as paddle


def _t(v, sg=True):
    return paddle.to_tensor(np.asarray(v, np.float32), stop_gradient=sg)


def test_data_dependent_if_both_paths():
    trace_count = {"n": 0}

    def f(x):
        trace_count["n"] += 1
        if x.sum() > 0:
            y = x * 2
        else:
            y = x - 1
        return y

    sf = paddle.jit.to_static(f)
    a, b = _t([1.0, 2.0]), _t([-5.0, 1.0])
    for _ in range(3):
        ra, rb = sf(a), sf(b)
    assert np.allclose(np.asarray(ra._value), [2, 4])
    assert np.allclose(np.asarray(rb._value), [-6, 0])
    # ONE trace serves both branch outcomes: the branch is lax.cond inside
    # the compiled program, not a retrace per predicate value
    assert trace_count["n"] == 1


def test_if_gradients_flow_through_cond():
    def f(x):
        if x.sum() > 0:
            y = x * 3
        else:
            y = x * 5
        return y.sum()

    sf = paddle.jit.to_static(f)
    for sign, expect in ((1.0, 3.0), (-1.0, 5.0)):
        w = _t([sign, sign], sg=False)
        sf(w).backward()
        assert np.allclose(np.asarray(w.grad._value), expect)


def test_data_dependent_while_variable_steps():
    def decode(x):
        s = x.sum() * 0
        n = x.sum() * 0
        while s < 10:
            s = s + x.sum()
            n = n + 1
        return s, n

    sf = paddle.jit.to_static(decode)
    # eager-vs-static parity across inputs needing DIFFERENT step counts
    for val, steps in ((3.0, 4), (1.5, 4), (0.5, 10)):
        x = _t([val, val])
        s, n = sf(x)
        es, en = decode(_t([val, val]))
        assert float(n) == float(en)
        assert abs(float(s) - float(es)) < 1e-5


def test_for_over_traced_range():
    def f(x, n):
        acc = x * 0
        for _i in range(n):
            acc = acc + x
        return acc

    sf = paddle.jit.to_static(f)
    x = _t([2.0])
    for n in (3, 7):
        out = sf(x, paddle.to_tensor(np.int32(n)))
        assert float(out._value[0]) == 2.0 * n


def test_bool_ops_on_tensors():
    def f(x):
        big = x.max() > 100
        ok = (x.sum() > 0) and (not big)
        if ok:
            y = x + 1
        else:
            y = x - 1
        return y

    sf = paddle.jit.to_static(f)
    assert np.allclose(np.asarray(sf(_t([1.0, 2.0]))._value), [2, 3])
    assert np.allclose(np.asarray(sf(_t([-1.0, -2.0]))._value), [-2, -3])
    assert np.allclose(np.asarray(sf(_t([1.0, 200.0]))._value), [0, 199])


def test_nested_if_in_while():
    def f(x):
        s = x.sum() * 0
        i = x.sum() * 0
        while i < 5:
            if s > 4:
                s = s + 1
            else:
                s = s + 2
            i = i + 1
        return s

    sf = paddle.jit.to_static(f)
    out = sf(_t([0.0]))
    # eager reference
    exp = f(_t([0.0]))
    assert float(out) == float(exp)


def test_to_static_compiles_once():
    """The compiled wrapper must trace once per config and replay the XLA
    program afterwards (regression: a closure-defeated jit cache re-ran
    the Python body every call)."""
    runs = {"n": 0}

    def f(x):
        runs["n"] += 1
        return x * 2 + 1

    sf = paddle.jit.to_static(f)
    x = _t([1.0, 2.0])
    for _ in range(6):
        out = sf(x)
    assert runs["n"] == 1, f"python body ran {runs['n']} times — not compiled"
    assert np.allclose(np.asarray(out._value), [3, 5])


def test_loop_body_temporaries_not_carried():
    """Temps written-before-read in a traced while body (h = f(x)) need no
    pre-loop init — the droppable-mask analysis keeps them out of the lax
    carry (the greedy-decode pattern)."""

    def decode(tok, max_len):
        steps = tok.sum() * 0
        cur = tok
        while steps < max_len:
            h = cur * 2.0        # body-local temp
            probe = h + 1.0      # another temp
            cur = probe - h      # = ones
            steps = steps + 1
        return cur, steps

    sf = paddle.jit.to_static(decode)
    out, n = sf(_t([5.0]), paddle.to_tensor(np.float32(4)))
    eo, en = decode(_t([5.0]), paddle.to_tensor(np.float32(4)))
    assert float(n) == float(en) == 4.0
    assert np.allclose(np.asarray(out._value), np.asarray(eo._value))


def test_branch_only_temp_errors_clearly():
    def f(x):
        if x.sum() > 0:
            tmp = x * 2
            y = tmp + 1
        else:
            y = x - 1
        return y

    sf = paddle.jit.to_static(f)
    with pytest.raises(Exception) as ei:
        sf(_t([1.0]))
    assert "branch" in str(ei.value) or "pytree" in str(ei.value).lower() or \
        "structure" in str(ei.value).lower()


def test_plain_python_conditions_unchanged():
    """Non-tensor conditions keep exact Python semantics after conversion."""

    def f(x, mode):
        if mode == "double":
            y = x * 2
        else:
            y = x + 10
        k = 0
        while k < 3:
            y = y + 1
            k += 1
        return y

    sf = paddle.jit.to_static(f)
    assert np.allclose(np.asarray(sf(_t([1.0]), "double")._value), [5.0])
    assert np.allclose(np.asarray(sf(_t([1.0]), "add")._value), [14.0])
