"""Shape-bucketing policy (VERDICT r4 missing #4 / SURVEY §7 hard part 3).

Reference capability replaced: LoDTensor ragged batches
(paddle/fluid/framework/lod_tensor.h) — here a padding policy bounds the
number of distinct compiled shapes instead."""
import warnings

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.io import BucketSpec, DataLoader, Dataset


def tonp(x):
    return x.numpy() if hasattr(x, "numpy") else np.asarray(x)


class RaggedText(Dataset):
    """NLP-style ragged dataset: token id sequences of length 5..120."""

    def __init__(self, n=64, seed=0):
        rng = np.random.default_rng(seed)
        self.seqs = [
            rng.integers(1, 1000, rng.integers(5, 121)).astype(np.int64)
            for _ in range(n)
        ]

    def __len__(self):
        return len(self.seqs)

    def __getitem__(self, i):
        return self.seqs[i], np.int64(len(self.seqs[i]))


def test_bucket_for_boundaries():
    spec = BucketSpec([32, 64, 128])
    assert spec.bucket_for(1) == 32
    assert spec.bucket_for(32) == 32
    assert spec.bucket_for(33) == 64
    assert spec.bucket_for(128) == 128
    # beyond the table: multiples of the top boundary, still bounded
    assert spec.bucket_for(129) == 256
    assert spec.bucket_for(300) == 384
    with pytest.raises(ValueError):
        BucketSpec([64, 32])


def test_ragged_loader_bounds_compiled_shapes():
    spec = BucketSpec([32, 64, 128], axis=-1, pad_value=0, fields=[0])
    loader = DataLoader(RaggedText(), batch_size=8, bucket_spec=spec,
                        drop_last=True, return_numpy=True)
    lengths = set()
    naive_lengths = set()
    for ids, lens in loader:
        ids, lens = tonp(ids), tonp(lens)
        assert ids.shape[0] == 8
        lengths.add(ids.shape[1])
        naive_lengths.add(int(np.max(lens)))
        # padding is zeros past each row's real length
        for row, n in zip(ids, lens):
            assert np.all(row[int(n):] == 0)
            assert np.all(row[:int(n)] != 0)
    # the policy's point: ≤3 padded widths where naive batch-max padding
    # would produce ~one shape per batch
    assert lengths <= {32, 64, 128}
    assert len(lengths) <= 3
    assert len(naive_lengths) > 2 * len(lengths)


def test_compile_count_bounded_vs_naive():
    import jax
    import jax.numpy as jnp

    traces = []

    @jax.jit
    def consume(ids):
        traces.append(ids.shape)  # runs once per distinct shape (trace)
        return jnp.sum(ids)

    spec = BucketSpec([32, 64, 128], fields=[0])
    loader = DataLoader(RaggedText(), batch_size=8, bucket_spec=spec,
                        drop_last=True, return_numpy=True)
    for ids, _ in loader:
        consume(tonp(ids))
    bucketed_traces = len(traces)

    traces.clear()
    naive = DataLoader(RaggedText(), batch_size=8, drop_last=True,
                       return_numpy=True,
                       collate_fn=lambda s: (
                           np.stack([
                               np.pad(a, (0, max(len(x) for x, _ in s) - len(a)))
                               for a, _ in s
                           ]),
                           np.asarray([n for _, n in s]),
                       ))
    for ids, _ in naive:
        consume(tonp(ids))
    naive_traces = len(traces)
    assert bucketed_traces <= 3
    assert naive_traces >= 3 * bucketed_traces  # ~one compile per batch


def test_recompile_budget_warns():
    spec = BucketSpec([8], max_shapes=2)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        for ln in (4, 12, 20, 28):  # buckets 8, 16, 24, 32
            spec.apply(np.zeros((2, ln)))
    msgs = [str(x.message) for x in w if "recompile budget" in str(x.message)]
    assert len(msgs) == 2  # 3rd and 4th distinct shapes
    assert len(spec.seen_shapes) == 4


def test_pad_batch_to_fixes_last_batch():
    spec = BucketSpec([16], pad_batch_to=8)
    # 20 samples / batch 8 -> last batch has 4 rows; policy pads it to 8
    class Fixed(Dataset):
        def __len__(self):
            return 20

        def __getitem__(self, i):
            return np.full((10,), i + 1, np.int64)

    loader = DataLoader(Fixed(), batch_size=8, bucket_spec=spec,
                        return_numpy=True)
    batches = list(loader)
    assert all(tuple(b.shape) == (8, 16) for b in batches)
    last = batches[-1]
    assert spec.real_batch_size(last) == 4
    assert spec.real_batch_size(batches[0]) is None  # full batch untouched
    # the padding repeats the final real row
    lastnp = tonp(last)
    np.testing.assert_array_equal(
        lastnp[4:], np.broadcast_to(lastnp[3], (4, 16)))


def test_bucketed_collate_multiprocess_workers():
    spec = BucketSpec([32, 64, 128], fields=[0])
    loader = DataLoader(RaggedText(), batch_size=8, num_workers=2,
                        bucket_spec=spec, drop_last=True, return_numpy=True)
    widths = set()
    count = 0
    for ids, lens in loader:
        ids, lens = tonp(ids), tonp(lens)
        widths.add(ids.shape[1])
        count += 1
        for row, n in zip(ids, lens):
            assert np.all(row[int(n):] == 0)
    assert count == 8 and widths <= {32, 64, 128}


def test_apply_on_collated_dict():
    spec = BucketSpec([8, 16])
    out = spec.apply({"ids": np.ones((2, 5)), "mask": np.ones((2, 13))})
    assert out["ids"].shape == (2, 8) and out["mask"].shape == (2, 16)


def test_scalar_label_fields_pass_through_by_default():
    # review r5: default fields=None must skip 0-d label fields
    spec = BucketSpec([8, 16])

    class WithLabels(Dataset):
        def __len__(self):
            return 12

        def __getitem__(self, i):
            return np.arange(3 + i % 5, dtype=np.int64), np.int64(i % 3)

    loader = DataLoader(WithLabels(), batch_size=4, bucket_spec=spec)
    for ids, labels in loader:
        assert tuple(ids.shape)[1] == 8
        assert tuple(labels.shape) == (4,)
    # dict apply: scalars untouched
    out = spec.apply({"ids": np.ones((2, 5)), "n": 7})
    assert out["ids"].shape == (2, 8) and out["n"] == 7


def test_pad_batch_to_rejected_with_process_workers():
    spec = BucketSpec([8], pad_batch_to=4)

    class D(Dataset):
        def __len__(self):
            return 8

        def __getitem__(self, i):
            return np.arange(4, dtype=np.int64)

    with pytest.raises(ValueError, match="pad_batch_to"):
        DataLoader(D(), batch_size=4, num_workers=2, bucket_spec=spec)
    DataLoader(D(), batch_size=4, num_workers=2, use_thread_workers=True,
               bucket_spec=spec)  # threads share the spec: allowed


def test_mp_workers_parent_observes_shapes():
    spec = BucketSpec([32, 64, 128], fields=[0])
    loader = DataLoader(RaggedText(n=32), batch_size=8, num_workers=2,
                        bucket_spec=spec, drop_last=True, return_numpy=True)
    for _ in loader:
        pass
    assert spec.seen_shapes  # parent-side tracking survives the fork
