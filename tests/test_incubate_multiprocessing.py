"""Tensor sharing across processes (incubate.multiprocessing reducers).

Reference analogue: test_paddle_multiprocessing.py — queue round-trip of
tensors between real processes over shared memory.
"""
import multiprocessing as mp

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.incubate.multiprocessing as pmp  # registers reducers


def _child(q_in, q_out):
    import jax

    jax.config.update("jax_platforms", "cpu")
    t = q_in.get(timeout=60)
    q_out.put(float(t.sum()))


@pytest.mark.slow
def test_tensor_queue_roundtrip():
    ctx = mp.get_context("spawn")
    q_in, q_out = ctx.Queue(), ctx.Queue()
    p = ctx.Process(target=_child, args=(q_in, q_out))
    p.start()
    try:
        q_in.put(paddle.to_tensor(np.arange(10, dtype=np.float32)))
        assert q_out.get(timeout=120) == 45.0
    finally:
        p.join(30)
        if p.is_alive():
            p.terminate()


def test_strategy_api():
    import pytest

    assert pmp.get_sharing_strategy() == "file_system"
    with pytest.raises(NotImplementedError):
        pmp.set_sharing_strategy("file_descriptor")
    pmp.set_sharing_strategy("file_system")


def test_unconsumed_payload_cleanup():
    import multiprocessing.reduction as red

    t = paddle.to_tensor(np.ones((8,), np.float32))
    red.ForkingPickler.dumps(t)  # pickled, never consumed
    assert pmp._pending_segments
    pmp._cleanup_pending()
    assert not pmp._pending_segments
