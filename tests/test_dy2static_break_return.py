"""Dy2static break/continue/early-return conversion (VERDICT r3 task 7).

Reference analogues: dygraph_to_static/break_continue_transformer.py:87
(loop-flag fusion) and return_transformer.py:136 (return guard
accumulation). Each test checks traced-predicate parity against the plain
eager execution of the SAME function body.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.jit import to_static


def _eager_vs_static(fn, *args):
    """Run the raw python fn and its to_static conversion; both must agree."""
    eager = fn(*[paddle.to_tensor(a) for a in args])
    static = to_static(fn)(*[paddle.to_tensor(a) for a in args])
    np.testing.assert_allclose(
        np.asarray(eager.numpy() if hasattr(eager, "numpy") else eager),
        np.asarray(static.numpy() if hasattr(static, "numpy") else static),
        rtol=1e-6,
    )
    return static


# -- break ---------------------------------------------------------------------
def test_break_in_traced_while():
    def fn(x):
        s = paddle.zeros([])
        i = paddle.zeros([], dtype="int32")
        while i < 100:  # traced bound
            if s > 10.0:
                break
            s = s + x
            i = i + 1
        return s + i.astype("float32")

    _eager_vs_static(fn, np.float32(3.0))


def test_break_compiles_to_one_program():
    # the traced while with break must become ONE lax.while_loop, not an
    # unrolled TracerBoolConversionError path
    import jax

    def fn(x):
        s = paddle.zeros([])
        i = paddle.zeros([], dtype="int32")
        while i < 50:
            if s > x * 4.0:
                break
            s = s + x
            i = i + 1
        return s

    conv = to_static(fn)
    out = jax.jit(lambda v: conv(paddle.to_tensor(v))._value)(2.0)
    assert float(out) > 8.0


def test_break_in_concrete_while_keeps_python_semantics():
    def fn(x):
        s = paddle.zeros([])
        n = 0
        while n < 10:  # concrete
            if n == 3:
                break
            s = s + x
            n = n + 1
        return s + n

    _eager_vs_static(fn, np.float32(1.0))


def test_break_in_for_range():
    def fn(x):
        s = paddle.zeros([])
        for i in range(8):
            if s > 4.0:
                break
            s = s + x
        return s + i  # python: i keeps its break-iteration value

    _eager_vs_static(fn, np.float32(2.0))


def test_break_in_traced_for_range():
    def fn(x, n):
        s = paddle.zeros([])
        for i in range(n):  # traced bound
            if s > 5.0:
                break
            s = s + x
        return s

    eager = fn(paddle.to_tensor(np.float32(2.0)), 100)
    static = to_static(fn)(
        paddle.to_tensor(np.float32(2.0)),
        paddle.to_tensor(np.int32(100)),
    )
    np.testing.assert_allclose(eager.numpy(), static.numpy(), rtol=1e-6)


# -- continue ------------------------------------------------------------------
def test_continue_in_while():
    def fn(x):
        s = paddle.zeros([])
        i = paddle.zeros([], dtype="int32")
        while i < 10:
            i = i + 1
            if i.astype("float32") % 2.0 < 0.5:
                continue
            s = s + x  # odd iterations only
        return s

    _eager_vs_static(fn, np.float32(1.0))


def test_continue_in_for_range():
    def fn(x):
        s = paddle.zeros([])
        for i in range(6):
            if i % 2 == 0:
                continue
            s = s + x * i
        return s

    _eager_vs_static(fn, np.float32(1.0))


def test_break_and_continue_together():
    def fn(x):
        s = paddle.zeros([])
        i = paddle.zeros([], dtype="int32")
        while i < 20:
            i = i + 1
            if (i % 3) == 0:
                continue
            if s > 7.0:
                break
            s = s + x
        return s + i.astype("float32")

    _eager_vs_static(fn, np.float32(1.0))


# -- early return --------------------------------------------------------------
def test_early_return_traced_if():
    def fn(x):
        if x > 0:
            return x * 2.0
        return x - 1.0

    _eager_vs_static(fn, np.float32(3.0))
    _eager_vs_static(fn, np.float32(-3.0))


def test_early_return_traced_if_compiles():
    import jax

    def fn(x):
        if x > 0:
            return x * 2.0
        return x - 1.0

    conv = to_static(fn)
    jfn = jax.jit(lambda v: conv(paddle.to_tensor(v))._value)
    np.testing.assert_allclose(float(jfn(3.0)), 6.0)
    np.testing.assert_allclose(float(jfn(-3.0)), -4.0)  # same compiled fn


def test_early_return_with_trailing_statements():
    def fn(x):
        y = x + 1.0
        if y > 2.0:
            return y * 10.0
        z = y * 2.0
        return z + x

    _eager_vs_static(fn, np.float32(5.0))
    _eager_vs_static(fn, np.float32(0.0))


def test_nested_early_returns():
    def fn(x):
        if x > 10.0:
            if x > 20.0:
                return x * 3.0
            return x * 2.0
        return x

    for v in (25.0, 15.0, 5.0):
        _eager_vs_static(fn, np.float32(v))


def test_early_return_none_path():
    # a CONCRETE predicate keeps exact python semantics incl. returning None
    def fn(x, flag):
        if flag:
            return None
        return x + 1.0

    out = to_static(fn)(paddle.to_tensor(np.float32(1.0)), False)
    np.testing.assert_allclose(float(out), 2.0)

    # a TRACED predicate cannot merge None with an array — readable error
    def fn2(x):
        if x > 100.0:
            return None
        return x + 1.0

    with pytest.raises(ValueError, match="same variables"):
        to_static(fn2)(paddle.to_tensor(np.float32(1.0)))


def test_return_in_loop_keeps_python_semantics():
    # documented subset: return inside a loop body stays python-only (the
    # loop and its predicate must be concrete)
    def fn(x):
        s = paddle.zeros([])
        for i in range(5):  # concrete loop: plain python
            s = s + x
            if i >= 2:  # concrete predicate
                return s
        return s - 1.0

    out = to_static(fn)(paddle.to_tensor(np.float32(1.0)))
    np.testing.assert_allclose(float(out), 3.0)


# -- interaction with the UNDEF machinery -------------------------------------
def test_break_with_branch_bound_temp():
    def fn(x, flag):
        s = paddle.zeros([])
        i = paddle.zeros([], dtype="int32")
        while i < 5:
            if flag:  # concrete False
                dbg = x * 0.0
                s = s + dbg
            if s > 100.0:
                break
            s = s + x
            i = i + 1
        return s

    out = to_static(fn)(paddle.to_tensor(np.float32(1.0)), False)
    np.testing.assert_allclose(float(out), 5.0)


# -- review regressions (r4) ---------------------------------------------------
def test_nested_loops_with_independent_breaks():
    # inner break must not leak into the outer loop's flag/induction state
    def fn(x):
        total = paddle.zeros([])
        for i in range(5):
            for j in range(4):
                if j >= 2:
                    break
                total = total + x
        return total  # 5 outer x 2 inner = 10

    _eager_vs_static(fn, np.float32(1.0))


def test_nested_while_breaks_traced():
    def fn(x):
        total = paddle.zeros([])
        i = paddle.zeros([], dtype="int32")
        while i < 4:
            k = paddle.zeros([], dtype="int32")
            while k < 10:
                if k >= 2:
                    break
                total = total + x
                k = k + 1
            i = i + 1
        return total  # 4 x 2 = 8

    _eager_vs_static(fn, np.float32(1.0))


def test_loop_var_survives_traced_for_break():
    def fn(x, n):
        s = paddle.zeros([])
        for i in range(n):
            if s > 5.0:
                break
            s = s + x
        return s + i  # python: i keeps the break-iteration index

    eager = fn(paddle.to_tensor(np.float32(2.0)), 100)
    static = to_static(fn)(
        paddle.to_tensor(np.float32(2.0)), paddle.to_tensor(np.int32(100))
    )
    np.testing.assert_allclose(eager.numpy(), static.numpy(), rtol=1e-6)


def test_temp_first_assigned_after_break_guard():
    # dbg is born after the potential break — the remainder guard must not
    # reject it for being unbound on the (empty) else path
    def fn(x):
        s = paddle.zeros([])
        i = paddle.zeros([], dtype="int32")
        while i < 100:
            if s > 10.0:
                break
            dbg = x * 2.0
            s = s + dbg
            i = i + 1
        return s

    _eager_vs_static(fn, np.float32(1.0))


def test_absorbed_tail_reassigns_outer_variable():
    # the absorbed `x = x + 1` must still see the outer x (concrete pred)
    def fn(x, c):
        if c:
            return x
        x = x + 1.0
        return x

    out = to_static(fn)(paddle.to_tensor(np.float32(3.0)), False)
    np.testing.assert_allclose(float(out), 4.0)
    out2 = to_static(fn)(paddle.to_tensor(np.float32(3.0)), True)
    np.testing.assert_allclose(float(out2), 3.0)
    # traced predicate too: x is bound at entry, so both branches merge
    def fn2(x):
        if x > 10.0:
            return x
        x = x + 1.0
        return x

    _eager_vs_static(fn2, np.float32(3.0))
    _eager_vs_static(fn2, np.float32(30.0))


def test_temp_computed_in_loop_read_after_loop():
    # u is born inside the traced loop and read after it — the carry
    # type-probe keeps it bound like python
    def fn(x):
        i = paddle.zeros([], dtype="int32")
        u = None
        while i < 5:
            if x.sum() + i.astype("float32") > 100.0:
                break
            u = x + i.astype("float32")
            i = i + 1
        return u

    del fn  # the None pre-bind variant is the easy case; test the raw one

    def fn2(x):
        i = paddle.zeros([], dtype="int32")
        while i < 5:
            if x.sum() + i.astype("float32") > 100.0:
                break
            u = x + i.astype("float32")
            i = i + 1
        return u

    eager = fn2(paddle.to_tensor(np.array([1.0, 2.0], np.float32)))
    static = to_static(fn2)(paddle.to_tensor(np.array([1.0, 2.0], np.float32)))
    np.testing.assert_allclose(eager.numpy(), static.numpy(), rtol=1e-6)


def test_shrink_on_non_ctr_table_is_noop():
    from paddle_tpu.distributed.ps import MemorySparseTable

    t = MemorySparseTable(emb_dim=4)
    t.pull(np.arange(100, dtype=np.int64))
    assert len(t) == 100
    assert t.shrink() == 0
    assert len(t) == 100


# -- transitive conversion (reference: convert_call) ---------------------------
def _helper_with_traced_while(x):
    s = paddle.zeros([])
    i = paddle.zeros([], dtype="int32")
    while i < 4:  # traced -> must convert even though only CALLED
        s = s + x
        i = i + 1
    return s


def test_convert_call_converts_user_helpers():
    def fn(x):
        return _helper_with_traced_while(x) * 2.0

    out = to_static(fn)(paddle.to_tensor(np.float32(1.5)))
    np.testing.assert_allclose(float(out), 12.0, rtol=1e-6)


def test_convert_call_skips_framework_and_builtins():
    def fn(x):
        ys = [x + float(i) for i in range(3)]  # builtins untouched
        return paddle.stack(ys).sum()          # framework untouched

    out = to_static(fn)(paddle.to_tensor(np.float32(1.0)))
    np.testing.assert_allclose(float(out), 6.0, rtol=1e-6)


def test_convert_call_recursive_helper():
    def fact_like(x, n):
        if n <= 0:  # concrete
            return x
        return fact_like(x + 1.0, n - 1)

    def fn(x):
        return fact_like(x, 3)

    out = to_static(fn)(paddle.to_tensor(np.float32(0.0)))
    np.testing.assert_allclose(float(out), 3.0)
